"""Diagnosis rule engine: from an incident bundle to a verdict.

Each rule inspects the bundle (``diagnosis/collector.py``) and emits a
Finding — a category, a blamed task, a confidence, and the EVIDENCE
LINES that fired it (an operator must be able to check the engine's
work; an unexplained verdict is worse than none). The engine runs every
rule, keeps all findings, and picks the verdict by category precedence:
explicit control-plane verdicts (hang events, recovery records,
backend-attributed preemption) outrank log-pattern heuristics, which
outrank the UNKNOWN fallback.

Rules declare the event types they consume (``events_used``) so a
tier-1 smoke test can assert every referenced type still exists in
``events.EventType`` — rules must not silently rot as events evolve.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from tony_tpu.diagnosis.collector import IncidentBundle, TaskIncident
from tony_tpu.diagnosis.exitcodes import describe_exit, exit_signal

# -- categories ------------------------------------------------------------
USER_TRACEBACK = "USER_TRACEBACK"
OOM_RSS = "OOM_RSS"
OOM_HBM = "OOM_HBM"
HANG = "HANG"
STRAGGLER_CASCADE = "STRAGGLER_CASCADE"
PREEMPTION = "PREEMPTION"
INFRA_STORM = "INFRA_STORM"
COORDINATOR_LOSS = "COORDINATOR_LOSS"
PORT_RENDEZVOUS = "PORT_RENDEZVOUS"
GANG_RESIZE = "GANG_RESIZE"
SLO_BREACH = "SLO_BREACH"
UNKNOWN = "UNKNOWN"

#: verdict precedence, most specific first: explicit verdicts the
#: control plane already made, then backend attribution, then log-shape
#: heuristics, then the fallback. SLO_BREACH sits just above UNKNOWN:
#: "an alert was firing" is real evidence but every structural verdict
#: explains MORE — the alert instead boosts whichever structural
#: finding it corroborates (see ``_ALERT_CATEGORY`` / ``run_rules``).
CATEGORY_PRECEDENCE = (
    COORDINATOR_LOSS, GANG_RESIZE, HANG, STRAGGLER_CASCADE, PREEMPTION,
    OOM_HBM, OOM_RSS, PORT_RENDEZVOUS, INFRA_STORM, USER_TRACEBACK,
    SLO_BREACH, UNKNOWN)


@dataclasses.dataclass
class Finding:
    category: str
    rule: str
    summary: str
    blamed_task: str = ""
    confidence: float = 0.5
    evidence: List[str] = dataclasses.field(default_factory=list)
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    category: str
    #: EventType NAMES this rule reads from the event stream — checked
    #: against events.EventType by the parity smoke test.
    events_used: Tuple[str, ...]
    fn: Callable[[IncidentBundle], Optional[Finding]]


RULES: List[Rule] = []


def _rule(name: str, category: str, events_used: Tuple[str, ...] = ()):
    def deco(fn):
        RULES.append(Rule(name, category, events_used, fn))
        return fn
    return deco


def _blame(bundle: IncidentBundle,
           task: Optional[TaskIncident] = None) -> str:
    t = task or bundle.first_failed_task()
    return t.task_id if t else ""


# -- rules -----------------------------------------------------------------
@_rule("coordinator-loss", COORDINATOR_LOSS,
       ("COORDINATOR_RECOVERED", "APPLICATION_FINISHED"))
def _coordinator_loss(b: IncidentBundle) -> Optional[Finding]:
    """The coordinator died and the job did not survive the recovery:
    the re-registration grace expired (the gang was lost with it), or
    the journal shows generation churn behind a failed recovery run."""
    recov = b.events_of("COORDINATOR_RECOVERED")
    grace = "re-registration grace" in (b.failure_reason or "")
    if not grace and not (recov and b.status in ("FAILED", "KILLED")):
        return None
    ev = []
    for e in recov:
        ev.append(f"events: COORDINATOR_RECOVERED generation="
                  f"{e.payload.get('generation')} awaiting="
                  f"{e.payload.get('awaiting_reregistration')}")
    if len(b.generations) > 1:
        ev.append(f"journal: {len(b.generations)} coordinator "
                  f"generation(s): {b.generations}")
    if grace:
        ev.append(f"failure_reason: {b.failure_reason}")
    if not grace and not any("re-registration" in x for x in ev):
        # Recovered AND failed, but not ON the recovery itself — let the
        # failure's own shape (hang, user crash...) take the verdict.
        return None
    return Finding(
        COORDINATOR_LOSS, "coordinator-loss",
        "the coordinator was lost mid-run and the surviving gang did not "
        "re-register within the recovery grace window",
        blamed_task=_blame(b), confidence=0.9 if grace else 0.6,
        evidence=ev)


@_rule("hang", HANG, ("TASK_HUNG", "TASK_FINISHED"))
def _hang(b: IncidentBundle) -> Optional[Finding]:
    """Progress-liveness verdict: heartbeats alive, step counter frozen.
    The control plane already diagnosed this live — surface its evidence
    (stall ages, the captured all-thread stack dump)."""
    hung_events = b.events_of("TASK_HUNG")
    if not hung_events:
        return None
    first = hung_events[0]
    tid = str(first.payload.get("task", ""))
    t = b.tasks.get(tid)
    ev = [f"events: TASK_HUNG {tid} steps={first.payload.get('steps')} "
          f"stalled_s={first.payload.get('stalled_s')} "
          f"timeout_s={first.payload.get('timeout_s')}"]
    details: Dict[str, Any] = {"stalled_s": first.payload.get("stalled_s"),
                               "steps": first.payload.get("steps")}
    if t is not None:
        if t.last_heartbeat_age_s is not None:
            ev.append(f"events: heartbeats were alive at the kill "
                      f"(age {t.last_heartbeat_age_s:.1f}s) — the "
                      f"executor survived; the user process wedged")
        if t.stack_dump:
            ev.append("stack dump captured (all-thread faulthandler "
                      "excerpt in blamed_task.stack_dump)")
            details["has_stack_dump"] = True
        if t.reason:
            ev.append(f"kill reason: {t.reason}")
    return Finding(
        HANG, "hang",
        f"task {tid} hung: heartbeats kept arriving while its step "
        f"counter stayed frozen past the progress deadline",
        blamed_task=tid or _blame(b), confidence=0.95,
        evidence=ev, details=details)


@_rule("straggler-cascade", STRAGGLER_CASCADE,
       ("TASK_STRAGGLER", "TASK_FINISHED"))
def _straggler(b: IncidentBundle) -> Optional[Finding]:
    strag = b.events_of("TASK_STRAGGLER")
    if not strag:
        return None
    by_task: Dict[str, dict] = {}
    for e in strag:
        by_task.setdefault(str(e.payload.get("task", "")), e.payload)
    first_tid = str(strag[0].payload.get("task", ""))
    ev = [f"events: TASK_STRAGGLER {tid} rate="
          f"{p.get('rate_steps_per_s')} median="
          f"{p.get('median_steps_per_s')}"
          for tid, p in by_task.items()]
    restarted = [tid for tid in by_task
                 if b.tasks.get(tid) and b.tasks[tid].failed]
    if restarted:
        ev.append(f"straggler(s) {restarted} killed/restarted by "
                  f"straggler policing")
    return Finding(
        STRAGGLER_CASCADE, "straggler-cascade",
        f"{len(by_task)} task(s) fell below the gang's median step rate "
        f"for the sustained window, dragging the whole gang",
        blamed_task=first_tid, confidence=0.85, evidence=ev,
        details={"stragglers": sorted(by_task)})


@_rule("elastic-resize", GANG_RESIZE, ("GANG_RESIZED", "TASK_FINISHED"))
def _elastic_resize(b: IncidentBundle) -> Optional[Finding]:
    """Distinguish "the gang shrank and continued" (deliberate
    elasticity — NOT the failure; other rules skip the absorbed task
    exits via their ``resized`` flag) from "the job died mid-resize"
    (drain/barrier never completed): only the latter takes the verdict,
    with the incomplete resize as the evidence."""
    resized = b.events_of("GANG_RESIZED")
    if not resized:
        return None
    started = [e for e in resized if e.payload.get("phase") == "started"]
    completed = [e for e in resized
                 if e.payload.get("phase") == "completed"]
    reason = (b.failure_reason or "").lower()
    mid_resize = "resize" in reason or len(completed) < len(started)
    if not mid_resize:
        # Every resize completed: absorbed losses are routine
        # elasticity. Let the real cause (if any) take the verdict.
        return None
    last = started[-1].payload if started else {}
    ev = [f"events: GANG_RESIZED started mgen={last.get('mgen')} "
          f"{last.get('from')}->{last.get('to')} "
          f"({last.get('reason')}) never completed"]
    if b.failure_reason:
        ev.append(f"failure_reason: {b.failure_reason}")
    absorbed = sorted(t.task_id for t in b.tasks.values() if t.resized)
    if absorbed:
        ev.append(f"absorbed member loss(es): {absorbed}")
    return Finding(
        GANG_RESIZE, "elastic-resize",
        "the job failed while an elastic resize was in flight — the "
        "drain or the post-remesh barrier never completed (the retry "
        "epoch relaunches at the configured size)",
        blamed_task=_blame(b), confidence=0.85, evidence=ev,
        details={"mgen": last.get("mgen"), "target": last.get("to")})


@_rule("preemption", PREEMPTION, ("TASK_FINISHED", "APPLICATION_FINISHED"))
def _preemption(b: IncidentBundle) -> Optional[Finding]:
    """Backend-attributed preemption (host reclaimed, spot notice, 143
    save-on-TERM exits) — authoritative when the domain says so. Losses
    a resize absorbed are deliberate elasticity, not this verdict."""
    preempted = [t for t in b.tasks.values()
                 if t.failed and t.failure_domain == "PREEMPTION"
                 and not t.resized]
    if not preempted and b.failure_domain != "PREEMPTION":
        return None
    blamed = min(preempted, key=lambda t: t.failure_us or t.finished_ms
                 * 1000 or float("inf")) if preempted else None
    ev = [f"events: TASK_FINISHED {t.task_id} "
          f"{t.exit_detail or describe_exit(t.exit_code)} "
          f"domain=PREEMPTION" for t in preempted[:5]]
    if b.failure_domain == "PREEMPTION":
        ev.append(f"failure_domain: PREEMPTION ({b.failure_reason})")
    return Finding(
        PREEMPTION, "preemption",
        "the backend attributed the failure to preemption — reclaimed "
        "capacity, not a bug; retries on a fresh lease usually clear it",
        blamed_task=blamed.task_id if blamed else _blame(b),
        confidence=0.9, evidence=ev)


#: allocator/oom phrases that mean DEVICE memory (XLA/jax HBM), matched
#: against tracebacks and log tails.
_HBM_RE = re.compile(
    r"RESOURCE_EXHAUSTED|out of memory while trying to allocate|"
    r"Failed to allocate request for .* of .* hbm|HBM OOM|"
    r"Allocator .* ran out of memory", re.IGNORECASE)
#: host-memory kill markers (the kernel OOM-killer reaps with SIGKILL and
#: says so in dmesg, not the task log — the log shows the victim's side).
_RSS_RE = re.compile(r"MemoryError|Cannot allocate memory|"
                     r"oom-?kill", re.IGNORECASE)


@_rule("oom-hbm", OOM_HBM, ("TASK_FINISHED",))
def _oom_hbm(b: IncidentBundle) -> Optional[Finding]:
    for t in sorted(b.tasks.values(),
                    key=lambda x: x.failure_us or x.finished_ms * 1000):
        if not t.failed:
            continue
        for text, where in ((t.traceback, "traceback"), *(
                (b.log_tails.get(p, ""), p) for p in t.logs)):
            m = _HBM_RE.search(text or "")
            if m:
                line = next((ln.strip() for ln in text.splitlines()
                             if m.group(0) in ln), m.group(0))
                return Finding(
                    OOM_HBM, "oom-hbm",
                    f"task {t.task_id} exhausted device memory (HBM) — "
                    f"shrink the per-device batch/model shard or widen "
                    f"the mesh",
                    blamed_task=t.task_id, confidence=0.9,
                    evidence=[f"{where}: {line[:200]}"])
    return None


@_rule("oom-rss", OOM_RSS, ("TASK_FINISHED",))
def _oom_rss(b: IncidentBundle) -> Optional[Finding]:
    """SIGKILL with no supervisor-stamped reason is the kernel
    OOM-killer's signature shape; explicit host-memory markers in the
    log raise the confidence."""
    for t in sorted(b.tasks.values(),
                    key=lambda x: x.failure_us or x.finished_ms * 1000):
        if not t.failed or t.hung or t.resized \
                or t.failure_domain == "PREEMPTION":
            continue
        texts = [(t.traceback, "traceback")] + \
            [(b.log_tails.get(p, ""), p) for p in t.logs]
        marker = next(((m.group(0), where) for text, where in texts
                       for m in [_RSS_RE.search(text or "")] if m), None)
        killed = exit_signal(t.exit_code) == 9 and not t.reason \
            and t.last_heartbeat_age_s is None
        if not marker and not killed:
            continue
        ev = []
        if killed:
            ev.append(f"events: TASK_FINISHED {t.task_id} "
                      f"{t.exit_detail or describe_exit(t.exit_code)} "
                      f"with no supervisor kill reason — the OOM-killer "
                      f"shape")
        if marker:
            ev.append(f"{marker[1]}: {marker[0]}")
        rss = t.metrics.get("MAX_MEMORY_BYTES") or \
            t.metrics.get("rss_bytes")
        if rss:
            ev.append(f"metrics: peak RSS {rss} bytes")
        return Finding(
            OOM_RSS, "oom-rss",
            f"task {t.task_id} was killed for host memory (RSS) — the "
            f"input pipeline / host-side buffers outgrew the VM",
            blamed_task=t.task_id,
            confidence=0.8 if marker else 0.5, evidence=ev)
    return None


@_rule("port-rendezvous", PORT_RENDEZVOUS,
       ("TASK_FINISHED", "APPLICATION_FINISHED"))
def _rendezvous(b: IncidentBundle) -> Optional[Finding]:
    reason = b.failure_reason or ""
    ev = []
    if "registration timeout" in reason:
        ev.append(f"failure_reason: {reason}")
    bind_re = re.compile(r"Address already in use|Failed to bind|"
                         r"EADDRINUSE|address in use", re.IGNORECASE)
    blamed = ""
    for t in b.tasks.values():
        for p in t.logs:
            m = bind_re.search(b.log_tails.get(p, ""))
            if m:
                ev.append(f"{p}: {m.group(0)}")
                blamed = blamed or t.task_id
    if not ev:
        return None
    return Finding(
        PORT_RENDEZVOUS, "port-rendezvous",
        "the gang never completed its rendezvous — a member could not "
        "register or bind its port",
        blamed_task=blamed or _blame(b),
        confidence=0.8 if len(ev) > 1 else 0.6, evidence=ev)


@_rule("executor-vanished", INFRA_STORM, ("TASK_FINISHED",))
def _vanished(b: IncidentBundle) -> Optional[Finding]:
    """Heartbeat-expiry kill: the EXECUTOR (not just the user process)
    went silent — host death, network partition, or a wedged VM."""
    gone = [t for t in b.tasks.values()
            if t.failed and not t.resized
            and t.last_heartbeat_age_s is not None
            and ("deemed dead" in t.reason
                 or t.last_heartbeat_age_s >= 1.0 and not t.hung
                 and not t.reason)]
    if not gone:
        return None
    blamed = min(gone, key=lambda t: t.failure_us or t.finished_ms * 1000
                 or float("inf"))
    ev = [f"events: TASK_FINISHED {t.task_id} after "
          f"{t.last_heartbeat_age_s:.1f}s of heartbeat silence "
          f"({t.reason or 'deemed dead'})" for t in gone[:5]]
    return Finding(
        INFRA_STORM, "executor-vanished",
        f"task {blamed.task_id}'s executor stopped heartbeating entirely "
        f"— host loss or network partition, not a user-code failure",
        blamed_task=blamed.task_id, confidence=0.8, evidence=ev,
        details={"vanished": sorted(t.task_id for t in gone)})


#: exception lines that mean the INFRASTRUCTURE failed under the user
#: process (transport resets, injected faults, rpc deadlines) — these
#: must not read as user bugs just because they arrived as a traceback.
_INFRA_EXC_RE = re.compile(
    r"^(.*\.)?(ConnectionError|ConnectionResetError|ConnectionRefusedError|"
    r"BrokenPipeError|TimeoutError|InjectedFault|RpcTimeout|RpcError|"
    r"OSError|socket\.gaierror|ssl\.SSLError)\b")


@_rule("infra-traceback", INFRA_STORM, ("TASK_FINISHED",))
def _infra_traceback(b: IncidentBundle) -> Optional[Finding]:
    hits = []
    for t in b.tasks.values():
        if not t.failed or not t.traceback:
            continue
        last = _final_exception_line(t.traceback)
        if last and _INFRA_EXC_RE.match(last):
            hits.append((t, last))
    if not hits:
        return None
    hits.sort(key=lambda x: x[0].failure_us or x[0].finished_ms * 1000)
    blamed, line = hits[0]
    ev = [f"traceback {t.task_id}: {ln[:200]}" for t, ln in hits[:5]]
    if b.verdicts:
        ev.append(f"journal: {len(b.verdicts)} epoch verdict(s): "
                  + ", ".join(str(v.get("domain")) for v in b.verdicts))
    return Finding(
        INFRA_STORM, "infra-traceback",
        f"{len(hits)} task(s) died on infrastructure-shaped exceptions "
        f"(transport/storage/timeout) — an infra storm, even where the "
        f"exit code was classified USER_ERROR",
        blamed_task=blamed.task_id, confidence=0.75, evidence=ev)


@_rule("retry-budget-exhausted", INFRA_STORM, ("APPLICATION_FINISHED",))
def _retry_exhausted(b: IncidentBundle) -> Optional[Finding]:
    infra = [v for v in b.verdicts
             if v.get("domain") == "INFRA_TRANSIENT"]
    if len(infra) < 2:
        return None
    reasons = [str(v.get("reason", ""))[:120] for v in infra]
    return Finding(
        INFRA_STORM, "retry-budget-exhausted",
        f"{len(infra)} consecutive epochs failed INFRA_TRANSIENT — "
        f"repeated transient failures exhausted the retry budget",
        blamed_task=_blame(b), confidence=0.7,
        evidence=[f"journal verdict epoch {v.get('session')}: "
                  f"{r}" for v, r in zip(infra, reasons)])


@_rule("user-traceback", USER_TRACEBACK, ("TASK_FINISHED",))
def _user_traceback(b: IncidentBundle) -> Optional[Finding]:
    candidates = []
    for t in b.tasks.values():
        if not t.failed or not t.traceback:
            continue
        last = _final_exception_line(t.traceback)
        if last and _INFRA_EXC_RE.match(last):
            continue            # infra-shaped: the storm rule owns it
        candidates.append((t, last or "?"))
    if not candidates:
        # Domain says user error but no traceback was captured: still a
        # user verdict, with the exit code as the only evidence.
        plain = [t for t in b.tasks.values()
                 if t.failed and t.failure_domain == "USER_ERROR"]
        if not plain:
            return None
        t = min(plain, key=lambda x: x.failure_us or x.finished_ms * 1000
                or float("inf"))
        return Finding(
            USER_TRACEBACK, "user-traceback",
            f"task {t.task_id} exited "
            f"{t.exit_detail or describe_exit(t.exit_code)} "
            f"(USER_ERROR) — no traceback captured in its log tail",
            blamed_task=t.task_id, confidence=0.5,
            evidence=[f"events: TASK_FINISHED {t.task_id} "
                      f"exit={t.exit_code} domain=USER_ERROR"])
    candidates.sort(key=lambda x: x[0].failure_us
                    or x[0].finished_ms * 1000)
    t, last = candidates[0]
    return Finding(
        USER_TRACEBACK, "user-traceback",
        f"task {t.task_id} crashed in user code: {last[:160]}",
        blamed_task=t.task_id, confidence=0.9,
        evidence=[f"traceback {t.task_id}: {last[:200]}",
                  f"events: TASK_FINISHED {t.task_id} exit={t.exit_code} "
                  f"domain={t.failure_domain or '?'}"],
        details={"exception": last})


def _alerts_still_firing(b: IncidentBundle) -> Dict[str, dict]:
    """Alert rules whose final journaled state in the event stream is
    firing: more ALERT_FIRING than ALERT_RESOLVED emissions (the state
    machine strictly alternates them per rule), payload of the last
    firing kept as the evidence."""
    fired: Dict[str, List[dict]] = {}
    for e in b.events_of("ALERT_FIRING"):
        fired.setdefault(str(e.payload.get("rule", "")),
                         []).append(e.payload)
    for e in b.events_of("ALERT_RESOLVED"):
        rule = str(e.payload.get("rule", ""))
        if fired.get(rule):
            fired[rule].pop(0)
    return {rule: payloads[-1]
            for rule, payloads in fired.items() if payloads}


@_rule("slo-breach", SLO_BREACH, ("ALERT_FIRING", "ALERT_RESOLVED"))
def _slo_breach(b: IncidentBundle) -> Optional[Finding]:
    """The alert engine saw the job breach an SLO before the terminal
    verdict and the alert never resolved. Structural rules outrank
    this; it carries the diagnosis alone only when nothing else
    matched (e.g. the job was killed by the operator mid-breach)."""
    firing = _alerts_still_firing(b)
    if not firing:
        return None
    worst = sorted(firing.items(), key=lambda kv: (
        0 if kv[1].get("severity") == "page" else 1, kv[0]))[0]
    ev = [f"events: ALERT_FIRING {rule} [{p.get('severity', '?')}] "
          f"value={p.get('value')} — never resolved"
          for rule, p in sorted(firing.items())]
    if worst[1].get("summary"):
        ev.append(f"alert summary: {worst[1]['summary']}")
    return Finding(
        SLO_BREACH, "slo-breach",
        f"alert {worst[0]!r} was firing when the job ended and never "
        f"resolved — the SLO broke before the terminal verdict",
        blamed_task=_blame(b), confidence=0.6, evidence=ev,
        details={"rules": sorted(firing)})


@_rule("unknown", UNKNOWN, ("APPLICATION_FINISHED",))
def _unknown(b: IncidentBundle) -> Optional[Finding]:
    """Fallback: a non-SUCCEEDED job always gets at least this."""
    ev = []
    if b.failure_reason:
        ev.append(f"failure_reason: {b.failure_reason}")
    t = b.first_failed_task()
    if t is not None:
        ev.append(f"first failed task: {t.task_id} "
                  f"{t.exit_detail or describe_exit(t.exit_code)}")
    return Finding(
        UNKNOWN, "unknown",
        "no rule matched — see the timeline and raw evidence",
        blamed_task=_blame(b), confidence=0.1, evidence=ev)


def _final_exception_line(traceback_text: str) -> str:
    """Last unindented 'ExcName: message' line of a traceback block."""
    for line in reversed(traceback_text.splitlines()):
        if line and line[0] not in (" ", "\t") \
                and not line.startswith("Traceback"):
            return line.strip()
    return ""


# -- engine ----------------------------------------------------------------
#: default-pack alert rule → the failure category it corroborates. An
#: alert left firing at job end is a precedence-boosted input: the
#: matching structural finding gains confidence and cites the alert.
_ALERT_CATEGORY = {
    "heartbeat-age": INFRA_STORM,    # executor silence precedes vanish
    "step-time-slo": HANG,           # step rate collapsed first
    "input-bound": STRAGGLER_CASCADE,
    "journal-fsync-p99": INFRA_STORM,
}


def run_rules(bundle: IncidentBundle) -> List[Finding]:
    """All findings, verdict-candidate first (category precedence, then
    confidence). Rules never raise out of the engine — a broken rule
    downgrades to absent, it cannot take the whole diagnosis down.

    Post-pass: alerts left firing at job end (``_alerts_still_firing``)
    boost the confidence of findings in the category the alert
    corroborates — the live SLO engine saw the breach develop BEFORE
    the terminal verdict, which is stronger than post-hoc log shape."""
    import logging

    findings: List[Finding] = []
    for rule in RULES:
        try:
            f = rule.fn(bundle)
        except Exception:  # noqa: BLE001 — diagnosis must degrade, not die
            logging.getLogger(__name__).exception(
                "diagnosis rule %s failed", rule.name)
            continue
        if f is not None:
            findings.append(f)
    try:
        firing = _alerts_still_firing(bundle)
    except Exception:  # noqa: BLE001 — same degrade contract as rules
        logging.getLogger(__name__).exception(
            "alert-evidence post-pass failed")
        firing = {}
    for f in findings:
        corroborating = sorted(
            rule for rule in firing
            if _ALERT_CATEGORY.get(rule) == f.category)
        if corroborating and f.category != SLO_BREACH:
            f.confidence = min(0.99, f.confidence + 0.1)
            f.evidence.append(
                f"alerts: {corroborating} firing before the terminal "
                f"verdict (corroborating — see `tony-tpu alerts`)")
    prec = {c: i for i, c in enumerate(CATEGORY_PRECEDENCE)}
    findings.sort(key=lambda f: (prec.get(f.category, len(prec)),
                                 -f.confidence))
    return findings


def verdict_of(findings: List[Finding]) -> Finding:
    return findings[0] if findings else Finding(
        UNKNOWN, "none", "no findings", confidence=0.0)
