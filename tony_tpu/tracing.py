"""Control-plane distributed tracing: spans across client, coordinator and
executors, stitched into ONE tree per job.

The reference had no tracing at all — its observability was the jhist
event stream read after the fact, so "where did the 15 s submit→first-step
go" had no answer short of grepping task logs. Podracer (arXiv:2104.06272)
makes the case that TPU-pod orchestration lives or dies on utilization
accounting across the whole launch path; this module is the launch-path
half of that story (tony_tpu/metrics.py is the steady-state half).

Model: the usual trace_id / span_id / parent_id tree. One trace per job:

- the CLIENT starts the trace at submit (``client.submit`` root span) and
  exports ``TONY_TRACE_ID`` / ``TONY_TRACE_PARENT`` to the coordinator;
- the COORDINATOR parents ``coordinator.run`` under the client's span and
  owns the span LOG: ``trace.spans.jsonl`` in the job history dir, next to
  the jhist stream (same durability posture: JSON lines, torn-tail
  tolerated on read);
- EXECUTORS get the trace id and their task-lifecycle span id through the
  task env, record their own spans (register, user-process, first-step,
  teardown) in a local buffer, and ship them home over the ordinary RPC
  plane (``trace.push``) — one stitched file per job even when tasks run
  on other hosts;
- every RPC frame carries the caller's trace context (``tc`` in the inner
  request, next to the generation field — rpc/wire.py), so server-side
  spans for significant RPCs parent under the caller's span.

Clocks: absolute timestamps are wall-clock microseconds (the only clock
two hosts share at all); durations are measured on the MONOTONIC clock
and the end timestamp is derived as ``start + monotonic_elapsed``, so an
NTP step mid-span can never produce a negative or inflated duration.

Record grammar (one JSON object per line):

- ``{"ev": "B", trace, span, parent, name, svc, task, ts_us, args}`` —
  span opened (file-sink tracers write these eagerly, so a crashed
  coordinator leaves evidence of what was in flight);
- ``{"ev": "E", span, ts_us, args}`` — span closed;
- ``{"ev": "X", ..., ts_us, dur_us, args}`` — complete span in one record
  (what buffered tracers emit: a span is only ever shipped CLOSED, so a
  lost push can drop spans but never manufacture an unclosed one);
- ``{"ev": "I", ..., ts_us, args}`` — instant annotation.

``to_trace_events`` exports the log as Chrome/Perfetto ``trace_events``
JSON (``tony-tpu trace <app>``, portal ``/trace/<app>`` view). Unmatched
B records are reported as unclosed — the golden e2e test and bench.py
treat a nonzero count as a tracing regression.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple, Union

log = logging.getLogger(__name__)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def now_us() -> int:
    return int(time.time() * 1e6)


# ---------------------------------------------------------------------------
# RPC context: the caller's (trace_id, span_id) rides every request frame
# (rpc/wire.py stamps/reads "tc"); the server parks it in a thread-local
# around dispatch so handler-side spans can parent under the caller.
# ---------------------------------------------------------------------------
_rpc_ctx = threading.local()


def set_rpc_context(tc: Optional[Tuple[str, str]]) -> None:
    _rpc_ctx.value = tc


def get_rpc_context() -> Optional[Tuple[str, str]]:
    return getattr(_rpc_ctx, "value", None)


def clear_rpc_context() -> None:
    _rpc_ctx.value = None


class Span:
    """One open span. ``end()`` exactly once; attrs merge at either edge."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "service",
                 "task", "start_us", "_t0_mono", "attrs", "_tracer", "_done")

    def __init__(self, tracer: "Tracer", name: str, parent_id: str,
                 task: str = "",
                 attrs: Optional[Dict[str, Any]] = None) -> None:
        self.trace_id = tracer.trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.service = tracer.service
        self.task = task
        self.start_us = now_us()
        self._t0_mono = time.monotonic()
        self.attrs = dict(attrs or {})
        self._tracer = tracer
        self._done = False

    def end(self, end_us: Optional[int] = None, **attrs: Any) -> None:
        if self._done:
            return
        self._done = True
        if end_us is None:
            # Monotonic duration, wall-anchored start (module docstring).
            end_us = self.start_us + int(
                (time.monotonic() - self._t0_mono) * 1e6)
        self._tracer._end_span(self, max(int(end_us), self.start_us), attrs)

    # Context-manager form: `with tracer.start_span("x") as span:` closes
    # the span on every exit path, error included — the shape the
    # span-leak lint rule (devtools/tonylint.py) prefers. An explicitly
    # end()ed span inside the block stays ended (end is once-only).
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type: Optional[type],
                 exc: Optional[BaseException], tb: object) -> None:
        if exc_type is not None and not self._done:
            self.end(error=f"{exc_type.__name__}: {exc}"[:200])
        else:
            self.end()


class _NullSpan:
    """Returned by a disabled tracer: every write is a no-op, so call
    sites need no ``if tracer.enabled`` guards around span lifecycles."""

    trace_id = span_id = parent_id = name = service = task = ""
    start_us = 0
    attrs: Dict[str, Any] = {}

    def end(self, end_us: Optional[int] = None, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type: Optional[type],
                 exc: Optional[BaseException], tb: object) -> None:
        pass


NULL_SPAN = _NullSpan()


def _parent_id(parent: Union[Span, _NullSpan, str, None]) -> str:
    if parent is None:
        return ""
    if isinstance(parent, str):
        return parent
    return parent.span_id


class Tracer:
    """Span factory + record sink. Two sink modes:

    - ``path`` given (coordinator): append records to the span log as they
      happen — B at open, E at close — durably greppable mid-run;
    - no path (client, executors): buffer COMPLETE records only and let
      the owner ``drain()`` them into a ``trace.push`` RPC. A span is
      never shipped half-open, so remote crashes can lose spans but never
      leave unclosed ones in the job's log.

    Disabled tracers (``enabled=False``) hand out NULL_SPAN and drop
    everything — the zero-overhead production off-switch
    (tony.trace.enabled)."""

    def __init__(self, trace_id: Optional[str] = None, service: str = "",
                 path: Optional[str] = None, enabled: bool = True) -> None:
        self.trace_id = trace_id or new_trace_id()
        self.service = service
        self.enabled = enabled
        self._path = path
        self._file = None
        self._buffer: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    # -- span lifecycle --------------------------------------------------
    def start_span(self, name: str,
                   parent: Union[Span, _NullSpan, str, None] = None,
                   task: str = "",
                   attrs: Optional[Dict[str, Any]] = None
                   ) -> Union[Span, _NullSpan]:
        if not self.enabled:
            return NULL_SPAN
        span = Span(self, name, _parent_id(parent), task=task, attrs=attrs)
        if self._path is not None:
            self._write({"ev": "B", "trace": span.trace_id,
                         "span": span.span_id, "parent": span.parent_id,
                         "name": span.name, "svc": span.service,
                         "task": span.task, "ts_us": span.start_us,
                         "args": span.attrs})
        return span

    def _end_span(self, span: Span, end_us: int,
                  attrs: Dict[str, Any]) -> None:
        if self._path is not None:
            self._write({"ev": "E", "span": span.span_id, "ts_us": end_us,
                         "args": dict(attrs)})
        else:
            merged = dict(span.attrs)
            merged.update(attrs)
            self._write({"ev": "X", "trace": span.trace_id,
                         "span": span.span_id, "parent": span.parent_id,
                         "name": span.name, "svc": span.service,
                         "task": span.task, "ts_us": span.start_us,
                         "dur_us": end_us - span.start_us, "args": merged})

    def emit(self, name: str, start_us: int, end_us: int,
             parent: Union[Span, _NullSpan, str, None] = None,
             task: str = "",
             attrs: Optional[Dict[str, Any]] = None) -> None:
        """Record a complete span whose edges were observed out of band
        (e.g. executor.first_step, whose end is the user process's own
        wall timestamp from the telemetry file)."""
        if not self.enabled:
            return
        self._write({"ev": "X", "trace": self.trace_id,
                     "span": new_span_id(), "parent": _parent_id(parent),
                     "name": name, "svc": self.service, "task": task,
                     "ts_us": int(start_us),
                     "dur_us": max(0, int(end_us) - int(start_us)),
                     "args": dict(attrs or {})})

    def instant(self, name: str,
                parent: Union[Span, _NullSpan, str, None] = None,
                task: str = "",
                attrs: Optional[Dict[str, Any]] = None) -> None:
        """Zero-duration annotation (APPLICATION_FINISHED, verdicts...)."""
        if not self.enabled:
            return
        self._write({"ev": "I", "trace": self.trace_id,
                     "span": new_span_id(), "parent": _parent_id(parent),
                     "name": name, "svc": self.service, "task": task,
                     "ts_us": now_us(), "args": dict(attrs or {})})

    # -- sinks -----------------------------------------------------------
    def _write(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._path is None:
                self._buffer.append(record)
                return
            try:
                if self._file is None:
                    os.makedirs(os.path.dirname(self._path) or ".",
                                exist_ok=True)
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(json.dumps(record, sort_keys=True) + "\n")
                self._file.flush()
            except (OSError, ValueError, TypeError) as e:
                # Tracing is diagnostics, never a job-failure source.
                log.warning("span record dropped: %s", e)

    def write_records(self, records: Any) -> int:
        """Remote-span intake (the ``trace.push`` RPC lands here): append
        pre-formed records from executors/clients into this tracer's sink.
        Malformed entries are dropped, counted records returned."""
        if not self.enabled or not isinstance(records, (list, tuple)):
            return 0
        n = 0
        for rec in records:
            if isinstance(rec, dict) and rec.get("ev") in ("B", "E", "X",
                                                           "I"):
                self._write(rec)
                n += 1
        return n

    def drain(self) -> List[Dict[str, Any]]:
        """Take the buffered records (buffer-mode tracers only)."""
        with self._lock:
            out, self._buffer = self._buffer, []
        return out

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# ---------------------------------------------------------------------------
# Span-log reading + Chrome/Perfetto export
# ---------------------------------------------------------------------------
def load_records(path: str) -> List[Dict[str, Any]]:
    """Decode a span log; torn-tail tolerant like events.read_events (a
    SIGKILLed coordinator can leave a partial final line)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    log.warning("torn span record in %s after %d good ones",
                                path, len(out))
                    break
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def existing_trace_id(path: str) -> str:
    """Trace id of an existing span log ('' when absent/empty) — how a
    recovered coordinator rejoins the job's original trace."""
    for rec in load_records(path)[:1]:
        return str(rec.get("trace", ""))
    return ""


def to_trace_events(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Export records as Chrome ``trace_events`` JSON (Perfetto-loadable).

    Complete ("X") events per span; services map to pids and tasks to
    tids with ``process_name``/``thread_name`` metadata so the timeline
    groups client / coordinator / per-task executor tracks. Returns the
    payload with two extra top-level keys (ignored by viewers):
    ``unclosedSpans`` (names of B records with no matching E — zero on any
    healthy run) and ``traceId``."""
    opens: Dict[str, Dict[str, Any]] = {}
    spans: List[Dict[str, Any]] = []
    instants: List[Dict[str, Any]] = []
    trace_id = ""
    for rec in records:
        ev = rec.get("ev")
        trace_id = trace_id or str(rec.get("trace", "") or "")
        if ev == "B":
            opens[str(rec.get("span"))] = rec
        elif ev == "E":
            begin = opens.pop(str(rec.get("span")), None)
            if begin is None:
                continue
            merged = dict(begin.get("args") or {})
            merged.update(rec.get("args") or {})
            span = dict(begin)
            span["args"] = merged
            span["dur_us"] = max(
                0, int(rec.get("ts_us", 0)) - int(begin.get("ts_us", 0)))
            spans.append(span)
        elif ev == "X":
            spans.append(rec)
        elif ev == "I":
            instants.append(rec)

    pids: Dict[str, int] = {}
    tids: Dict[Tuple[str, str], int] = {}
    events: List[Dict[str, Any]] = []

    def _ids(rec: Dict[str, Any]) -> Tuple[int, int]:
        svc = str(rec.get("svc", "") or "?")
        task = str(rec.get("task", "") or "")
        if svc not in pids:
            pids[svc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[svc], "tid": 0,
                           "args": {"name": svc}})
        key = (svc, task)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == svc]) + 1 \
                if task else 0
            if task:
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pids[svc], "tid": tids[key],
                               "args": {"name": task}})
        return pids[svc], tids[key]

    for rec in sorted(spans, key=lambda r: int(r.get("ts_us", 0))):
        pid, tid = _ids(rec)
        args = dict(rec.get("args") or {})
        args.update({"trace": rec.get("trace", ""),
                     "span": rec.get("span", ""),
                     "parent": rec.get("parent", "")})
        if rec.get("task"):
            args["task"] = rec["task"]
        events.append({"ph": "X", "name": str(rec.get("name", "?")),
                       "cat": str(rec.get("svc", "") or "span"),
                       "ts": int(rec.get("ts_us", 0)),
                       "dur": int(rec.get("dur_us", 0)),
                       "pid": pid, "tid": tid, "args": args})
    for rec in sorted(instants, key=lambda r: int(r.get("ts_us", 0))):
        pid, tid = _ids(rec)
        events.append({"ph": "i", "s": "g",
                       "name": str(rec.get("name", "?")),
                       "cat": str(rec.get("svc", "") or "span"),
                       "ts": int(rec.get("ts_us", 0)),
                       "pid": pid, "tid": tid,
                       "args": dict(rec.get("args") or {})})
    unclosed = [str(r.get("name", "?")) for r in opens.values()]
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "traceId": trace_id, "unclosedSpans": unclosed}


# ---------------------------------------------------------------------------
# Cold-start decomposition: the submit→first-step critical path as phases
# ---------------------------------------------------------------------------
#: (phase, span name, edge) boundary schedule along the critical path. Each
#: phase runs from the previous boundary to this span's start/end, so the
#: phase durations are CONSECUTIVE and sum exactly to the headline
#: submit→first-step latency — the property that lets a BENCH artifact
#: attribute a regression to one phase without re-running anything.
_COLD_START_BOUNDARIES = (
    # client-side staging (bundle copytree / store PUTs / venv)
    ("stage", "client.stage", "end"),
    # coordinator interpreter boot + backend/slice provisioning + schedule
    ("provision", "task.lifecycle", "start"),
    # executor process spawn + python interpreter + tony_tpu import
    # (the phase a warm-pool lease collapses to ~0)
    ("spawn", "executor.run", "start"),
    # registration + gang barrier (bundle localization overlaps this
    # since the parallel-localize change; its own duration is reported
    # separately under span_durations)
    ("register", "executor.register", "end"),
    # runtime env build + port release + user-process exec
    ("launch", "executor.user_process", "start"),
    # user interpreter + jax import + compile + first real step
    ("user_boot", "executor.first_step", "end"),
)


def cold_start_breakdown(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Decompose ``client.submit → executor.first_step`` into per-phase
    durations, straight from a job's span records.

    Anchors on the FIRST ``executor.first_step`` span (by end time) and
    that task's own lifecycle/executor spans, so multi-task gangs and
    retry epochs report the path of the task that actually reached its
    first step first. Raises RuntimeError when the anchor spans are
    missing — the same loud-on-regression posture as the bench's span
    check. Returns::

        {"total_s": float,            # == sum(phases.values()), exact
         "task": "worker:0",
         "phases": {phase: seconds, ...},     # ordered, consecutive
         "span_durations": {name: seconds}}   # raw (possibly overlapping)
    """
    payload = to_trace_events(records)
    events = [e for e in payload["traceEvents"] if e.get("ph") == "X"]

    def _task(e: Dict[str, Any]) -> str:
        return str((e.get("args") or {}).get("task", "") or "")

    submits = [e for e in events if e["name"] == "client.submit"]
    firsts = [e for e in events if e["name"] == "executor.first_step"]
    if not submits or not firsts:
        raise RuntimeError(
            f"cold-start breakdown needs client.submit and "
            f"executor.first_step spans (have: "
            f"{sorted({e['name'] for e in events})})")
    submit = min(submits, key=lambda e: e["ts"])
    first = min(firsts, key=lambda e: e["ts"] + e.get("dur", 0))
    task = _task(first)

    def _boundary(name: str, edge: str) -> Optional[int]:
        # Prefer the anchor task's span; fall back to task-less spans
        # (client.stage has no task). First occurrence wins — a retry
        # epoch's second lifecycle span is not this cold start.
        cands = [e for e in events if e["name"] == name
                 and _task(e) in (task, "")]
        if not cands:
            return None
        e = min(cands, key=lambda c: c["ts"])
        return int(e["ts"] + (e.get("dur", 0) if edge == "end" else 0))

    t0 = int(submit["ts"])
    phases: Dict[str, float] = {}
    prev = t0
    end = int(first["ts"] + first.get("dur", 0))
    for phase, span_name, edge in _COLD_START_BOUNDARIES:
        b = _boundary(span_name, edge)
        if b is None:
            # A missing intermediate span folds its time into the next
            # phase instead of losing it (the sum must stay exact).
            continue
        b = max(min(b, end), prev)   # clamp: monotonic, inside the window
        phases[phase] = round((b - prev) / 1e6, 4)
        prev = b
    # Anything after the last known boundary still belongs to the total.
    if end > prev:
        phases["user_boot"] = round(
            phases.get("user_boot", 0.0) + (end - prev) / 1e6, 4)
    durations: Dict[str, float] = {}
    for name in ("client.stage", "executor.localize", "executor.register",
                 "executor.user_process", "executor.first_step",
                 "pool.lease", "gang.rendezvous"):
        cands = [e for e in events if e["name"] == name
                 and _task(e) in (task, "")]
        if cands:
            e = min(cands, key=lambda c: c["ts"])
            durations[name] = round(e.get("dur", 0) / 1e6, 4)
    return {"total_s": round((end - t0) / 1e6, 4), "task": task,
            "phases": phases, "span_durations": durations}
