"""Shared retry policy: exponential backoff with full jitter.

One policy object serves every transient-failure surface — control-plane
RPC reconnects (``rpc/wire.py``), object-store transfers
(``storage/store.py`` — GCS 429/5xx and socket resets), and any future
cloud-API caller. The reference retried everything on a fixed cadence
(``ApplicationRpcClient.java:66-76``: 10 × 2 s), which synchronizes an
entire gang's retries into bursts exactly when the service is least able
to absorb them; full jitter (delay ~ U[0, min(cap, base·2^attempt)]) is
the standard de-correlator (the AWS-architecture result: near-optimal
total load at the same completion time).

Determinism for tests: the RNG, sleep, and (therefore) the clock are all
injectable — the ``-m faults`` unit suite drives policies with a seeded
``random.Random`` and a recording fake sleep, so backoff schedules are
asserted exactly, with zero wall-clock cost.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional, Sequence, Tuple, Type

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter.

    ``max_attempts`` bounds TOTAL tries (first call included);
    ``base_delay_s`` seeds the exponential ramp; ``max_delay_s`` caps any
    single sleep. ``jitter=False`` makes the schedule the deterministic
    upper envelope (min(cap, base·2^attempt)) — for tests that want exact
    delays without threading an RNG through.
    """

    max_attempts: int = 5
    base_delay_s: float = 0.5
    max_delay_s: float = 10.0
    jitter: bool = True

    def delay_s(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Sleep before retry number ``attempt`` (0-based: the delay
        between the first failure and the second try)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
        if not self.jitter:
            return cap
        return (rng or _default_rng).uniform(0.0, cap)


#: module-level RNG for production call sites (seeded by the fault
#: harness when determinism is requested — see tony_tpu/faults.py)
_default_rng = random.Random()


def seed_default_rng(seed: int) -> None:
    """Make jittered delays reproducible process-wide (fault harness)."""
    global _default_rng
    _default_rng = random.Random(seed)


def call_with_retry(
    fn: Callable[[], "object"],
    policy: RetryPolicy,
    retry_on: Tuple[Type[BaseException], ...] = (ConnectionError, OSError),
    give_up_on: Tuple[Type[BaseException], ...] = (),
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    what: str = "operation",
) -> object:
    """Run ``fn`` under ``policy``. Exceptions in ``give_up_on`` (checked
    first — carve non-retryable subclasses like FileNotFoundError out of
    OSError) and anything not in ``retry_on`` propagate immediately; the
    last retryable failure propagates once attempts are exhausted.
    ``on_retry(attempt, err, delay_s)`` observes each scheduled retry.
    """
    attempts = max(1, policy.max_attempts)
    for attempt in range(attempts):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if attempt >= attempts - 1:
                raise
            delay = policy.delay_s(attempt, rng)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            else:
                log.debug("%s failed (%s); retry %d/%d in %.2fs",
                          what, e, attempt + 1, attempts - 1, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # loop always returns or raises
