"""Local-process backend: one executor subprocess per task.

Dual role, mirroring the reference:
- the **test substrate** — in-process fake cluster like
  ``tony-mini/.../MiniCluster.java:43-63`` (no YARN/HDFS needed);
- the **single-host production path** — on a TPU VM the coordinator and all
  task processes are host-local, and JAX device visibility is partitioned per
  task via env when multiple tasks share the host's chips.

Each task runs ``python -m tony_tpu.executor`` (the TaskExecutor entrypoint)
in its own working directory with the task-identity environment; stdout/stderr
are captured per task like YARN container logs
(``ApplicationMaster.java:1145-1147``).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from tony_tpu import constants

from tony_tpu.cluster.base import (Backend, TaskLaunchSpec,
                                   build_executor_argv, container_name,
                                   docker_kill)

log = logging.getLogger(__name__)


class _Proc:
    def __init__(self, task_id: str, popen, workdir: str,
                 container: str = ""):
        self.task_id = task_id
        self.popen = popen
        self.workdir = workdir
        self.container = container   # docker container name, if dockerized
        self.reported = False


class _LeasedProc:
    """Popen-shaped handle over a warm-pool executor. The process is the
    POOL DAEMON's child, not ours, so liveness is a signal-0 probe and
    the exit code comes from the ``pool-exit.json`` the adopted executor
    writes into its task workdir at exit (constants.POOL_EXIT_FILE) —
    pid-dead with no report reads as a crash (EXIT_FAILURE)."""

    def __init__(self, pid: int, workdir: str, worker_id: str):
        self.pid = pid
        self.workdir = workdir
        self.worker_id = worker_id
        self.returncode: object = None

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        path = os.path.join(self.workdir, constants.POOL_EXIT_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                self.returncode = int(json.load(f).get("exit_code", 1))
            return self.returncode
        except (OSError, ValueError, TypeError):
            pass
        try:
            os.kill(self.pid, 0)
            return None               # still running
        except ProcessLookupError:
            # Dead without a report: killed or crashed pre-report. Mirror
            # waitpid's negative-signal convention (what a SIGKILLed cold
            # spawn reports) so poll_completions maps it to 137 →
            # INFRA_TRANSIENT — a kill must stay retryable, not become a
            # USER_ERROR exit-1, just because the executor was pooled.
            self.returncode = -int(signal.SIGKILL)
            return self.returncode
        except PermissionError:
            return None


class LocalProcessBackend(Backend):
    def __init__(self, workdir: str, python: str = sys.executable,
                 inherit_env: bool = True, pool_dir: str = ""):
        self.workdir = workdir
        self.python = python
        self.inherit_env = inherit_env
        self._procs: Dict[str, _Proc] = {}
        self._lock = threading.Lock()
        # Warm executor pool (tony_tpu/pool.py): with tony.pool.dir set,
        # launch_task tries to ADOPT a pre-warmed executor before cold-
        # spawning; every pool failure degrades to the cold path below.
        self._pool = None
        if pool_dir:
            from tony_tpu.pool import PoolClient

            self._pool = PoolClient(pool_dir)
        os.makedirs(workdir, exist_ok=True)

    def launch_task(self, spec: TaskLaunchSpec) -> object:
        task_dir = os.path.join(self.workdir,
                                spec.task_id.replace(":", "_"))
        os.makedirs(task_dir, exist_ok=True)
        env = dict(os.environ) if self.inherit_env else {}
        env.update(spec.env)
        # Make `import tony_tpu` resolvable in the child regardless of cwd.
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep + env.get("PYTHONPATH", "")
                             ).rstrip(os.pathsep)
        if self._pool is not None and not spec.docker_image:
            proc = self._try_pool_lease(spec, task_dir, env)
            if proc is not None:
                with self._lock:
                    self._procs[spec.task_id] = proc
                return proc
        stdout = open(os.path.join(task_dir, "stdout.log"), "ab")
        stderr = open(os.path.join(task_dir, "stderr.log"), "ab")
        popen = subprocess.Popen(
            build_executor_argv(self.python, spec, task_dir),
            cwd=task_dir, env=env, stdout=stdout, stderr=stderr,
            start_new_session=True)
        proc = _Proc(spec.task_id, popen, task_dir,
                     container=container_name(spec) if spec.docker_image
                     else "")
        with self._lock:
            self._procs[spec.task_id] = proc
        log.info("launched %s pid=%d dir=%s", spec.task_id, popen.pid, task_dir)
        return proc

    def _try_pool_lease(self, spec: TaskLaunchSpec, task_dir: str,
                        env: Dict[str, str]) -> Optional[_Proc]:
        """Adopt a warm executor for this task, or None → cold spawn.
        Pool trouble of ANY shape — daemon gone, lease refused, stale
        generation, worker dead on adoption (each rehearsable via the
        pool.* fault sites) — degrades to the cold path; it must never
        fail the launch. A granted-but-unusable lease is DISCARDED at the
        daemon (never returned to the pool) before falling back."""
        from tony_tpu import faults, tracing
        from tony_tpu.pool import PoolError

        t0 = tracing.now_us()
        lease = None
        try:
            faults.check("pool.lease")
            faults.check("pool.stale")
            lease = self._pool.lease(
                spec.task_id, env, task_dir,
                app_id=env.get(constants.APP_ID, ""),
                generation=int(
                    env.get(constants.COORDINATOR_GENERATION, "0") or 0))
            dead: Optional[BaseException] = None
            try:
                faults.check("pool.adopt")
                os.kill(int(lease["pid"]), 0)
            except ProcessLookupError as e:
                dead = e
            except PermissionError:
                pass                   # alive, just not ours to signal
            except faults.InjectedFault as e:
                dead = e
            if dead is not None:
                self._pool.discard(str(lease.get("worker_id", "")),
                                   reason=f"dead on adoption: {dead}")
                raise PoolError(
                    f"leased executor pid {lease.get('pid')} dead on "
                    f"adoption: {dead}") from dead
        except Exception as e:  # noqa: BLE001 — every shape cold-spawns
            # A granted-then-unusable lease names its worker in the span:
            # the trace is how an operator finds the discarded worker.
            worker = str(lease.get("worker_id", "")) if lease else ""
            self._emit_lease_span(spec, t0, error=str(e)[:200],
                                  **({"worker": worker} if worker else {}))
            log.warning("pool lease for %s failed (%s); cold-spawning",
                        spec.task_id, e)
            return None
        self._emit_lease_span(spec, t0, worker=lease["worker_id"],
                              pid=int(lease["pid"]),
                              worker_age_s=lease.get("age_s"))
        log.info("adopted warm executor for %s: worker %s pid %d",
                 spec.task_id, lease["worker_id"], lease["pid"])
        return _Proc(spec.task_id,
                     _LeasedProc(int(lease["pid"]), task_dir,
                                 str(lease["worker_id"])),
                     task_dir)

    def _emit_lease_span(self, spec: TaskLaunchSpec, start_us: int,
                         **attrs) -> None:
        """pool.lease span under the task's lifecycle span (the trace
        parent the coordinator stamped into the launch env) — how a warm
        adoption (or its failure→fallback) shows up on the timeline."""
        tracer = getattr(self, "tracer", None)
        if tracer is None:
            return
        from tony_tpu import tracing

        tracer.emit("pool.lease", start_us=start_us,
                    end_us=tracing.now_us(),
                    parent=spec.env.get(constants.TRACE_PARENT_ENV, ""),
                    task=spec.task_id, attrs=attrs)

    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        proc = handle
        if not isinstance(proc, _Proc):
            return
        if proc.container and proc.popen.poll() is None:
            # The containerized executor is containerd's child, not ours:
            # signal the container by name, then the docker-run client.
            docker_kill(proc.container, grace_s=grace_s)
        # The user command lives in its OWN session (utils/proc.execute_shell)
        # — signalling the executor's group alone never reaches it. Deliver
        # the TERM→grace→KILL ladder to both groups; the pgid file is how we
        # reach the user tree even when the executor is already dead
        # (constants.USER_PGID_FILE contract). Pooled executors work the
        # same way: the daemon spawned them session-leading, so their pid
        # IS their pgid.
        from tony_tpu.utils.proc import kill_process_groups, read_pgid_file

        groups = [proc.popen.pid] if proc.popen.poll() is None else []
        if not proc.container:
            # Containerized tasks: user.pgid holds a pid from the
            # container's OWN pid namespace — meaningless (and dangerous to
            # signal) on the host; docker_kill above reaps the in-container
            # tree instead.
            user_pgid = read_pgid_file(
                os.path.join(proc.workdir, constants.USER_PGID_FILE))
            if user_pgid:
                groups.append(user_pgid)
        kill_process_groups(groups, grace_s=grace_s)

    def gang_active(self) -> bool:
        """Any launched executor still alive? The coordinator's epoch
        reset waits on this before relaunching (Backend.gang_active) so a
        killed-but-unreaped task can't leak its exit into the new epoch."""
        with self._lock:
            return any(not p.reported and p.popen.poll() is None
                       for p in self._procs.values())

    def poll_completions(self) -> List[Tuple[str, int]]:
        done: List[Tuple[str, int]] = []
        with self._lock:
            for proc in self._procs.values():
                if proc.reported:
                    continue
                rc = proc.popen.poll()
                if rc is not None:
                    proc.reported = True
                    # Negative returncode = killed by signal N.
                    exit_code = 128 - rc if rc < 0 else rc
                    done.append((proc.task_id, exit_code))
        return done

    def task_log_paths(self, task_id: str) -> Optional[Tuple[str, str]]:
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None:
            return None
        return (os.path.join(proc.workdir, "stdout.log"),
                os.path.join(proc.workdir, "stderr.log"))

    def stop(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            self.kill_task(proc, grace_s=0.5)


class VirtualExecutorBackend(Backend):
    """Width-harness twin of :class:`LocalProcessBackend`
    (``tony.scale.virtual-executors``): every launched task becomes a
    beat-only in-process virtual executor (executor/virtual.py) — real
    registration/heartbeat/result RPC traffic against the coordinator,
    no subprocess, no user command — so the control plane is exercised
    at 128–1024 tasks per box (``bench.py --suite scale``,
    tests/test_scale.py). One shared :class:`VirtualGang` pump serves
    every task; its coordinates come from the first launch spec's env
    (the same identity contract a real executor reads)."""

    def __init__(self, workdir: str, hb_interval_s: float = 1.0,
                 steps_per_s: float = 5.0, run_s: float = 0.0,
                 pump_threads: int = 8):
        self.workdir = workdir
        self.hb_interval_s = hb_interval_s
        self.steps_per_s = steps_per_s
        self.run_s = run_s
        self.pump_threads = pump_threads
        self._gang = None
        self._handles: Dict[str, object] = {}
        self._reported: set = set()
        self._lock = threading.Lock()

    @classmethod
    def from_conf(cls, conf, workdir: str) -> "VirtualExecutorBackend":
        from tony_tpu.conf import keys as K

        return cls(
            workdir,
            hb_interval_s=conf.get_int(K.TASK_HEARTBEAT_INTERVAL_MS,
                                       1000) / 1000.0,
            steps_per_s=float(
                conf.get(K.SCALE_VIRTUAL_STEPS_PER_S, 5.0) or 5.0),
            run_s=float(conf.get(K.SCALE_VIRTUAL_RUN_S, 0.0) or 0.0),
            pump_threads=conf.get_int(K.SCALE_VIRTUAL_PUMP_THREADS, 8))

    def launch_task(self, spec: TaskLaunchSpec) -> object:
        from tony_tpu.executor.virtual import VirtualGang

        # Same launch-path fault seam every real backend passes through
        # (``executor.spawn``) — argv itself is discarded.
        build_executor_argv(sys.executable, spec, self.workdir)
        env = spec.env
        with self._lock:
            if self._gang is None:
                self._gang = VirtualGang(
                    env.get(constants.COORDINATOR_HOST, "127.0.0.1"),
                    int(env.get(constants.COORDINATOR_PORT, "0") or 0),
                    token=env.get("TONY_RPC_TOKEN") or None,
                    generation=int(
                        env.get(constants.COORDINATOR_GENERATION, "0")
                        or 0),
                    hb_interval_s=self.hb_interval_s,
                    steps_per_s=self.steps_per_s, run_s=self.run_s,
                    pump_threads=self.pump_threads)
            gang = self._gang
        handle = gang.launch(
            spec.task_id,
            session_id=int(env.get(constants.SESSION_ID, "0") or 0),
            mgen=int(env.get(constants.MEMBERSHIP_GEN, "-1") or -1))
        with self._lock:
            self._handles[spec.task_id] = handle
            self._reported.discard(spec.task_id)
        return handle

    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        task_id = getattr(handle, "task_id", None)
        if task_id is not None and self._gang is not None:
            self._gang.kill(task_id)

    def poll_completions(self) -> List[Tuple[str, int]]:
        done: List[Tuple[str, int]] = []
        with self._lock:
            for task_id, handle in self._handles.items():
                if task_id in self._reported:
                    continue
                rc = handle.poll()
                if rc is not None:
                    self._reported.add(task_id)
                    done.append((task_id, int(rc)))
        return done

    def gang_active(self) -> bool:
        with self._lock:
            return any(h.poll() is None for h in self._handles.values())

    def stop(self) -> None:
        if self._gang is not None:
            self._gang.stop()
