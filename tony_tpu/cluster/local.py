"""Local-process backend: one executor subprocess per task.

Dual role, mirroring the reference:
- the **test substrate** — in-process fake cluster like
  ``tony-mini/.../MiniCluster.java:43-63`` (no YARN/HDFS needed);
- the **single-host production path** — on a TPU VM the coordinator and all
  task processes are host-local, and JAX device visibility is partitioned per
  task via env when multiple tasks share the host's chips.

Each task runs ``python -m tony_tpu.executor`` (the TaskExecutor entrypoint)
in its own working directory with the task-identity environment; stdout/stderr
are captured per task like YARN container logs
(``ApplicationMaster.java:1145-1147``).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from tony_tpu.cluster.base import (Backend, TaskLaunchSpec,
                                   build_executor_argv, container_name,
                                   docker_kill)

log = logging.getLogger(__name__)


class _Proc:
    def __init__(self, task_id: str, popen: subprocess.Popen, workdir: str,
                 container: str = ""):
        self.task_id = task_id
        self.popen = popen
        self.workdir = workdir
        self.container = container   # docker container name, if dockerized
        self.reported = False


class LocalProcessBackend(Backend):
    def __init__(self, workdir: str, python: str = sys.executable,
                 inherit_env: bool = True):
        self.workdir = workdir
        self.python = python
        self.inherit_env = inherit_env
        self._procs: Dict[str, _Proc] = {}
        self._lock = threading.Lock()
        os.makedirs(workdir, exist_ok=True)

    def launch_task(self, spec: TaskLaunchSpec) -> object:
        task_dir = os.path.join(self.workdir,
                                spec.task_id.replace(":", "_"))
        os.makedirs(task_dir, exist_ok=True)
        env = dict(os.environ) if self.inherit_env else {}
        env.update(spec.env)
        # Make `import tony_tpu` resolvable in the child regardless of cwd.
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep + env.get("PYTHONPATH", "")
                             ).rstrip(os.pathsep)
        stdout = open(os.path.join(task_dir, "stdout.log"), "ab")
        stderr = open(os.path.join(task_dir, "stderr.log"), "ab")
        popen = subprocess.Popen(
            build_executor_argv(self.python, spec, task_dir),
            cwd=task_dir, env=env, stdout=stdout, stderr=stderr,
            start_new_session=True)
        proc = _Proc(spec.task_id, popen, task_dir,
                     container=container_name(spec) if spec.docker_image
                     else "")
        with self._lock:
            self._procs[spec.task_id] = proc
        log.info("launched %s pid=%d dir=%s", spec.task_id, popen.pid, task_dir)
        return proc

    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        proc = handle
        if not isinstance(proc, _Proc):
            return
        if proc.container and proc.popen.poll() is None:
            # The containerized executor is containerd's child, not ours:
            # signal the container by name, then the docker-run client.
            docker_kill(proc.container, grace_s=grace_s)
        # The user command lives in its OWN session (utils/proc.execute_shell)
        # — signalling the executor's group alone never reaches it. Deliver
        # the TERM→grace→KILL ladder to both groups; the pgid file is how we
        # reach the user tree even when the executor is already dead
        # (constants.USER_PGID_FILE contract).
        from tony_tpu import constants
        from tony_tpu.utils.proc import kill_process_groups, read_pgid_file

        groups = [proc.popen.pid] if proc.popen.poll() is None else []
        if not proc.container:
            # Containerized tasks: user.pgid holds a pid from the
            # container's OWN pid namespace — meaningless (and dangerous to
            # signal) on the host; docker_kill above reaps the in-container
            # tree instead.
            user_pgid = read_pgid_file(
                os.path.join(proc.workdir, constants.USER_PGID_FILE))
            if user_pgid:
                groups.append(user_pgid)
        kill_process_groups(groups, grace_s=grace_s)

    def gang_active(self) -> bool:
        """Any launched executor still alive? The coordinator's epoch
        reset waits on this before relaunching (Backend.gang_active) so a
        killed-but-unreaped task can't leak its exit into the new epoch."""
        with self._lock:
            return any(not p.reported and p.popen.poll() is None
                       for p in self._procs.values())

    def poll_completions(self) -> List[Tuple[str, int]]:
        done: List[Tuple[str, int]] = []
        with self._lock:
            for proc in self._procs.values():
                if proc.reported:
                    continue
                rc = proc.popen.poll()
                if rc is not None:
                    proc.reported = True
                    # Negative returncode = killed by signal N.
                    exit_code = 128 - rc if rc < 0 else rc
                    done.append((proc.task_id, exit_code))
        return done

    def task_log_paths(self, task_id: str) -> Optional[Tuple[str, str]]:
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None:
            return None
        return (os.path.join(proc.workdir, "stdout.log"),
                os.path.join(proc.workdir, "stderr.log"))

    def stop(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            self.kill_task(proc, grace_s=0.5)
