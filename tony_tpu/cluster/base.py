"""Backend abstraction: how task processes are actually started.

This replaces the reference's YARN substrate (RM container allocation
``RMCallbackHandler.onContainersAllocated`` ``ApplicationMaster.java:1051`` +
NM container launch ``ContainerLauncher.run`` :1108-1175) with a minimal
lease-style interface the coordinator drives directly:

- ``LocalProcessBackend`` (``local.py``) — subprocesses on this host; the
  MiniCluster analogue (``tony-mini/.../MiniCluster.java:43-63``) and also
  the real single-TPU-VM path (one process per local chip group).
- ``TpuSliceBackend`` (``tpu.py``) — gang launch over an atomically leased
  multi-host slice via a ``SliceProvisioner`` (ssh inventory for real TPU
  VMs, ``FakeSliceProvisioner`` for hardware-free e2e, incl. host-loss and
  capacity-denial fault injection).

A backend launches whole tasks-with-environments and reports exits; it knows
nothing about rendezvous, heartbeats or failure policy — those live in the
coordinator, exactly as the AM/YARN split does in the reference.
"""

from __future__ import annotations

import abc
import dataclasses
import os
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class TaskLaunchSpec:
    task_id: str
    job_name: str
    index: int
    command: str
    env: Dict[str, str]
    vcores: int = 1
    memory: str = "2g"
    chips: int = 0
    node_pool: str = ""
    docker_image: str = ""
    # Hosts this task must NOT land on (health exclude-on-retry: the
    # coordinator threads the hosts that already failed this task so a
    # relaunch never re-rolls the same bad hardware). Best-effort — a
    # backend with no alternative host may still use one.
    exclude_hosts: Tuple[str, ...] = ()


def container_name(spec: TaskLaunchSpec) -> str:
    """Deterministic docker container name for a task, so teardown can
    ``docker kill`` it by name (killing the ``docker run`` client process
    does NOT kill the container — it is containerd's child)."""
    raw = f"tony-{spec.env.get('TONY_APP_ID', 'app')}-{spec.task_id}"
    return "".join(c if c.isalnum() or c in "_.-" else "-" for c in raw)


def build_executor_argv(python: str, spec: TaskLaunchSpec,
                        workdir: str) -> list:
    """argv that launches this task's executor — wrapped in ``docker run``
    when the jobtype configures a container image (reference per-job docker
    support, ``TonyConfigurationKeys.java:178-239`` + docker env
    ``Utils.java:729-776``). Host networking keeps the rendezvous port
    contract unchanged; every task env var crosses with ``-e``; the task
    workdir, the job dir (frozen config + locally-staged bundle/resources/
    venv), and the checkpoint dir are bind-mounted at their host paths so
    localization works unchanged — with a remote store configured nothing
    but the workdir needs mounting. The image must contain python3 with
    tony-tpu installed (and, for accelerator jobs, ``jax[tpu]`` plus TPU
    device access — typically ``--privileged`` baked into a wrapper image
    or the docker daemon's default runtime on TPU VMs)."""
    from tony_tpu import faults

    # Single choke point every backend passes through immediately before
    # its process spawn — the ``executor.spawn`` injection site. A firing
    # raises, launch_task propagates, and the coordinator's launch-failure
    # policy (an INFRA_TRANSIENT session failure) takes over.
    faults.check("executor.spawn")
    if not spec.docker_image:
        return [python, "-m", "tony_tpu.executor"]
    argv = ["docker", "run", "--rm", "--network=host",
            "--name", container_name(spec),
            "-v", f"{workdir}:{workdir}", "-w", workdir]
    mounts = set()
    conf_path = spec.env.get("TONY_EXECUTOR_CONF", "")
    from tony_tpu.storage.store import is_url

    if conf_path and not is_url(conf_path):
        mounts.add(os.path.dirname(os.path.abspath(conf_path)))
    ckpt = spec.env.get("TONY_CHECKPOINT_DIR", "")
    if ckpt and not is_url(ckpt):
        mounts.add(os.path.abspath(ckpt))
    for m in sorted(mounts):
        argv += ["-v", f"{m}:{m}"]
    for k, v in spec.env.items():
        argv += ["-e", f"{k}={v}"]
    argv += [spec.docker_image, "python3", "-m", "tony_tpu.executor"]
    return argv


def docker_kill(name: str, grace_s: float = 0.0) -> None:
    """Best-effort teardown of a named task container (companion of
    build_executor_argv; see container_name). ``docker stop -t`` delivers
    TERM first and escalates to KILL after the grace window, preserving
    kill_task's TERM→grace→KILL contract for in-container checkpoint/
    cleanup handlers (bare ``docker kill`` is SIGKILL with no warning)."""
    import subprocess

    try:
        subprocess.run(
            ["docker", "stop", "-t", str(max(0, int(grace_s))), name],
            timeout=15 + grace_s,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    except Exception:  # noqa: BLE001 — teardown is best-effort
        pass


class Backend(abc.ABC):
    @abc.abstractmethod
    def launch_task(self, spec: TaskLaunchSpec) -> object:
        """Start the task; returns an opaque handle."""

    @abc.abstractmethod
    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        """Terminate the task (SIGTERM, then SIGKILL after grace)."""

    @abc.abstractmethod
    def poll_completions(self) -> List[Tuple[str, int]]:
        """Drain (task_id, exit_code) for tasks that exited since last call.

        The analogue of YARN's ``onContainersCompleted`` callback
        (``ApplicationMaster.java:1005-1023``) — catches processes that died
        without reporting their own exit over RPC.
        """

    def task_log_paths(self, task_id: str) -> Optional[Tuple[str, str]]:
        """(stdout, stderr) paths/URLs for a task, if the backend captures
        them (the reference surfaces NodeManager log URLs per container,
        ``models/JobLog.java:69-80``)."""
        return None

    def completion_domain(self, task_id: str) -> Optional[str]:
        """Failure-domain hint for a completion this backend reported:
        ``"PREEMPTION"`` when the backend KNOWS the machine went away
        under the task (slice host lost, node state PREEMPTED) — an exit
        code alone can't distinguish that from an OOM kill. None = no
        backend knowledge; the coordinator classifies from the exit code
        (coordinator/session.py classify_exit)."""
        return None

    def host_of(self, task_id: str) -> Optional[str]:
        """Which physical host a launched task runs on, if the backend
        places tasks on distinguishable hosts (slice VMs). None = no
        host identity (local processes) — the health exclude-on-retry
        path and fleet failure attribution both no-op then."""
        return None

    def gang_active(self) -> bool:
        """Any launched task still running? Backends with gang-scoped
        resources (slice leases) override this so the coordinator's
        epoch reset can wait for the old gang to be FULLY down before
        relaunching — re-leasing under a live gang would split it across
        slices (cluster/tpu.py lease invariant)."""
        return False

    def set_tracer(self, tracer) -> None:
        """Give the backend the job's tracer so launch-path work it does
        on the coordinator's behalf (warm-pool leases) lands in the span
        tree. Default: kept but unused — emitting spans stays optional
        per backend."""
        self.tracer = tracer

    def stop(self) -> None:
        """Release backend resources."""
