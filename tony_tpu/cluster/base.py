"""Backend abstraction: how task processes are actually started.

This replaces the reference's YARN substrate (RM container allocation
``RMCallbackHandler.onContainersAllocated`` ``ApplicationMaster.java:1051`` +
NM container launch ``ContainerLauncher.run`` :1108-1175) with a minimal
lease-style interface the coordinator drives directly:

- ``LocalProcessBackend`` (``local.py``) — subprocesses on this host; the
  MiniCluster analogue (``tony-mini/.../MiniCluster.java:43-63``) and also
  the real single-TPU-VM path (one process per local chip group).
- ``TpuSliceBackend`` (``tpu.py``) — gang launch over an atomically leased
  multi-host slice via a ``SliceProvisioner`` (ssh inventory for real TPU
  VMs, ``FakeSliceProvisioner`` for hardware-free e2e, incl. host-loss and
  capacity-denial fault injection).

A backend launches whole tasks-with-environments and reports exits; it knows
nothing about rendezvous, heartbeats or failure policy — those live in the
coordinator, exactly as the AM/YARN split does in the reference.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class TaskLaunchSpec:
    task_id: str
    job_name: str
    index: int
    command: str
    env: Dict[str, str]
    vcores: int = 1
    memory: str = "2g"
    chips: int = 0
    node_pool: str = ""


class Backend(abc.ABC):
    @abc.abstractmethod
    def launch_task(self, spec: TaskLaunchSpec) -> object:
        """Start the task; returns an opaque handle."""

    @abc.abstractmethod
    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        """Terminate the task (SIGTERM, then SIGKILL after grace)."""

    @abc.abstractmethod
    def poll_completions(self) -> List[Tuple[str, int]]:
        """Drain (task_id, exit_code) for tasks that exited since last call.

        The analogue of YARN's ``onContainersCompleted`` callback
        (``ApplicationMaster.java:1005-1023``) — catches processes that died
        without reporting their own exit over RPC.
        """

    def task_log_paths(self, task_id: str) -> Optional[Tuple[str, str]]:
        """(stdout, stderr) paths/URLs for a task, if the backend captures
        them (the reference surfaces NodeManager log URLs per container,
        ``models/JobLog.java:69-80``)."""
        return None

    def stop(self) -> None:
        """Release backend resources."""
