from tony_tpu.cluster.base import Backend, TaskLaunchSpec  # noqa: F401
from tony_tpu.cluster.local import LocalProcessBackend  # noqa: F401
from tony_tpu.cluster.tpu import (  # noqa: F401
    FakeSliceProvisioner, SliceLease, SliceProvisionError, SliceProvisioner,
    StaticSshProvisioner, TpuSliceBackend)
from tony_tpu.cluster.gcloud import (  # noqa: F401
    GcloudSliceLease, GcloudTpuProvisioner, TpuApiClient, TpuApiError)
