"""Cloud TPU API slice provisioner: the framework acquires its own compute.

In the reference, compute acquisition is IN the framework: the AM asks the
YARN ResourceManager for containers (``TaskScheduler.java:101-103``
``addContainerRequest``) and reacts to grants
(``ApplicationMaster.java:1051-1070`` ``onContainersAllocated``). Until now
the TPU analogue was an operator running ``gcloud compute tpus tpu-vm
create`` and pasting IPs into ``tony.slice.hosts`` — the one reference
*role* not yet code. This module closes it the TPU-native way:

- ``TpuApiClient`` — the Cloud TPU v2 REST surface this provisioner speaks
  (create node / poll long-running operation / get node / delete node),
  stdlib HTTP only, bearer auth via ``utils/gcp.GcpBearer`` (explicit
  credential → env token → metadata server) — the same discipline as the
  GCS client (``storage/store.py``), and like it contract-tested against an
  in-process fake API server (``tests/tpu_api_fake_server.py``).
- ``GcloudTpuProvisioner`` — ``SliceProvisioner`` over that client:
  ``acquire(n)`` creates a node, waits for the create operation, polls the
  node to READY, and derives one host channel per ``networkEndpoints``
  entry; ``release`` deletes the node. All-or-nothing holds end-to-end: any
  failure (quota denial, stockout, timeout, endpoint-count mismatch)
  deletes the half-created node and raises ``SliceProvisionError`` — never
  a partial slice.
- ``GcloudSliceLease`` — a lease that also watches the API: preemption and
  suspension flip the node's ``state`` server-side, so ``check()`` (called
  from the backend's poll loop) surfaces a terminal state as host loss on
  every channel. That feeds the EXISTING recovery machinery unchanged —
  tasks report ``HOST_LOST_EXIT``, the coordinator kills the gang and
  starts a retry epoch, ``_ensure_lease`` releases the broken lease
  (deleting the preempted node) and acquires a fresh one — so
  preempt → re-create → resume-from-checkpoint needs no new control flow
  (the analogue of ``onTaskDeemedDead`` → AM reset,
  ``ApplicationMaster.java:1178-1185``).

The slice stays indivisible: one node == one lease == the whole gang
(SURVEY.md §7 hard part (a)); the v2 API's multi-host node IS the atomic
grant, which is why there is no per-container bookkeeping here.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional

from tony_tpu.cluster.tpu import (HostChannel, LocalSimHostChannel,
                                  SliceLease, SliceProvisionError,
                                  SliceProvisioner, SshHostChannel)
from tony_tpu.utils.gcp import GcpBearer, json_request

log = logging.getLogger(__name__)

TPU_API_ENDPOINT_ENV = "TONY_TPU_API_ENDPOINT"
_DEFAULT_ENDPOINT = "https://tpu.googleapis.com"

#: node states that invalidate a lease (the slice cannot come back: spot
#: reclaim, manual stop, deletion). CREATING/REPAIRING are NOT terminal —
#: REPAIRING nodes return to READY and killing the gang for them would turn
#: a maintenance blip into a retry epoch.
TERMINAL_STATES = frozenset({
    "PREEMPTED", "TERMINATED", "STOPPED", "STOPPING", "SUSPENDED",
    "SUSPENDING", "DELETING", "DELETED", "FAILED"})

#: queued-resource states that are a RECLAIM NOTICE: the provider has
#: decided to take the capacity back but the nodes still run — the
#: warning window the fleet daemon's proactive live migration spends
#: moving jobs OFF the doomed slice (fleet/daemon.py ``_poll_reclaim``)
#: instead of absorbing host losses after the reclaim lands.
RECLAIM_NOTICE_STATES = frozenset({"SUSPENDING"})


def reclaim_notices(api: "TpuApiClient") -> List[str]:
    """Queued-resource ids the provider is actively reclaiming — the
    production feed behind the fleet daemon's slice-preemption intake
    (drills use the ``slice.preempt`` fault site instead). A flaky API
    yields no notices, never an exception: a poll hiccup must not read
    as a reclaim."""
    try:
        qrs = api.list_queued_resources()
    except Exception as e:  # noqa: BLE001 — a flaky feed is no notice
        log.debug("queued-resource reclaim poll failed: %s", e)
        return []
    out: List[str] = []
    for qr in qrs:
        state = str((qr.get("state") or {}).get("state", ""))
        if state in RECLAIM_NOTICE_STATES:
            name = str(qr.get("name", "") or "")
            out.append(name.rsplit("/", 1)[-1] or name)
    return sorted(out)


class TpuApiError(RuntimeError):
    """Non-transient Cloud TPU API failure (carries the HTTP code)."""

    def __init__(self, msg: str, code: int = 0):
        super().__init__(msg)
        self.code = code


class TpuApiClient:
    """The slice of the Cloud TPU v2 REST API the provisioner needs.

    Same wire discipline as ``GcsStore._request``: bounded retry with
    backoff on 429/5xx/transport errors, 404 → FileNotFoundError, 401/403
    → one cached-token refresh then ``TpuApiError`` — long jobs must
    survive token expiry between the create and the (hours-later) delete.
    """

    def __init__(self, project: str, zone: str,
                 endpoint: Optional[str] = None,
                 credential: Optional[str] = None,
                 retries: int = 4, backoff_s: float = 1.0,
                 timeout_s: float = 60.0):
        if not project or not zone:
            raise ValueError("TpuApiClient needs a project and a zone")
        self.project = project
        self.zone = zone
        self.endpoint = (endpoint or os.environ.get(TPU_API_ENDPOINT_ENV)
                         or _DEFAULT_ENDPOINT).rstrip("/")
        self._auth = GcpBearer(credential)
        self.retries = retries
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s

    def probe_clone(self) -> "TpuApiClient":
        """A low-latency sibling for health probes: no retries, short
        timeout, SAME auth cache. Control-plane mutations want the full
        retry discipline; a periodic health check running inside the
        coordinator's poll loop must never stall it for minutes on an
        API blip (it tolerates failure anyway — it just returns)."""
        clone = TpuApiClient.__new__(TpuApiClient)
        clone.__dict__.update(self.__dict__)
        clone.retries = 0
        clone.timeout_s = 10.0
        return clone

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        return json_request(method, f"{self.endpoint}/v2/{path}",
                            auth=self._auth, body=body,
                            retries=self.retries, backoff_s=self.backoff_s,
                            timeout_s=self.timeout_s,
                            error_cls=TpuApiError)

    # -- the four calls the provisioner makes --------------------------
    def create_node(self, node_id: str, node_body: dict) -> dict:
        """POST …/nodes?nodeId= → a long-running operation dict."""
        return self._request("POST",
                             f"{self.parent}/nodes?nodeId={node_id}",
                             body=node_body)

    def get_node(self, node_id: str) -> dict:
        return self._request("GET", f"{self.parent}/nodes/{node_id}")

    # -- queued resources (the capacity-queue acquisition path) --------
    def create_queued_resource(self, qr_id: str, body: dict) -> dict:
        return self._request(
            "POST",
            f"{self.parent}/queuedResources?queuedResourceId={qr_id}",
            body=body)

    def get_queued_resource(self, qr_id: str) -> dict:
        return self._request("GET",
                             f"{self.parent}/queuedResources/{qr_id}")

    def delete_queued_resource(self, qr_id: str,
                               force: bool = True) -> dict:
        return self._request(
            "DELETE", f"{self.parent}/queuedResources/{qr_id}"
            + ("?force=true" if force else ""))

    def _list_paged(self, collection: str, item_key: str) -> List[dict]:
        """Paginated zone listing, following ``nextPageToken`` to the end
        (same discipline as the GCS listing — a janitor that only reads
        page 1 'finds no leaks' while billing resources sit on page 2)."""
        from urllib.parse import quote

        items: List[dict] = []
        token = ""
        while True:
            path = f"{self.parent}/{collection}"
            if token:
                path += f"?pageToken={quote(token, safe='')}"
            page = self._request("GET", path)
            items += page.get(item_key, [])
            token = page.get("nextPageToken", "")
            if not token:
                return items

    def list_nodes(self) -> List[dict]:
        """All nodes in the zone (the janitor's view — ``cli gcloud-gc``)."""
        return self._list_paged("nodes", "nodes")

    def list_queued_resources(self) -> List[dict]:
        """All queued resources in the zone — a hard-crashed coordinator
        can leak a WAITING request that later grants and bills."""
        return self._list_paged("queuedResources", "queuedResources")

    def delete_node(self, node_id: str) -> dict:
        return self._request("DELETE", f"{self.parent}/nodes/{node_id}")

    def get_operation(self, op_name: str) -> dict:
        """``op_name`` is the full resource name the API returned
        (``projects/…/locations/…/operations/…``)."""
        return self._request("GET", op_name)

    def wait_operation(self, op: dict, timeout_s: float,
                       interval_s: float) -> dict:
        """Poll a long-running operation to ``done``; raise on op error."""
        deadline = time.monotonic() + timeout_s
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TpuApiError(
                    f"operation {op.get('name')} not done after "
                    f"{timeout_s:.0f}s")
            time.sleep(interval_s)
            op = self.get_operation(op["name"])
        if "error" in op:
            err = op["error"]
            raise TpuApiError(
                f"operation {op.get('name')} failed: "
                f"{err.get('message', err)}", code=int(err.get("code", 0)))
        return op


class GcloudSliceLease(SliceLease):
    """A lease whose health has two sources: the channels (is the VM
    reachable?) and the API (has the cloud taken the node away?)."""

    def __init__(self, slice_id: str, hosts: List[HostChannel],
                 api: TpuApiClient, poll_interval_s: float):
        super().__init__(slice_id, hosts)
        # Health probes ride a no-retry/short-timeout clone so a flaky
        # API endpoint cannot stall the coordinator's poll loop.
        self._api = api.probe_clone()
        self._poll_interval_s = poll_interval_s
        self._last_check = 0.0
        self.terminal_state: Optional[str] = None

    def check(self) -> None:
        """Poll the node state (rate-limited); a terminal state marks every
        host lost, which the backend's normal poll loop then reports as
        ``HOST_LOST_EXIT`` for the tasks on them. Called from
        ``TpuSliceBackend.poll_completions``."""
        if self.terminal_state is not None:
            return
        now = time.monotonic()
        if now - self._last_check < self._poll_interval_s:
            return
        self._last_check = now
        try:
            node = self._api.get_node(self.slice_id)
            state = str(node.get("state", ""))
        except FileNotFoundError:
            state = "DELETED"
        except Exception as e:  # noqa: BLE001
            # A transient API hiccup is not evidence the slice died; the
            # ssh-liveness side of lost_hosts() still stands guard.
            log.debug("node state poll for %s failed: %s", self.slice_id, e)
            return
        if state in TERMINAL_STATES:
            log.warning("node %s entered terminal state %s; marking all "
                        "%d hosts lost", self.slice_id, state,
                        len(self.hosts))
            self.terminal_state = state
            for h in self.hosts:
                h.mark_lost()

    def lost_hosts(self) -> List[HostChannel]:
        self.check()
        return super().lost_hosts()


class GcloudTpuProvisioner(SliceProvisioner):
    """``SliceProvisioner`` over the Cloud TPU API (module docstring).

    ``channel_factory(host_id, endpoint_dict) -> HostChannel`` defaults to
    ssh channels onto the node's internal IPs (TPU VMs in the same VPC —
    the production shape); tests inject ``localsim_channel_factory`` so the
    full create/READY/preempt/delete lifecycle runs against the fake API
    server with real local executors and no hardware."""

    def __init__(self, api: TpuApiClient, accelerator_type: str,
                 runtime_version: str, node_prefix: str = "tony",
                 ssh_user: str = "", remote_python: str = "python3",
                 create_timeout_s: float = 900.0,
                 poll_interval_s: float = 5.0, spot: bool = False,
                 network: str = "", queued: bool = False,
                 channel_factory: Optional[
                     Callable[[str, dict], HostChannel]] = None):
        if not accelerator_type or not runtime_version:
            raise SliceProvisionError(
                "gcloud provisioner needs tony.gcloud.accelerator-type "
                "and tony.gcloud.runtime-version")
        self.api = api
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.node_prefix = node_prefix
        self.ssh_user = ssh_user
        self.remote_python = remote_python
        self.create_timeout_s = create_timeout_s
        self.poll_interval_s = poll_interval_s
        self.spot = spot
        self.network = network
        #: acquire capacity via the queued-resources API instead of a
        #: direct node create — the path real TPU capacity is commonly
        #: granted through (reservations/spot queues): the request WAITS
        #: in the provider's queue until capacity exists, then the node
        #: materializes. tony.gcloud.queued-resource.
        self.queued = queued
        self._channel_factory = channel_factory or self._ssh_channel
        #: node ids this provisioner created and has not yet deleted —
        #: release() only ever deletes its own nodes. Value records the
        #: acquisition mode ("node" | "qr") so release tears down the
        #: right resources.
        self._owned: Dict[str, str] = {}

    # -- channels ------------------------------------------------------
    def _ssh_channel(self, host_id: str, endpoint: dict) -> HostChannel:
        ip = endpoint.get("ipAddress", "")
        access = endpoint.get("accessConfig") or {}
        target = ip or access.get("externalIp", "")
        if self.ssh_user:
            target = f"{self.ssh_user}@{target}"
        return SshHostChannel(host_id=host_id, ssh_target=target,
                              python=self.remote_python)

    # -- SliceProvisioner ----------------------------------------------
    def _node_body(self, nonce: str,
                   include_scheduling: bool = True) -> dict:
        body: dict = {
            "acceleratorType": self.accelerator_type,
            "runtimeVersion": self.runtime_version,
            # The nonce makes THIS create attempt identifiable: a 409
            # whose existing node carries it is our own create with a
            # lost response, not someone else's node (see acquire).
            "labels": {"tony-managed": "true", "tony-nonce": nonce},
        }
        if self.spot and include_scheduling:
            # Direct create only: on the queued path the tier is
            # expressed on the QueuedResource envelope and the API
            # rejects schedulingConfig inside a QR node spec.
            body["schedulingConfig"] = {"preemptible": True}
        if self.network:
            body["networkConfig"] = {"network": self.network}
        return body

    def acquire(self, n_hosts: int, node_pool: str = "") -> SliceLease:
        # ONE deadline for the whole acquire (create op + READY polling)
        # — tony.gcloud.create-timeout-s promises a bound on the sum, not
        # per phase.
        deadline = time.monotonic() + self.create_timeout_s
        if self.queued:
            return self._acquire_queued(n_hosts, deadline)
        node_id = ""
        op: Optional[dict] = None
        last_err: Optional[Exception] = None
        for _ in range(3):
            node_id = f"{self.node_prefix}-{os.urandom(3).hex()}"
            nonce = os.urandom(8).hex()
            try:
                op = self.api.create_node(node_id, self._node_body(nonce))
                break
            except TpuApiError as e:
                if e.code == 409:
                    # Two ways to 409 on a name WE just randomized: our
                    # own create succeeded but its response was lost and
                    # the transport retry hit the existing node, or
                    # another job really holds the name. The per-attempt
                    # nonce label distinguishes them exactly — only OUR
                    # lost create carries this nonce, so a concurrent
                    # tony job's node can never be adopted (and later
                    # deleted) by mistake.
                    if self._probe_is_ours(node_id, nonce):
                        log.warning(
                            "create of %s 409'd but the node is ours "
                            "(lost create response); adopting", node_id)
                        op = None           # no operation left to wait on
                        break
                    last_err = e
                    continue
                raise SliceProvisionError(
                    f"TPU node create denied: {e}") from e
        else:
            raise SliceProvisionError(
                f"could not find a free node name: {last_err}")
        self._owned[node_id] = "node"
        try:
            if op is not None:
                self.api.wait_operation(
                    op, max(0.0, deadline - time.monotonic()),
                    self.poll_interval_s)
            node = self._await_ready(node_id, deadline)
            return self._lease_from_node(node_id, node, n_hosts)
        except BaseException as e:
            # All-or-nothing: never leak a half-created (and billing!)
            # node behind a failed acquire.
            self._delete_quietly(node_id)
            if isinstance(e, SliceProvisionError):
                raise
            raise SliceProvisionError(
                f"TPU node {node_id} did not become READY: {e}") from e

    def _lease_from_node(self, node_id: str, node: dict,
                         n_hosts: int) -> SliceLease:
        endpoints = node.get("networkEndpoints") or []
        if len(endpoints) != n_hosts:
            raise SliceProvisionError(
                f"node {node_id} ({self.accelerator_type}) has "
                f"{len(endpoints)} hosts but the job needs {n_hosts} — "
                f"fix tony.slice.num-hosts or the accelerator type")
        hosts = [self._channel_factory(f"{node_id}-host-{i}", ep)
                 for i, ep in enumerate(endpoints)]
        log.info("leased TPU node %s (%s): %d hosts", node_id,
                 self.accelerator_type, len(hosts))
        return GcloudSliceLease(node_id, hosts, self.api,
                                self.poll_interval_s)

    #: queued-resource states that will never become ACTIVE
    _QR_TERMINAL = frozenset({"FAILED", "SUSPENDED", "SUSPENDING"})

    def _acquire_queued(self, n_hosts: int, deadline: float) -> SliceLease:
        """Capacity via the queued-resources API: the request waits in
        the provider's queue (WAITING_FOR_RESOURCES → PROVISIONING →
        ACTIVE) and the node materializes when granted. Same
        all-or-nothing contract: any failure deletes the queued resource
        (force — taking its half-created node with it)."""
        qr_id = ""
        last_err: Optional[Exception] = None
        for _ in range(3):
            qr_id = f"{self.node_prefix}-{os.urandom(3).hex()}"
            nonce = os.urandom(8).hex()
            body: dict = {"tpu": {"nodeSpec": [{
                "parent": self.api.parent,
                "nodeId": qr_id,
                "node": self._node_body(nonce, include_scheduling=False),
            }]}}
            # Queued-resource tier rides the QR, not schedulingConfig.
            # Plain on-demand omits BOTH tier fields — "guaranteed" means
            # reservation/commitment capacity the project may not hold.
            if self.spot:
                body["spot"] = {}
            try:
                self.api.create_queued_resource(qr_id, body)
                break
            except TpuApiError as e:
                if e.code == 409:
                    # Same lost-response hazard as the direct path: our
                    # create may have landed server-side with the
                    # response dropped, and abandoning that WAITING
                    # request would let it grant and bill a node nobody
                    # owns. The per-attempt nonce distinguishes ours.
                    if self._probe_qr_is_ours(qr_id, nonce):
                        log.warning(
                            "queued-resource create of %s 409'd but the "
                            "request is ours (lost response); adopting",
                            qr_id)
                        break
                    last_err = e    # true collision: new random suffix
                    continue
                raise SliceProvisionError(
                    f"queued-resource create denied: {e}") from e
        else:
            raise SliceProvisionError(
                f"could not find a free queued-resource name: {last_err}")
        self._owned[qr_id] = "qr"
        try:
            self._poll_state(
                fetch=lambda: self.api.get_queued_resource(qr_id),
                state_of=lambda qr: str(
                    (qr.get("state") or {}).get("state", "")),
                ready_state="ACTIVE", terminal=self._QR_TERMINAL,
                deadline=deadline, what=f"queued resource {qr_id}",
                stuck_hint="no capacity granted within the acquire "
                           "budget",
                # Right after create the QR may not be GETtable yet
                # (the create LRO is still materializing it) — a 404
                # within the deadline is "not visible yet", not gone.
                tolerate_missing=True)
            # ACTIVE: the node exists; poll it to READY like the direct
            # path (endpoints appear with READY).
            node = self._await_ready(qr_id, deadline)
            return self._lease_from_node(qr_id, node, n_hosts)
        except BaseException as e:
            self._delete_quietly(qr_id)
            if isinstance(e, SliceProvisionError):
                raise
            raise SliceProvisionError(
                f"queued resource {qr_id} did not become ACTIVE: "
                f"{e}") from e

    def _probe_is_ours(self, node_id: str, nonce: str) -> bool:
        """After a 409 on a name we generated: does the node carry the
        nonce of THIS create attempt? (The lost-create-response case.)"""
        try:
            node = self.api.get_node(node_id)
        except Exception:  # noqa: BLE001 — can't tell: treat as not ours
            return False
        return node.get("labels", {}).get("tony-nonce") == nonce

    def _probe_qr_is_ours(self, qr_id: str, nonce: str) -> bool:
        """QR flavor of the lost-create-response probe: the nonce lives
        in the queued resource's embedded node spec labels."""
        try:
            qr = self.api.get_queued_resource(qr_id)
        except Exception:  # noqa: BLE001 — can't tell: treat as not ours
            return False
        specs = (qr.get("tpu") or {}).get("nodeSpec") or []
        for spec in specs:
            labels = (spec.get("node") or {}).get("labels") or {}
            if labels.get("tony-nonce") == nonce:
                return True
        return False

    def _poll_state(self, fetch, state_of, ready_state: str,
                    terminal: frozenset, deadline: float, what: str,
                    stuck_hint: str = "",
                    tolerate_missing: bool = False) -> dict:
        """ONE poll-until-ready-or-terminal-or-deadline loop for both
        resource kinds (node READY, queued resource ACTIVE) — two copies
        of the deadline/terminal semantics would drift."""
        while True:
            state = ""
            try:
                res = fetch()
                state = state_of(res)
                if state == ready_state:
                    return res
                if state in terminal:
                    raise SliceProvisionError(
                        f"{what} became {state} while waiting for "
                        f"{ready_state}")
            except FileNotFoundError:
                if not tolerate_missing:
                    raise
                state = "(not yet visible)"
            if time.monotonic() > deadline:
                raise SliceProvisionError(
                    f"{what} still {state or '?'} after "
                    f"{self.create_timeout_s:.0f}s"
                    + (f" — {stuck_hint}" if stuck_hint else ""))
            time.sleep(self.poll_interval_s)

    def _await_ready(self, node_id: str, deadline: float) -> dict:
        """The create op finishing does not mean the node is usable —
        poll the node itself to READY (the API may report CREATING for a
        while after, and endpoints appear only when READY). ``deadline``
        is the acquire-wide monotonic bound."""
        return self._poll_state(
            fetch=lambda: self.api.get_node(node_id),
            state_of=lambda n: str(n.get("state", "")),
            ready_state="READY", terminal=TERMINAL_STATES,
            deadline=deadline, what=f"node {node_id}",
            stuck_hint="stockout/preempt during create")

    def _delete_quietly(self, node_id: str) -> None:
        mode = self._owned.get(node_id, "node")
        try:
            if mode == "qr":
                # force=true takes the queued resource AND its node in
                # one call, whatever state the grant reached.
                op = self.api.delete_queued_resource(node_id, force=True)
            else:
                op = self.api.delete_node(node_id)
            self.api.wait_operation(op, timeout_s=120,
                                    interval_s=self.poll_interval_s)
        except FileNotFoundError:
            pass                        # already gone
        except Exception as e:  # noqa: BLE001
            log.warning("best-effort delete of %s %s failed: %s",
                        mode, node_id, e)
        finally:
            self._owned.pop(node_id, None)

    def release(self, lease: SliceLease) -> None:
        if lease.slice_id not in self._owned:
            log.warning("release of unknown lease %s ignored",
                        lease.slice_id)
            return
        log.info("deleting TPU node %s", lease.slice_id)
        self._delete_quietly(lease.slice_id)


def localsim_channel_factory(workroot: str
                             ) -> Callable[[str, dict], HostChannel]:
    """Test-substrate channels for the gcloud provisioner: each endpoint
    the (fake) API reports becomes a LocalSimHostChannel, so the whole
    create → READY → run → preempt → delete lifecycle is e2e-testable with
    real executors and no cloud (``tony.gcloud.channel=localsim``)."""
    def factory(host_id: str, endpoint: dict) -> HostChannel:
        return LocalSimHostChannel(host_id, os.path.join(workroot, host_id))
    return factory
