"""TPU-slice backend: gang launch over leased multi-host slices.

The reference acquires compute incrementally — YARN grants containers one
callback at a time (``RMCallbackHandler.onContainersAllocated``
``ApplicationMaster.java:1051-1070``) and each is launched on its
NodeManager (``ContainerLauncher.run`` :1108-1175). A TPU pod slice is NOT
incremental: the interconnect topology makes a slice indivisible, so the
cluster substrate here is a **lease**: a provisioner grants a whole slice
(all hosts) or nothing (SURVEY.md §7 hard part (a)), and losing any host
invalidates the lease — the whole gang fails and the coordinator's existing
failure policy / whole-job retry takes over (the analogue of
``onTaskDeemedDead`` → AM reset, ``ApplicationMaster.java:1178-1185``,
:559-575).

Three layers:

- ``HostChannel`` — exec/kill/poll on one TPU VM. ``SshHostChannel`` is the
  production shape (plain ssh; TPU VMs are reachable hosts, no cluster
  manager needed). ``LocalSimHostChannel`` runs the same contract as local
  subprocesses so the full gang-over-hosts path is e2e-testable on one
  machine (the MiniCluster role, ``tony-mini/.../MiniCluster.java:43-63``).
- ``SliceProvisioner`` — ``acquire(n_hosts)`` → all-or-nothing
  ``SliceLease``. ``StaticSshProvisioner`` leases from a fixed host list;
  ``FakeSliceProvisioner`` simulates an inventory, including host **loss**
  mid-job (``fail_host``) and capacity denial, for the fault e2e matrix.
- ``TpuSliceBackend`` — the ``Backend`` implementation: leases on first
  launch, places tasks round-robin over the slice's hosts, surfaces host
  loss as synthetic exit codes for every task on the lost host.

Env contract exported per slice task (the analogue of the reference wiring
each framework's rendezvous env, ``TaskExecutor.java:161-207``):

- ``TONY_HOST_ID`` / ``TONY_HOST_LOCAL_ORDINAL`` — which slice host this
  task landed on, and its per-host ordinal.
- ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` — libtpu's multi-host
  topology contract (worker index within the slice + the full host list),
  derived from the lease. On real Cloud TPU VMs libtpu can also discover
  these from the metadata server; exporting them makes the slice
  self-describing where the MDS is absent (custom pools, tunnels). User
  env wins: both are set only if the job didn't set them itself.

JAX *process* rendezvous (``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``) is NOT a backend concern: it
rides the coordinator's gang barrier and is exported by the JaxRuntime
after registration (``runtimes/frameworks.py``), exactly because the
rendezvous ports don't exist yet at launch time.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from tony_tpu.devtools import sanitizer
from tony_tpu.utils import durable
from tony_tpu import constants
from tony_tpu.cluster.base import (Backend, TaskLaunchSpec,
                                   build_executor_argv)

log = logging.getLogger(__name__)

# Exit code reported for tasks whose HOST died under them (distinct from
# any user exit so failure policy/logs can tell "your code crashed" from
# "the machine went away"). 128+SIGKILL by convention.
HOST_LOST_EXIT = 137


class HostChannel:
    """Exec/kill/poll on one host of a slice."""

    host_id: str

    @property
    def address(self) -> str:
        """Hostname/IP peers on the slice can reach this host at (feeds
        TPU_WORKER_HOSTNAMES). Default: the host id."""
        return self.host_id

    def exec_task(self, task_id: str, argv: Sequence[str],
                  env: Dict[str, str], workdir: str) -> object:
        raise NotImplementedError

    def kill(self, handle: object, grace_s: float = 0.0) -> None:
        raise NotImplementedError

    def poll(self, handle: object) -> Optional[int]:
        """Exit code if the task finished, else None."""
        raise NotImplementedError

    def alive(self) -> bool:
        """Is the host itself still reachable?"""
        return not getattr(self, "_forced_lost", False)

    def mark_lost(self) -> None:
        """An outside authority (the cloud API reporting the node
        PREEMPTED/DELETED — cluster/gcloud.py) declares this host gone:
        ``alive()`` goes False without waiting for a probe to time out."""
        self._forced_lost = True

    def log_paths(self, handle: object) -> Optional[Tuple[str, str]]:
        return None

    def fetch_logs(self, handle: object) -> None:
        """Pull the task's stdout/stderr to the coordinator's machine if
        they live remotely — a no-op where ``log_paths`` already points at
        local files. Called by the backend when a task completes or is
        killed, BEFORE the TASK_FINISHED event snapshots the paths, so
        `tony-tpu logs` / the portal read real content instead of paths
        stranded on a TPU VM (the reference surfaces NodeManager log URLs
        per container, ``models/JobLog.java:69-80``,
        ``util/Utils.java:215-230``; with no NM, the coordinator fetches)."""


class LocalSimHostChannel(HostChannel):
    """A 'host' that is really a local process group — same contract as a
    remote TPU VM, minus the network. Used by FakeSliceProvisioner."""

    def __init__(self, host_id: str, workroot: str):
        self.host_id = host_id
        self.workroot = workroot
        self._alive = True
        self._handles: List[dict] = []
        self._lock = threading.Lock()

    def exec_task(self, task_id, argv, env, workdir):
        os.makedirs(workdir, exist_ok=True)
        full_env = dict(os.environ)
        full_env.update(env)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        full_env["PYTHONPATH"] = (repo_root + os.pathsep
                                  + full_env.get("PYTHONPATH", "")
                                  ).rstrip(os.pathsep)
        stdout = open(os.path.join(workdir, "stdout.log"), "ab")
        stderr = open(os.path.join(workdir, "stderr.log"), "ab")
        popen = subprocess.Popen(
            list(argv), cwd=workdir, env=full_env, stdout=stdout,
            stderr=stderr, start_new_session=True)
        handle = {"popen": popen, "workdir": workdir}
        with self._lock:
            self._handles.append(handle)
        return handle

    @staticmethod
    def _task_groups(handle) -> List[int]:
        """Process groups of one task: the executor's (while alive), plus
        the user command's own session read from the pgid file the executor
        wrote (constants.USER_PGID_FILE) — the only route to the user tree
        once the executor is gone."""
        from tony_tpu import constants
        from tony_tpu.utils.proc import read_pgid_file

        popen = handle["popen"]
        groups = [popen.pid] if popen.poll() is None else []
        user_pgid = read_pgid_file(
            os.path.join(handle["workdir"], constants.USER_PGID_FILE))
        if user_pgid:
            groups.append(user_pgid)
        return groups

    def kill(self, handle, grace_s: float = 0.0) -> None:
        from tony_tpu.utils.proc import kill_process_groups

        kill_process_groups(self._task_groups(handle), grace_s=grace_s)

    def poll(self, handle) -> Optional[int]:
        # A task that FINISHED before the host died keeps its real exit
        # code (a real channel has the buffered status too) — only
        # still-running tasks are converted to host-lost.
        rc = handle["popen"].poll()
        if rc is not None:
            return 128 - rc if rc < 0 else rc
        if not self._alive:
            return HOST_LOST_EXIT
        return None

    def alive(self) -> bool:
        return self._alive

    def mark_lost(self) -> None:
        # For a sim host, "the cloud reclaimed the VM" means its
        # processes die too.
        self.simulate_loss()

    def log_paths(self, handle):
        wd = handle["workdir"]
        return (os.path.join(wd, "stdout.log"),
                os.path.join(wd, "stderr.log"))

    def simulate_loss(self) -> None:
        """The host 'disappears': every process on it — executor AND its
        user session — dies instantly and the channel reports dead."""
        self._alive = False
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            for pg in self._task_groups(h):
                try:
                    os.killpg(pg, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


class SshHostChannel(HostChannel):
    """Run executors on a remote TPU VM over plain ssh.

    The remote command writes its process-group id to ``<workdir>/task.pid``
    so kill() can signal the group from a second ssh exec; ssh itself exits
    with the remote command's code (255 = ssh transport failure = host
    loss). Assumes the job bundle is reachable from the VM (a shared
    filesystem or the remote store — ``tony_tpu.storage``)."""

    def __init__(self, host_id: str, ssh_target: str,
                 ssh_args: Optional[List[str]] = None,
                 python: str = "python3"):
        self.host_id = host_id
        self.ssh_target = ssh_target
        self.ssh_args = list(ssh_args or
                             ["-o", "BatchMode=yes",
                              "-o", "ConnectTimeout=10",
                              "-o", "StrictHostKeyChecking=accept-new",
                              # A suspended/reclaimed VM drops packets
                              # silently; without keepalives an ESTABLISHED
                              # connection (the exec_task channel) can hang
                              # in TCP timeout for many minutes. 15s×4 ≈
                              # a 60s organic detection bound even when no
                              # cloud API reports the loss.
                              "-o", "ServerAliveInterval=15",
                              "-o", "ServerAliveCountMax=4"])
        self.python = python
        self._alive_cache: Optional[Tuple[float, bool]] = None

    @property
    def address(self) -> str:
        # ssh targets may carry a login user; peers need the bare host.
        return self.ssh_target.rsplit("@", 1)[-1]

    def _ssh(self, remote_cmd: str, **popen_kw) -> subprocess.Popen:
        return subprocess.Popen(
            ["ssh", *self.ssh_args, self.ssh_target, remote_cmd],
            **popen_kw)

    def exec_task(self, task_id, argv, env, workdir):
        exports = " ".join(
            f"export {k}={shlex.quote(v)};" for k, v in env.items())
        cmd = " ".join(shlex.quote(a) for a in argv)
        remote = (
            f"mkdir -p {shlex.quote(workdir)} && cd {shlex.quote(workdir)} "
            f"&& echo $$ > task.pid && {exports} exec {cmd} "
            f"> stdout.log 2> stderr.log")
        popen = self._ssh(remote)
        container = ""
        if argv and argv[0] == "docker" and "--name" in argv:
            container = argv[argv.index("--name") + 1]
        return {"popen": popen, "workdir": workdir, "container": container}

    def kill(self, handle, grace_s: float = 0.0) -> None:
        wd = shlex.quote(handle["workdir"])
        if handle.get("container"):
            # Stop the container by name first: signalling the docker-run
            # client's process group does not reach containerd's child.
            # `docker stop -t` = TERM, grace, then KILL (kill_task's
            # escalation contract; bare `docker kill` is instant SIGKILL).
            k = self._ssh(f"docker stop -t {max(0, int(grace_s))} "
                          f"{shlex.quote(handle['container'])} "
                          f">/dev/null 2>&1 || true",
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL)
            try:
                k.wait(timeout=15 + grace_s)
            except subprocess.TimeoutExpired:
                k.kill()
        # Two groups per task: the remote executor's (task.pid, written by
        # the launch wrapper) and — for non-containerized tasks — the user
        # command's own session (user.pgid, written by the executor; the
        # only route to the user tree if the executor already died). A
        # container's user.pgid is a pid in the container's namespace and
        # must NOT be signalled on the host; docker stop above reaps it.
        files = "task.pid" if handle.get("container") else "task.pid user.pgid"
        for sig in ("TERM", "KILL"):
            k = self._ssh(
                f"for f in {files}; do "
                f"test -f {wd}/$f && kill -{sig} -$(cat {wd}/$f); "
                f"done 2>/dev/null; true",
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            try:
                k.wait(timeout=15)
            except subprocess.TimeoutExpired:
                k.kill()
            if sig == "TERM":
                # Grace window; the local ssh client exiting early just
                # shortens the wait. The KILL rung always runs: the
                # executor's ssh client being gone says nothing about the
                # USER group (the dead-executor case is exactly when the
                # pgid file matters), and KILL on dead groups is a no-op.
                deadline = time.monotonic() + grace_s
                while (time.monotonic() < deadline
                       and handle["popen"].poll() is None):
                    time.sleep(0.1)

    def poll(self, handle) -> Optional[int]:
        rc = handle["popen"].poll()
        if rc is None:
            if getattr(self, "_forced_lost", False):
                # The cloud API declared the VM gone (lease check). The
                # local ssh client may take minutes of TCP timeout to
                # notice (a SUSPENDED VM drops packets silently); tasks
                # on this host are lost NOW — waiting would wedge
                # gang_active() and block the re-lease. Kill the local
                # client too: the task is terminal after this report, so
                # nothing else would ever reap the hung ssh process.
                handle["popen"].kill()
                try:
                    handle["popen"].wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
                return HOST_LOST_EXIT
            return None
        if rc == 255:
            # ssh reports ITS OWN failures as 255, but a remote command
            # exiting 255 looks identical. Disambiguate with a FRESH
            # liveness probe (the cache may be seconds old — exactly the
            # window in which a preempted host died): reachable host →
            # the user code really exited 255.
            self._alive_cache = None
            return 255 if self.alive() else HOST_LOST_EXIT
        return 128 - rc if rc < 0 else rc

    #: bound on fetched log size per stream — TASK_FINISHED wants tails
    #: for diagnosis, not multi-GB training stdout over the control plane
    LOG_TAIL_BYTES = 1024 * 1024

    def fetch_logs(self, handle) -> None:
        # One fetch at a time per handle: completion, kill and stop hooks
        # can race (e.g. a fetch thread abandoned by a join timeout vs a
        # later retry), and two writers interleaving into the same
        # .fetch-tmp would corrupt the very file the atomic-replace
        # protects. dict.setdefault is atomic under the GIL. io_lock:
        # this lock EXISTS to hold across the blocking scp/ssh fetch —
        # only fetchers of the same handle contend — so the lock
        # sanitizer's hold-while-blocking check does not apply.
        with handle.setdefault("fetch_lock", sanitizer.io_lock()):
            self._fetch_logs_locked(handle)

    def _fetch_logs_locked(self, handle) -> None:
        if handle.get("logs_fetched"):
            return
        if not self.alive():
            # The VM is gone (preemption/suspend) and its disk with it;
            # paying ssh connect timeouts per stream would stall the
            # coordinator's completion loop for nothing.
            return
        wd = handle["workdir"]
        os.makedirs(wd, exist_ok=True)   # local mirror of the remote path
        # Both streams fetch CONCURRENTLY (launch all, then wait): this
        # runs inside the coordinator's poll loop, where serial 30 s ssh
        # round trips would stall completion processing for the gang.
        procs = []
        for name in ("stdout.log", "stderr.log"):
            local = os.path.join(wd, name)
            # Download to a temp file, then atomically replace: on a
            # shared filesystem (or the stub-ssh test substrate) the
            # "remote" file IS this local path, and opening it for write
            # before tail reads it would truncate the very content being
            # fetched.
            tmp = local + ".fetch-tmp"
            f = None
            try:
                f = open(tmp, "wb")
                p = self._ssh(
                    f"tail -c {self.LOG_TAIL_BYTES} "
                    f"{shlex.quote(wd)}/{name} 2>/dev/null || true",
                    stdout=f, stderr=subprocess.DEVNULL)
                procs.append((name, local, tmp, f, p))
            except OSError as e:
                log.warning("could not fetch %s from %s: %s", name,
                            self.host_id, e)
                if f is not None:       # Popen failed after open: no leak
                    f.close()
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        all_ok = len(procs) == 2
        for name, local, tmp, f, p in procs:
            ok = False
            try:
                ok = p.wait(timeout=15) == 0
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=5)    # reap — no zombie per timeout
                except subprocess.TimeoutExpired:
                    pass
            f.close()
            # Replace only on a CLEAN fetch: a transport failure (255)
            # or timeout leaves tmp empty/partial, and on a shared
            # filesystem `local` IS the authoritative file — clobbering
            # it with a bad fetch would destroy the log.
            if ok:
                try:
                    durable.fsync_path(tmp)
                    durable.durable_replace(tmp, local)
                except OSError:
                    ok = False
            if not ok:
                all_ok = False
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        if all_ok:
            # Only a fully-clean fetch is final; a transient ssh failure
            # stays retryable (the next completion/kill hook retries).
            handle["logs_fetched"] = True

    def log_paths(self, handle) -> Optional[Tuple[str, str]]:
        """The FETCHED copies (fetch_logs), which mirror the remote
        workdir path locally; None until a fetch produced content."""
        wd = handle["workdir"]
        out = os.path.join(wd, "stdout.log")
        err = os.path.join(wd, "stderr.log")
        if os.path.isfile(out) or os.path.isfile(err):
            return (out, err)
        return None

    def alive(self) -> bool:
        if getattr(self, "_forced_lost", False):
            return False    # the cloud API already said the VM is gone
        # A real ssh probe per call would serialize 15 s round trips into
        # every launch (lost_hosts() runs before each one) — cache for 5 s.
        now = time.monotonic()
        if self._alive_cache is not None and now - self._alive_cache[0] < 5:
            return self._alive_cache[1]
        probe = self._ssh("true", stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL)
        try:
            ok = probe.wait(timeout=15) == 0
        except subprocess.TimeoutExpired:
            probe.kill()
            ok = False
        self._alive_cache = (now, ok)
        return ok


class SliceLease:
    """An atomic grant of a whole slice: every host or none."""

    def __init__(self, slice_id: str, hosts: List[HostChannel]):
        self.slice_id = slice_id
        self.hosts = hosts

    def lost_hosts(self) -> List[HostChannel]:
        return [h for h in self.hosts if not h.alive()]


class SliceProvisionError(RuntimeError):
    """The provisioner cannot grant the requested slice."""


class SliceProvisioner:
    def acquire(self, n_hosts: int, node_pool: str = "") -> SliceLease:
        """Grant a slice of ``n_hosts`` hosts atomically, or raise
        SliceProvisionError. Never returns a partial slice."""
        raise NotImplementedError

    def release(self, lease: SliceLease) -> None:
        raise NotImplementedError


class StaticSshProvisioner(SliceProvisioner):
    """Leases from a fixed inventory of ssh-reachable TPU VMs (the
    operator's host list — e.g. the VMs of one pre-created pod slice)."""

    def __init__(self, ssh_targets: List[str], python: str = "python3"):
        self.targets = list(ssh_targets)
        self.python = python
        self._leased: Dict[str, List[str]] = {}
        self._lock = threading.Lock()
        self._n = 0

    def acquire(self, n_hosts: int, node_pool: str = "") -> SliceLease:
        with self._lock:
            used = {t for ts in self._leased.values() for t in ts}
            free = [t for t in self.targets if t not in used]
            if len(free) < n_hosts:
                raise SliceProvisionError(
                    f"need {n_hosts} hosts, only {len(free)} of "
                    f"{len(self.targets)} free")
            grant = free[:n_hosts]
            self._n += 1
            slice_id = f"slice-{self._n}"
            self._leased[slice_id] = grant
        hosts: List[HostChannel] = [
            SshHostChannel(host_id=t, ssh_target=t, python=self.python)
            for t in grant]
        return SliceLease(slice_id, hosts)

    def release(self, lease: SliceLease) -> None:
        with self._lock:
            self._leased.pop(lease.slice_id, None)


class FakeSliceProvisioner(SliceProvisioner):
    """In-memory slice inventory over LocalSimHostChannels: the test double
    that lets the gang-over-hosts path (grant, placement, host loss,
    capacity denial) run e2e with REAL executors and no hardware."""

    def __init__(self, n_hosts: int, workroot: str):
        self.workroot = workroot
        self._hosts = {
            f"fakehost-{i}": LocalSimHostChannel(
                f"fakehost-{i}", os.path.join(workroot, f"fakehost-{i}"))
            for i in range(n_hosts)}
        self._leased: Dict[str, List[str]] = {}
        self._lock = threading.Lock()
        self._n = 0

    def acquire(self, n_hosts: int, node_pool: str = "") -> SliceLease:
        with self._lock:
            used = {h for hs in self._leased.values() for h in hs}
            free = [h for h, ch in self._hosts.items()
                    if h not in used and ch.alive()]
            if len(free) < n_hosts:
                raise SliceProvisionError(
                    f"need {n_hosts} hosts, only {len(free)} healthy/free")
            grant = free[:n_hosts]
            self._n += 1
            slice_id = f"fakeslice-{self._n}"
            self._leased[slice_id] = grant
            return SliceLease(slice_id, [self._hosts[h] for h in grant])

    def release(self, lease: SliceLease) -> None:
        with self._lock:
            self._leased.pop(lease.slice_id, None)

    def fail_host(self, host_id: str) -> None:
        """Simulate sudden host loss (preemption / hardware failure)."""
        self._hosts[host_id].simulate_loss()


class _SliceTask:
    def __init__(self, spec: TaskLaunchSpec, host: HostChannel,
                 handle: object):
        self.spec = spec
        self.host = host
        self.handle = handle
        self.reported = False


class TpuSliceBackend(Backend):
    """Gang launch over a leased TPU slice (see module docstring).

    The lease is acquired lazily at the first ``launch_task`` — the
    coordinator launches gangs task-by-task, and the all-or-nothing
    semantics live in ``SliceProvisioner.acquire``. Host loss is detected
    on ``poll_completions`` (dead channel → every task on that host reports
    ``HOST_LOST_EXIT``), feeding the coordinator's normal chief/worker
    failure policy and whole-job retry."""

    def __init__(self, provisioner: SliceProvisioner, n_hosts: int,
                 workdir: str, python: str = sys.executable,
                 node_pool: str = ""):
        self.provisioner = provisioner
        self.n_hosts = n_hosts
        self.workdir = workdir
        self.python = python
        self.node_pool = node_pool
        self.lease: Optional[SliceLease] = None
        self._tasks: Dict[str, _SliceTask] = {}
        self._next_host = 0
        self._host_tasks: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._test_fail_done = False
        self._last_launch = 0.0
        # task_id → failure-domain hint for completions this backend
        # attributed to the MACHINE rather than the task (host loss =
        # preemption; see Backend.completion_domain).
        self._domains: Dict[str, str] = {}

    # -- lease ---------------------------------------------------------
    def gang_active(self) -> bool:
        """Any launched task still running on a live host of the current
        lease? (Terminal = already reported, or poll() returns a code.)"""
        with self._lock:
            tasks = list(self._tasks.values())
        return any(not st.reported and st.host.poll(st.handle) is None
                   for st in tasks)

    _gang_active = gang_active   # internal alias (used by _ensure_lease)

    def _ensure_lease(self) -> SliceLease:
        if self.lease is not None and self.lease.lost_hosts():
            # A slice with a dead host is invalid as a whole (the ICI mesh
            # is broken) — release it and lease a fresh one. Only legal
            # once the old gang is fully down (the retry-epoch path: the
            # coordinator killed the gang and is relaunching, reference
            # reset :559-575). Re-leasing mid-gang would split the gang
            # across slices and double-book the old lease's healthy hosts.
            if self._gang_active():
                raise SliceProvisionError(
                    f"lease {self.lease.slice_id} lost hosts "
                    f"{[h.host_id for h in self.lease.lost_hosts()]} while "
                    f"its gang is still running — kill the gang first")
            log.warning("lease %s lost hosts %s; re-leasing",
                        self.lease.slice_id,
                        [h.host_id for h in self.lease.lost_hosts()])
            self.provisioner.release(self.lease)
            self.lease = None
        if self.lease is None:
            self.lease = self.provisioner.acquire(self.n_hosts,
                                                  self.node_pool)
            with self._lock:
                self._next_host = 0
                # Reset per-slice-host ordinals only: the coordinator-host
                # counter tracks tasks that outlive slice re-leases.
                self._host_tasks = {
                    k: v for k, v in self._host_tasks.items()
                    if k == "coordinator-host"}
            log.info("leased %s: hosts=%s", self.lease.slice_id,
                     [h.host_id for h in self.lease.hosts])
        return self.lease

    def _maybe_test_fail_host(self) -> None:
        """TEST_SLICE_FAIL_HOST hook (see constants.py): once per job, kill
        the named fake host. Bare ``host`` form: a short post-launch delay.
        ``host#<glob>`` form: only once the glob matches an existing path —
        condition-triggered, so "preempt AFTER the first checkpoint is
        durable" is deterministic instead of a race against the victim's
        startup (a 0.7 s timer loses to a JAX import every time)."""
        import glob as globmod

        from tony_tpu import constants
        target = os.environ.get(constants.TEST_SLICE_FAIL_HOST, "")
        if not target or self._test_fail_done or self.lease is None:
            return
        if not self._tasks:
            return
        target, _, condition = target.partition("#")
        if condition:
            if not globmod.glob(condition):
                return
        elif time.monotonic() - self._last_launch < 0.7:
            return
        for h in self.lease.hosts:
            if h.host_id == target and hasattr(h, "simulate_loss"):
                log.warning("TEST hook: simulating loss of host %s", target)
                h.simulate_loss()
                self._test_fail_done = True
                return

    # -- Backend -------------------------------------------------------
    def _coordinator_host(self) -> HostChannel:
        """Lazy local channel for ``tony.<job>.node-pool=coordinator``
        jobtypes: ps/db-style CPU tasks run on the coordinator's machine
        instead of occupying a TPU VM — SURVEY.md §7 hard part (d),
        heterogeneous gangs on infrastructure that wants homogeneous
        slices. They share the rendezvous/heartbeat plane with the slice
        tasks unchanged (the cluster spec doesn't care where a host is)."""
        with self._lock:
            if not hasattr(self, "_coord_channel"):
                self._coord_channel = LocalSimHostChannel(
                    "coordinator-host", os.path.join(self.workdir,
                                                     "coordinator-host"))
            return self._coord_channel

    def launch_task(self, spec: TaskLaunchSpec) -> object:
        if spec.node_pool and spec.node_pool != "coordinator":
            # Per-job pools other than the reserved "coordinator" are not
            # routed by this backend (slice selection is a lease-level
            # concern) — say so instead of silently parking a CPU task on
            # a TPU VM after a typo like "Coordinator".
            log.warning(
                "tony.%s.node-pool=%r has no effect on the tpu-slice "
                "backend (only 'coordinator' is special); %s will run on "
                "a slice host", spec.job_name, spec.node_pool,
                spec.task_id)
        if spec.node_pool == "coordinator":
            host = self._coordinator_host()
            with self._lock:
                local_ordinal = self._host_tasks.get(host.host_id, 0)
                self._host_tasks[host.host_id] = local_ordinal + 1
            return self._exec_on(host, spec, local_ordinal,
                                 python=self.python)
        lease = self._ensure_lease()
        with self._lock:
            # Round-robin, skipping hosts the coordinator excluded
            # (exclude-on-retry: this task already failed there). Only
            # best-effort — with every lease host excluded the plain
            # rotation wins; a relaunch beats no launch.
            host = lease.hosts[self._next_host % len(lease.hosts)]
            if spec.exclude_hosts and len(lease.hosts) > 1:
                excluded = set(spec.exclude_hosts)
                if not excluded.issuperset(
                        h.host_id for h in lease.hosts):
                    while host.host_id in excluded:
                        self._next_host += 1
                        host = lease.hosts[
                            self._next_host % len(lease.hosts)]
            self._next_host += 1
            local_ordinal = self._host_tasks.get(host.host_id, 0)
            self._host_tasks[host.host_id] = local_ordinal + 1
        # A channel that knows its host's interpreter (ssh: the remote
        # VM's python, tony.slice.remote-python) wins over the
        # coordinator-local default — sys.executable is a path on THIS
        # machine and means nothing on a TPU VM.
        python = getattr(host, "python", None) or self.python
        return self._exec_on(host, spec, local_ordinal, python=python,
                             lease=lease)

    def _exec_on(self, host: HostChannel, spec: TaskLaunchSpec,
                 local_ordinal: int, python: str,
                 lease: Optional[SliceLease] = None) -> "_SliceTask":
        env = dict(spec.env)
        env[constants.HOST_ID_ENV] = host.host_id
        env["TONY_HOST_LOCAL_ORDINAL"] = str(local_ordinal)
        if lease is not None:
            # libtpu multi-host topology (see module docstring); job env
            # wins when the user wired it explicitly.
            env.setdefault("TPU_WORKER_ID",
                           str(lease.hosts.index(host)))
            env.setdefault("TPU_WORKER_HOSTNAMES",
                           ",".join(h.address for h in lease.hosts))
        spec.env = env          # the spec records what actually ran
        workdir = os.path.join(self.workdir, host.host_id,
                               spec.task_id.replace(":", "_"))
        handle = host.exec_task(
            spec.task_id, build_executor_argv(python, spec, workdir),
            env, workdir)
        st = _SliceTask(spec, host, handle)
        with self._lock:
            self._tasks[spec.task_id] = st
            # A relaunched task (retry epoch) must not inherit the old
            # epoch's host-loss attribution.
            self._domains.pop(spec.task_id, None)
        self._last_launch = time.monotonic()
        log.info("launched %s on %s", spec.task_id, host.host_id)
        return st

    def host_of(self, task_id: str) -> Optional[str]:
        with self._lock:
            st = self._tasks.get(task_id)
        return st.host.host_id if st is not None else None

    def kill_task(self, handle: object, grace_s: float = 0.0) -> None:
        if isinstance(handle, _SliceTask):
            handle.host.kill(handle.handle, grace_s=grace_s)
            # A force-killed job's logs are the diagnosis artifact; pull
            # them while the host (and lease) still exist.
            handle.host.fetch_logs(handle.handle)

    def poll_completions(self) -> List[Tuple[str, int]]:
        self._maybe_test_fail_host()
        if self.lease is not None and hasattr(self.lease, "check"):
            # Leases with an external health authority (the Cloud TPU API:
            # preemption flips the node state server-side) get it consulted
            # on the same cadence as task polling; a terminal state marks
            # every host lost and the loop below reports the tasks.
            self.lease.check()
        done: List[Tuple[str, int]] = []
        newly_done: List[_SliceTask] = []
        with self._lock:
            tasks = list(self._tasks.values())
        for st in tasks:
            if st.reported:
                continue
            rc = st.host.poll(st.handle)
            if rc is not None:
                st.reported = True
                if rc == HOST_LOST_EXIT and not st.host.alive():
                    log.warning("host %s lost; %s reported exit %d",
                                st.host.host_id, st.spec.task_id, rc)
                    # The MACHINE died, not the task: classify as
                    # PREEMPTION so the coordinator's free-retry budget
                    # applies (Backend.completion_domain contract).
                    with self._lock:
                        self._domains[st.spec.task_id] = "PREEMPTION"
                newly_done.append(st)
                done.append((st.spec.task_id, rc))
        # Bring remote stdout/stderr home BEFORE the coordinator snapshots
        # log paths into TASK_FINISHED (no-op for local channels; skipped
        # for dead hosts) — one thread per task so a whole gang finishing
        # in one poll cycle pays one fetch latency, not N.
        if len(newly_done) > 1:
            fetchers = [threading.Thread(target=st.host.fetch_logs,
                                         args=(st.handle,), daemon=True)
                        for st in newly_done]
            for t in fetchers:
                t.start()
            for t in fetchers:
                t.join(timeout=30)
        elif newly_done:
            newly_done[0].host.fetch_logs(newly_done[0].handle)
        return done

    def task_log_paths(self, task_id: str) -> Optional[Tuple[str, str]]:
        with self._lock:
            st = self._tasks.get(task_id)
        if st is None:
            return None
        return st.host.log_paths(st.handle)

    def completion_domain(self, task_id: str) -> Optional[str]:
        with self._lock:
            return self._domains.get(task_id)

    def stop(self) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
        for st in tasks:
            if st.host.alive():
                st.host.kill(st.handle, grace_s=0.5)
                st.host.fetch_logs(st.handle)
        if self.lease is not None:
            self.provisioner.release(self.lease)
            self.lease = None
