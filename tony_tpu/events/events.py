"""Asynchronous structured event stream for job history.

Reference model: ``events/EventHandler.java`` (157 LoC) — a BlockingQueue
drained by a writer thread into an Avro container file named
``<appId>-<start>[-<end>]-<user>[-STATUS].jhist`` under the job's history
directory, written as ``.inprogress`` and renamed on completion
(:43-60, :98-113, :126-135). Event types are APPLICATION_INITED,
APPLICATION_FINISHED, TASK_STARTED, TASK_FINISHED (``avro/EventType.avsc``).

This build uses JSON-lines instead of Avro (self-describing, greppable, no
schema compiler) with the same lifecycle: queue → writer thread → in-progress
file → atomic rename to final name carrying end-time and status.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional


class EventType(str, enum.Enum):
    APPLICATION_INITED = "APPLICATION_INITED"
    APPLICATION_FINISHED = "APPLICATION_FINISHED"
    TASK_STARTED = "TASK_STARTED"
    TASK_FINISHED = "TASK_FINISHED"
    # A coordinator restarted with --recover re-adopted this job mid-run
    # (coordinator/journal.py); payload carries the new generation and
    # the tasks awaiting re-registration. No reference analogue — the AM
    # restart was invisible in jhist; operators asked why a job "paused".
    COORDINATOR_RECOVERED = "COORDINATOR_RECOVERED"
    # Progress-based liveness (coordinator/liveness.py; no reference
    # analogue — TonY's liveness was heartbeat-only).
    # A task's step counter stopped advancing past the progress deadline
    # while its heartbeats kept arriving: the user process is wedged.
    # Payload: steps, stalled_s, timeout_s; the subsequent TASK_FINISHED
    # carries the captured stack-dump excerpt.
    TASK_HUNG = "TASK_HUNG"
    # A task's step rate stayed below the configured fraction of its
    # gang's median for the sustained window. Payload: rate vs median.
    TASK_STRAGGLER = "TASK_STRAGGLER"
    # One-time warning: progress liveness is configured but this task
    # never reported a step counter — it degrades to heartbeat-only
    # liveness (never a false hang kill).
    TASK_PROGRESS_UNINSTRUMENTED = "TASK_PROGRESS_UNINSTRUMENTED"
    # Automatic failure diagnosis ran on a non-SUCCEEDED finish
    # (tony_tpu/diagnosis/): payload carries the verdict category, the
    # blamed task, the rule that fired, and the incident.json path —
    # downstream tooling reads the verdict without re-running the engine.
    JOB_DIAGNOSED = "JOB_DIAGNOSED"
    # Elastic gang resize (coordinator/elastic.py): the gang's membership
    # changed WITHOUT restarting the job — host-loss absorption, an
    # explicit `tony-tpu resize`, or grow-back. Emitted with
    # phase="started" when the drain begins and phase="completed" when
    # the re-meshed gang's barrier reopens; payload carries the jobtype,
    # the bumped membership generation, the member indices, the from/to
    # sizes and the trigger reason. A deliberate resize on the timeline —
    # the diagnosis engine must not read its absorbed task exits as the
    # job's failure.
    GANG_RESIZED = "GANG_RESIZED"
    # Live job migration (coordinator/migrate.py): the WHOLE gang drained,
    # snapshotted, and relaunched on a different slice WITHOUT restarting
    # the job — spot-reclaim survival or fleet defragmentation. Emitted
    # with phase="started" when the drain begins and phase="completed"
    # when the barrier reopens on the destination; payload carries the
    # jobtype, mgen, members, source/target slice and the trigger reason.
    # The goodput ledger books the completed window as its own
    # "migration" phase (fleet/ledger.py), never as train.
    GANG_MIGRATED = "GANG_MIGRATED"
    # On-demand device profiling (tony-tpu profile <app>): a task's
    # capture reached a terminal state. Payload: task, request id, steps,
    # status ("captured" with the artifact dir, or "failed" with the
    # error — a failed capture never kills or stalls training).
    TASK_PROFILED = "TASK_PROFILED"
    # Fleet scheduler events (tony_tpu/fleet/daemon.py — the multi-job
    # gang scheduler's own stream, written into the fleet dir, not a job
    # dir). A submission entered the queue; payload: job, tenant,
    # priority, hosts.
    FLEET_JOB_QUEUED = "FLEET_JOB_QUEUED"
    # A queued submission was granted capacity and spawned; payload:
    # job, hosts, placement, wait_s (queue wait — the p50/p99 source).
    FLEET_JOB_GRANTED = "FLEET_JOB_GRANTED"
    # A running job was shrunk via its coordinator's elastic resize to
    # reclaim hosts for a higher-priority submission (preempt-to-
    # reclaim: drain→remesh, no victim epoch burned, never a kill);
    # payload: job, from/to hosts, the demanding job.
    FLEET_JOB_PREEMPTED = "FLEET_JOB_PREEMPTED"
    # A grant was deferred because the tenant is at its host quota
    # (emitted once per queued→quota-denied transition, not per tick);
    # payload: job, tenant, used, quota.
    FLEET_QUOTA_DENIED = "FLEET_QUOTA_DENIED"
    # A queued job's not-placed reason TRANSITIONED (the scheduler
    # decision explainer, tony_tpu/fleet/daemon.py): the policy engine
    # held the job this tick for a DIFFERENT reason than last tick —
    # quota / capacity / fragmentation / priority-held / preempt-wait.
    # Emitted per transition, never per tick (the per-tick stream is the
    # REC_FLEET_DECISION journal + the in-memory decision ring behind
    # `tony-tpu fleet explain`); payload: job, action, reason, blocking
    # (the job ids / tenants holding the capacity).
    FLEET_JOB_HELD = "FLEET_JOB_HELD"
    # A running fleet job was live-migrated between slices (spot-reclaim
    # survival or FRAGMENTATION repacking) via its coordinator's migrate
    # op — drain→move→reshard, no epoch burned, never a kill; payload:
    # job, source, target, reason.
    FLEET_JOB_MIGRATED = "FLEET_JOB_MIGRATED"
    # A fleet job reached a terminal state (finished/failed/cancelled);
    # payload: job, state, exit, app_id.
    FLEET_JOB_FINISHED = "FLEET_JOB_FINISHED"
    # Host health (tony_tpu/fleet/health.py): the failure-attribution
    # ledger pushed a host over the quarantine threshold (or an operator
    # / preflight probe cordoned it) — the host leaves the placement
    # pool until probation clears it; payload: host, slice, state,
    # score, reason, manual.
    FLEET_HOST_QUARANTINED = "FLEET_HOST_QUARANTINED"
    # A cordoned host returned to the healthy pool — probation canary
    # ran clean, quarantine cooldown expired into a clean canary, or an
    # operator uncordoned it; payload: host, slice, state, reason.
    FLEET_HOST_RESTORED = "FLEET_HOST_RESTORED"
    # Correlated failure detection: >= blast-n hosts on one slice went
    # suspect inside the blast window, so the whole slice is treated as
    # sick — cordoned and queued for evacuation migration; payload:
    # slice, hosts.
    FLEET_SLICE_CORDONED = "FLEET_SLICE_CORDONED"
    # Alerting (tony_tpu/alerts/): a rule completed its for-duration and
    # transitioned to FIRING — the breach is real, not a blip. Emitted
    # by the coordinator monitor tick (job-scope rules, into the job's
    # event stream) or the fleet daemon tick (fleet-scope rules, into
    # the fleet stream), AFTER the REC_ALERT/REC_FLEET_ALERT record is
    # journaled write-ahead; payload: rule, severity, value, labels,
    # summary, scope ("job"|"fleet"). An alert firing before a failure
    # becomes precedence-boosted diagnosis evidence.
    ALERT_FIRING = "ALERT_FIRING"
    # The firing (or pending) rule returned below threshold — one good
    # evaluation resolves; payload mirrors ALERT_FIRING. A SUCCEEDED
    # job's teardown force-resolves every open alert, so its journal
    # never ends with an alert firing (the alert-journal invariant).
    ALERT_RESOLVED = "ALERT_RESOLVED"


@dataclasses.dataclass
class Event:
    type: EventType
    payload: Dict[str, Any]
    timestamp_ms: int = 0

    def __post_init__(self) -> None:
        if not self.timestamp_ms:
            self.timestamp_ms = int(time.time() * 1000)

    def to_json(self) -> str:
        return json.dumps(
            {"type": self.type.value, "timestamp": self.timestamp_ms,
             "event": self.payload},
            sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Event":
        d = json.loads(line)
        return cls(EventType(d["type"]), d.get("event", {}), d.get("timestamp", 0))


class EventHandler:
    """Queue-backed async writer (reference EventHandler.java:98-113)."""

    def __init__(self, job_dir: str, in_progress_name: str,
                 on_emit: Optional[Any] = None):
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._job_dir = job_dir
        self._path = os.path.join(job_dir, in_progress_name)
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        # Observability tap: called synchronously with each emitted event
        # (the coordinator counts event types into its metrics registry).
        self._on_emit = on_emit
        os.makedirs(job_dir, exist_ok=True)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._drain, name="tony-event-writer", daemon=True)
        self._thread.start()

    def emit(self, event: Event) -> None:
        if self._on_emit is not None:
            try:
                self._on_emit(event)
            except Exception:  # noqa: BLE001 — the tap must never block history
                pass
        self._queue.put(event)

    def _drain(self) -> None:
        from tony_tpu.utils.durable import fsync_file

        with open(self._path, "a", encoding="utf-8") as f:
            dirty = False
            while True:
                try:
                    ev = self._queue.get(timeout=0.2)
                except queue.Empty:
                    if self._stopped.is_set():
                        break
                    if dirty:
                        # Durability on the idle edge, not per event: a
                        # coordinator crash then loses at most the burst
                        # in flight, and readers tolerate a torn tail
                        # (read_events) — same contract as the journal.
                        fsync_file(f)
                        dirty = False
                    continue
                if ev is None:
                    break
                if not isinstance(ev, Event):
                    # Flush barrier (a threading.Event — possibly the
                    # sanitizer's wrapper, so match "not an event
                    # record" rather than the concrete class):
                    # everything queued before it is now written; push
                    # it to disk and wake the waiter.
                    fsync_file(f)
                    dirty = False
                    ev.set()
                    continue
                f.write(ev.to_json() + "\n")
                dirty = True
            fsync_file(f)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every event emitted so far is written AND synced
        to the in-progress file (FIFO queue ⇒ a barrier marker behind
        them proves it). The diagnosis collector reads that file from
        disk mid-teardown, so the stream must be materialized first."""
        if self._thread is None or not self._thread.is_alive():
            return False
        done = threading.Event()
        self._queue.put(done)  # type: ignore[arg-type]
        return done.wait(timeout)

    def stop(self, final_name: str) -> str:
        """Flush remaining events and rename in-progress → final
        (reference EventHandler.java:126-135). The rename is made durable
        (dir fsync) — a finalized-then-vanished history file would read
        as a still-running job forever."""
        from tony_tpu.utils.durable import durable_replace

        self._stopped.set()
        self._queue.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        final_path = os.path.join(self._job_dir, final_name)
        if os.path.exists(self._path):
            durable_replace(self._path, final_path)
        return final_path


def read_events(path: str) -> List[Event]:
    """Decode an event file back into Events (reference
    ``ParserUtils.parseEvents`` :258-287).

    Torn-tail tolerant: a coordinator crash can leave a partially
    written final line (the window between write and fsync). Decoding
    stops at the first bad line with a warning — the portal and CLI must
    render the crashed job's history, not traceback over it."""
    import logging

    out: List[Event] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(Event.from_json(line))
            except (ValueError, KeyError):
                logging.getLogger(__name__).warning(
                    "torn/undecodable event record in %s after %d good "
                    "ones — returning the prefix", path, len(out))
                break
    return out
