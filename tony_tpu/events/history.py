"""History file naming, layout, parsing, moving and purging.

Reference model:
- filename grammar ``<appId>-<started>[-<completed>]-<user>[-<STATUS>].jhist``
  (``util/HistoryFileUtils.java:12-31``, parse ``util/ParserUtils.java:67-98``);
- directory layout ``<history>/intermediate/<appId>/`` while running, moved to
  ``<history>/finished/yyyy/MM/dd/<appId>/`` by a background mover every 5 min
  (``tony-portal/.../HistoryFileMover.java:74-121``), retention-deleted by a
  purger (``HistoryFilePurger.java:53-107``);
- job metadata synthesized from the filename (``models/JobMetadata.java``).
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import time
from typing import Dict, List, Optional

from tony_tpu import constants

_HIST_RE = re.compile(
    r"^(?P<app>[A-Za-z0-9_]+)-(?P<start>\d+)(?:-(?P<end>\d+))?-(?P<user>[^-]+)"
    r"(?:-(?P<status>[A-Z]+))?" + re.escape(constants.EVENTS_SUFFIX) + r"$")


@dataclasses.dataclass
class JobMetadata:
    """Reference ``models/JobMetadata.java`` (143 LoC)."""

    app_id: str
    started_ms: int
    completed_ms: int
    user: str
    status: str

    @property
    def finished(self) -> bool:
        return self.completed_ms > 0


def in_progress_name(app_id: str, started_ms: int, user: str) -> str:
    return f"{app_id}-{started_ms}-{user}{constants.INPROGRESS_SUFFIX}"


def final_name(app_id: str, started_ms: int, completed_ms: int, user: str,
               status: str) -> str:
    """Reference ``HistoryFileUtils.generateFileName`` :12-31."""
    return (f"{app_id}-{started_ms}-{completed_ms}-{user}-{status}"
            f"{constants.EVENTS_SUFFIX}")


def parse_metadata(filename: str) -> Optional[JobMetadata]:
    """Parse filename metadata (reference ``ParserUtils.parseMetadata`` :67-98)."""
    m = _HIST_RE.match(os.path.basename(filename))
    if not m:
        return None
    return JobMetadata(
        app_id=m.group("app"),
        started_ms=int(m.group("start")),
        completed_ms=int(m.group("end") or 0),
        user=m.group("user"),
        status=m.group("status") or "RUNNING",
    )


def date_partition(ms: int) -> str:
    """yyyy/MM/dd partition dir (reference ``ParserUtils.getYearMonthDayDirectory``
    :307)."""
    t = time.gmtime(ms / 1000.0)
    return os.path.join(f"{t.tm_year:04d}", f"{t.tm_mon:02d}", f"{t.tm_mday:02d}")


def intermediate_dir(history_root: str, app_id: str) -> str:
    return os.path.join(history_root, constants.HISTORY_INTERMEDIATE, app_id)


def find_history_file(job_dir: str) -> Optional[str]:
    """Latest event file in a job dir (reference ``ParserUtils`` :100)."""
    if not os.path.isdir(job_dir):
        return None
    candidates = [f for f in os.listdir(job_dir)
                  if f.endswith(constants.EVENTS_SUFFIX)]
    if not candidates:
        return None
    return os.path.join(job_dir, sorted(candidates)[-1])


def list_job_dirs(history_root: str) -> Dict[str, str]:
    """app_id → job dir, across intermediate and finished trees."""
    out: Dict[str, str] = {}
    inter = os.path.join(history_root, constants.HISTORY_INTERMEDIATE)
    if os.path.isdir(inter):
        for app in os.listdir(inter):
            out[app] = os.path.join(inter, app)
    fin = os.path.join(history_root, constants.HISTORY_FINISHED)
    for root, dirs, _files in os.walk(fin):
        depth = os.path.relpath(root, fin).count(os.sep)
        if depth == 2:  # root == finished/yyyy/MM/dd → its dirs are app ids
            for app in list(dirs):
                out[app] = os.path.join(root, app)
            dirs.clear()
    return out


@dataclasses.dataclass
class JobRow:
    """One row of the jobs index (portal jobs view / CLI history)."""

    app_id: str
    status: str
    user: str
    started_ms: int

    @property
    def started_iso(self) -> str:
        return time.strftime("%Y-%m-%d %H:%M:%S",
                             time.gmtime(self.started_ms / 1000.0))


def list_jobs(history_root: str) -> List[JobRow]:
    """Jobs index across intermediate + finished trees, newest first."""
    rows: List[JobRow] = []
    for app, job_dir in list_job_dirs(history_root).items():
        hist = find_history_file(job_dir)
        meta = parse_metadata(hist) if hist else None
        if meta is None:
            # Fall back to the in-progress file for running jobs.
            for f in os.listdir(job_dir):
                if f.endswith(constants.INPROGRESS_SUFFIX):
                    meta = parse_metadata(
                        f[: -len(constants.INPROGRESS_SUFFIX)]
                        + constants.EVENTS_SUFFIX)
                    break
        if meta is None:
            continue
        rows.append(JobRow(app_id=app, status=meta.status, user=meta.user,
                           started_ms=meta.started_ms))
    rows.sort(key=lambda r: -r.started_ms)
    return rows


def read_job_events(history_root: str, app_id: str):
    """Decoded event list for one job, or None if unknown
    (reference ``ParserUtils.parseEvents`` :258-287)."""
    from tony_tpu.events.events import read_events

    job_dir = list_job_dirs(history_root).get(app_id)
    if job_dir is None:
        return None
    hist = find_history_file(job_dir)
    if hist is None:
        for f in os.listdir(job_dir):
            if f.endswith(constants.INPROGRESS_SUFFIX):
                hist = os.path.join(job_dir, f)
                break
    if hist is None:
        return None
    return read_events(hist)


class HistoryFileMover:
    """Move completed jobs intermediate → finished/yyyy/MM/dd
    (reference ``HistoryFileMover.java:74-121``; KILLED-rename behaviour for
    jobs whose coordinator died before finalizing)."""

    def __init__(self, history_root: str):
        self.root = history_root

    def move_once(self) -> List[str]:
        moved = []
        inter = os.path.join(self.root, constants.HISTORY_INTERMEDIATE)
        if not os.path.isdir(inter):
            return moved
        for app in os.listdir(inter):
            job_dir = os.path.join(inter, app)
            hist = find_history_file(job_dir)
            if hist is None:
                # Coordinator died without finalizing: finalize as KILLED
                # (reference HistoryFileMover.java in-progress rename).
                for f in os.listdir(job_dir):
                    if f.endswith(constants.INPROGRESS_SUFFIX):
                        meta_part = f[: -len(constants.INPROGRESS_SUFFIX)]
                        m = re.match(r"^(.+)-(\d+)-([^-]+)$", meta_part)
                        if not m:
                            continue
                        killed = final_name(m.group(1), int(m.group(2)),
                                            int(time.time() * 1000),
                                            m.group(3), "KILLED")
                        from tony_tpu.utils.durable import durable_replace
                        durable_replace(os.path.join(job_dir, f),
                                        os.path.join(job_dir, killed))
                        hist = os.path.join(job_dir, killed)
                if hist is None:
                    continue
            meta = parse_metadata(hist)
            when = meta.completed_ms if meta and meta.completed_ms else int(
                time.time() * 1000)
            dest = os.path.join(self.root, constants.HISTORY_FINISHED,
                                date_partition(when), app)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            shutil.move(job_dir, dest)
            moved.append(dest)
        return moved


class HistoryFilePurger:
    """Delete finished history older than retention
    (reference ``HistoryFilePurger.java:53-107``)."""

    def __init__(self, history_root: str, retention_days: int):
        self.root = history_root
        self.retention_days = retention_days

    def purge_once(self, now_ms: Optional[int] = None) -> List[str]:
        now_ms = now_ms or int(time.time() * 1000)
        cutoff = now_ms - self.retention_days * 86400 * 1000
        purged = []
        for app, job_dir in list_job_dirs(self.root).items():
            if constants.HISTORY_INTERMEDIATE in job_dir.split(os.sep):
                continue
            hist = find_history_file(job_dir)
            meta = parse_metadata(hist) if hist else None
            when = meta.completed_ms if meta and meta.completed_ms else 0
            if when and when < cutoff:
                shutil.rmtree(job_dir, ignore_errors=True)
                purged.append(app)
        return purged
