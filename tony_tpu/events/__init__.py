from tony_tpu.events.events import Event, EventType, EventHandler  # noqa: F401
from tony_tpu.events import history  # noqa: F401
