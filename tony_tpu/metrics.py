"""Live metrics pipeline primitives: ring-buffer time series, monotonic
counters, latency histograms, and Prometheus text exposition.

The reference's metrics surface was post-hoc only: TaskMonitor pushed
max/avg aggregates that surfaced on TASK_FINISHED (``TaskMonitor.java``)
— nothing answered "what is the gang doing RIGHT NOW". Here the
executor's heartbeat already carries a progress beacon
(coordinator/liveness.py); the same beacon widened with utilization
numbers (steps/s, MFU, HBM, RSS — tony_tpu/telemetry.py derives them in
the user process) feeds a coordinator-side :class:`MetricsRegistry`,
which renders the whole job as Prometheus text exposition (served live
by the portal at ``/metrics`` and written to ``metrics.prom`` in the job
dir) and as the ``metrics.live`` RPC behind ``tony-tpu top``.

Design constraints:

- **Bounded memory**: gauges keep a ring buffer of the last N points
  (``tony.metrics.ring-points``) — enough for sparklines and short-window
  rates, never an unbounded series store. Prometheus owns long-term
  storage; this registry is the scrape source, not a TSDB.
- **Counter monotonicity across ``--recover``**: counters snapshot to
  ``metrics.counters.json`` (atomic replace) and a recovered coordinator
  reloads them, so ``tony_rpc_requests_total`` never steps backwards just
  because the coordinator process was replaced — rate() windows spanning
  a recovery stay truthful.
- **Cross-process histograms**: executors keep their RPC client latency
  histogram locally and ship the cumulative snapshot on the beacon; the
  registry re-exposes it verbatim (cumulative counts from the executor's
  own lifetime — exactly the monotonic shape Prometheus expects).
"""

from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from tony_tpu.devtools.race import guarded

#: Latency buckets (seconds) shared by RPC server/client histograms:
#: sub-ms localhost dispatch up to the 10 s call-timeout ceiling.
DEFAULT_LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                             0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: THE series-name registry: every ``tony_*`` family the system exports,
#: in one place. tonylint's ``metrics-registry`` rule enforces it both
#: ways (an exported name must be registered; a registered name must
#: have an exporting call site), and ``tony-tpu check`` verifies every
#: family in a job's ``metrics.prom`` against it — so the docs, the
#: portal and benchdiff can never drift against what actually exports.
SERIES: Dict[str, str] = {
    # -- per-task utilization (heartbeat-beacon-fed gauges) --------------
    "tony_task_steps_completed": "step counter from the progress beacon",
    "tony_task_steps_per_sec": "training steps per second",
    "tony_task_tokens_per_sec": "tokens per second",
    "tony_task_mfu": "model FLOPs utilization vs peak bf16",
    "tony_task_hbm_bytes": "device HBM bytes in use",
    "tony_task_rss_bytes": "process-tree resident set size bytes",
    "tony_step_phase_seconds": "cumulative step wall per phase",
    "tony_task_heartbeat_age_seconds": "seconds since last heartbeat",
    # -- gang / session shape --------------------------------------------
    "tony_tasks": "tasks by status",
    "tony_gang_size": "current task count per jobtype gang",
    "tony_session_epoch": "current retry epoch",
    "tony_coordinator_generation": "coordinator generation",
    "tony_membership_generation": "elastic membership generation",
    # -- RPC plane --------------------------------------------------------
    "tony_rpc_server_seconds": "coordinator-side RPC dispatch latency",
    "tony_rpc_client_seconds": "executor-side RPC call latency",
    "tony_rpc_requests_total": "RPC requests dispatched",
    "tony_events_total": "job-history events emitted, by type",
    # -- fleet: multi-job gang scheduler (tony_tpu/fleet/daemon.py) ------
    "tony_fleet_hosts": "pool hosts by state (total/used/free/cordoned)",
    "tony_fleet_jobs": "fleet jobs by state",
    "tony_fleet_queue_depth": "submissions waiting for a grant",
    "tony_fleet_tenant_hosts": "granted hosts per tenant",
    "tony_fleet_grants_total": "job grants applied",
    "tony_fleet_preemptions_total": "preempt-to-reclaim shrinks applied",
    "tony_fleet_migrations_total": "live slice migrations applied "
                                   "(defrag, evacuation, operator)",
    "tony_fleet_reclaim_notices_total": "slice-preemption notices "
                                        "received from the reclaim feed",
    "tony_fleet_quota_denials_total": "grants deferred by tenant quota",
    "tony_fleet_queue_wait_seconds": "submit-to-grant wait latency",
    # -- fleet host health (tony_tpu/fleet/health.py) ---------------------
    "tony_fleet_host_health": "per-host health state (0 healthy, "
                              "1 suspect, 2 probation, 3 quarantined)",
    "tony_fleet_quarantined_hosts": "hosts currently cordoned by "
                                    "health quarantine or probation",
    "tony_fleet_quarantines_total": "host quarantine transitions applied",
    "tony_fleet_sick_slices_total": "correlated slice cordons "
                                    "(blast-radius evacuations)",
    # -- fleet goodput ledger (tony_tpu/fleet/ledger.py) ------------------
    "tony_fleet_goodput_fraction": "chip-seconds doing useful train "
                                   "steps / chip-seconds held, per "
                                   "tenant and fleet-wide",
    "tony_fleet_phase_seconds": "cumulative ledger chip-seconds per "
                                "goodput phase and tenant",
    # -- control-plane self-observation (coordinator/coordphases.py) -----
    "tony_coord_phase_seconds": "coordinator tick wall per phase",
    "tony_coord_tick_seconds": "mean active coordinator tick duration",
    "tony_coord_registered_tasks": "tasks currently registered",
    "tony_coord_beats_total": "heartbeats received",
    "tony_journal_records_total": "write-ahead journal records appended",
    "tony_journal_bytes_total": "write-ahead journal bytes appended",
    "tony_journal_fsync_seconds": "journal append latency (fsync incl.)",
    # -- alerting (tony_tpu/alerts/) --------------------------------------
    "tony_alerts_firing": "alerts currently firing, by severity",
    "tony_alert_transitions_total": "alert state-machine transitions "
                                    "journaled, by state",
}

_LabelsKey = Tuple[Tuple[str, str], ...]


def escape_label_value(value: Any) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline (exposition format spec, in this order — escaping the
    backslash last would corrupt the other two escapes)."""
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels_key(labels: Optional[Dict[str, Any]]) -> _LabelsKey:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def format_labels(key: _LabelsKey,
                  extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = list(key) + list(extra or [])
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{escape_label_value(v)}"'
                          for k, v in pairs) + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Series:
    """Gauge with bounded history: the ring buffer behind sparklines,
    windowed evaluators (``MetricsRegistry.rate`` over cumulative
    gauges, burn-rate windows) and the `latest` sample the exposition
    renders. Ring timestamps are ``time.monotonic()`` — they only ever
    feed window arithmetic, never wall-clock display."""

    def __init__(self, maxlen: int = 512):
        self.points: Deque[Tuple[float, float]] = collections.deque(
            maxlen=max(2, int(maxlen)))

    def set(self, value: float, ts: Optional[float] = None) -> None:
        self.points.append((ts if ts is not None else time.monotonic(),
                            float(value)))

    @property
    def latest(self) -> Optional[float]:
        return self.points[-1][1] if self.points else None

    def values(self) -> List[float]:
        return [v for _, v in self.points]


class Counter:
    """Monotonic counter; ``inc`` with a negative amount is a programming
    error and raises (monotonicity is the contract Prometheus rate()
    depends on). Keeps a bounded ring of (monotonic ts, value-after-inc)
    points so ``MetricsRegistry.rate`` can window it; the seed point
    anchors the recover base, so a rate window spanning a ``--recover``
    sees the reloaded value as history, not as a fresh increase."""

    def __init__(self, base: float = 0.0, maxlen: int = 512):
        self.value = float(base)
        self.points: Deque[Tuple[float, float]] = collections.deque(
            maxlen=max(2, int(maxlen)))
        self.points.append((time.monotonic(), self.value))

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter decrement ({amount}) is not allowed")
        self.value += amount
        self.points.append((time.monotonic(), self.value))


class Histogram:
    """Fixed-bucket latency histogram (cumulative on render, like the
    exposition format wants). ``snapshot()`` is the wire form executors
    put on the heartbeat beacon."""

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_S,
                 raw_points: int = 1024):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # last = overflow
        self.sum = 0.0
        self.count = 0
        #: bounded (monotonic ts, value) ring behind quantile_over —
        #: exact windowed quantiles for local histograms, no bucket error
        self.raw: Deque[Tuple[float, float]] = collections.deque(
            maxlen=max(2, int(raw_points)))
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            self.raw.append((time.monotonic(), v))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self.counts),
                    "sum": self.sum, "count": self.count}


def render_histogram_lines(name: str, key: _LabelsKey,
                           snap: Dict[str, Any]) -> List[str]:
    """_bucket/_sum/_count lines from a snapshot (cumulative, +Inf last)."""
    buckets = [float(b) for b in snap.get("buckets", [])]
    counts = [int(c) for c in snap.get("counts", [])]
    counts += [0] * (len(buckets) + 1 - len(counts))
    lines = []
    cum = 0
    for b, c in zip(buckets, counts):
        cum += c
        lines.append(f"{name}_bucket{format_labels(key, [('le', _fmt_value(b))])}"
                     f" {cum}")
    total = int(snap.get("count", cum + counts[len(buckets)]))
    lines.append(f'{name}_bucket{format_labels(key, [("le", "+Inf")])} '
                 f"{total}")
    lines.append(f"{name}_sum{format_labels(key)} "
                 f"{_fmt_value(float(snap.get('sum', 0.0)))}")
    lines.append(f"{name}_count{format_labels(key)} {total}")
    return lines


def _window_increase(pts: List[Tuple[float, float]],
                     cutoff: float) -> float:
    """Increase of a cumulative series over [cutoff, now]: last in-window
    value minus the value as of the window's start (the newest point at
    or before the cutoff — so a window spanning a quiet stretch, or a
    ``--recover`` reload, reads zero increase instead of re-counting the
    whole base). A backwards step (counter reset) contributes its
    post-reset value, Prometheus-style."""
    base: Optional[float] = None
    in_win: List[float] = []
    for ts, v in pts:
        if ts < cutoff:
            base = v
        else:
            in_win.append(v)
    if not in_win:
        return 0.0
    prev = base if base is not None else in_win[0]
    inc = 0.0
    for v in in_win:
        d = v - prev
        inc += d if d >= 0 else v
        prev = v
    return inc


def _bucket_quantile(bounds: List[float], counts: List[float],
                     q: float) -> float:
    """Quantile from per-bucket counts (+overflow last) by linear
    interpolation inside the owning bucket; overflow clamps to the top
    bound (same convention as coordphases.histogram_quantile)."""
    total = sum(counts)
    if total <= 0 or not bounds:
        return 0.0
    rank = max(0.0, min(1.0, float(q))) * total
    cum, lo = 0.0, 0.0
    for bound, c in zip(bounds, counts):
        if cum + c >= rank and c > 0:
            return lo + (bound - lo) * (rank - cum) / c
        cum += c
        lo = bound
    return float(bounds[-1])


@guarded
class MetricsRegistry:
    """The coordinator's in-memory metrics store: gauges (ring-buffer
    series), counters (recover-persistent), histograms (local and
    beacon-shipped snapshots), rendered as one Prometheus exposition.

    Thread-safety: instruments are registered from beat/RPC threads
    while the export worker renders — every registry-map touch holds
    ``_lock`` (the ``GUARDED_BY`` declaration below is enforced at
    runtime by the tonyrace detector, devtools/race.py)."""

    #: tonyrace registry: every family map is guarded by the one lock.
    GUARDED_BY = {
        "_gauges": "_lock",
        "_counters": "_lock",
        "_hists": "_lock",
        "_hist_snaps": "_lock",
        "_hist_snap_rings": "_lock",
        "_help": "_lock",
        "_saved_counters": "_lock",
    }

    def __init__(self, ring_points: int = 512):
        self._ring_points = ring_points
        self._gauges: Dict[str, Dict[_LabelsKey, Series]] = {}
        self._counters: Dict[str, Dict[_LabelsKey, Counter]] = {}
        self._hists: Dict[str, Dict[_LabelsKey, Histogram]] = {}
        self._hist_snaps: Dict[str, Dict[_LabelsKey, Dict[str, Any]]] = {}
        # (monotonic ts, snapshot) rings behind quantile_over for
        # beacon-shipped histograms: windowed quantile = bucket diff of
        # the newest snapshot against the last one older than the window
        self._hist_snap_rings: Dict[
            str, Dict[_LabelsKey,
                      Deque[Tuple[float, Dict[str, Any]]]]] = {}
        self._help: Dict[str, str] = {}
        self._saved_counters: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()

    # -- instruments -----------------------------------------------------
    def gauge(self, name: str, labels: Optional[Dict[str, Any]] = None,
              help: str = "") -> Series:
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            fam = self._gauges.setdefault(name, {})
            series = fam.get(key)
            if series is None:
                series = fam[key] = Series(self._ring_points)
        return series

    def counter(self, name: str, labels: Optional[Dict[str, Any]] = None,
                help: str = "") -> Counter:
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            fam = self._counters.setdefault(name, {})
            c = fam.get(key)
            if c is None:
                base = self._saved_counters.get(name, {}).get(
                    json.dumps(key), 0.0)
                c = fam[key] = Counter(base, maxlen=self._ring_points)
        return c

    def histogram(self, name: str,
                  labels: Optional[Dict[str, Any]] = None,
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_S,
                  help: str = "") -> Histogram:
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            fam = self._hists.setdefault(name, {})
            h = fam.get(key)
            if h is None:
                h = fam[key] = Histogram(buckets)
        return h

    def set_histogram_snapshot(self, name: str,
                               labels: Optional[Dict[str, Any]],
                               snap: Dict[str, Any],
                               help: str = "") -> None:
        """Adopt a remote histogram verbatim (executor client-latency
        histograms ride the beacon as cumulative snapshots)."""
        if not isinstance(snap, dict) or "buckets" not in snap:
            return
        key = _labels_key(labels)
        with self._lock:
            if help and name not in self._help:
                self._help[name] = help
            self._hist_snaps.setdefault(name, {})[key] = snap
            ring = self._hist_snap_rings.setdefault(name, {}).get(key)
            if ring is None:
                ring = self._hist_snap_rings[name][key] = \
                    collections.deque(maxlen=64)
            ring.append((time.monotonic(), snap))

    # -- reads -----------------------------------------------------------
    def gauge_value(self, name: str,
                    labels: Optional[Dict[str, Any]] = None
                    ) -> Optional[float]:
        with self._lock:
            series = self._gauges.get(name, {}).get(_labels_key(labels))
        return series.latest if series is not None else None

    def gauge_history(self, name: str,
                      labels: Optional[Dict[str, Any]] = None
                      ) -> List[float]:
        with self._lock:
            series = self._gauges.get(name, {}).get(_labels_key(labels))
        return series.values() if series is not None else []

    # -- windowed evaluator APIs (tony_tpu/alerts rides these) -----------
    def label_sets(self, name: str) -> List[Dict[str, str]]:
        """Every label set the family currently carries, across all
        instrument kinds."""
        with self._lock:
            keys: set = set()
            for store in (self._gauges, self._counters, self._hists,
                          self._hist_snaps):
                keys.update(store.get(name, {}).keys())
        return [dict(k) for k in sorted(keys)]

    def sample(self, name: str,
               labels: Optional[Dict[str, Any]] = None
               ) -> Optional[float]:
        """Latest instantaneous value: gauge latest, else counter value."""
        key = _labels_key(labels)
        with self._lock:
            series = self._gauges.get(name, {}).get(key)
            if series is not None and series.latest is not None:
                return series.latest
            c = self._counters.get(name, {}).get(key)
        return c.value if c is not None else None

    def gauge_points(self, name: str,
                     labels: Optional[Dict[str, Any]] = None
                     ) -> List[Tuple[float, float]]:
        """The (monotonic ts, value) ring of a gauge (or a counter's
        value-after-inc ring) — burn-rate windows walk this."""
        key = _labels_key(labels)
        with self._lock:
            series = self._gauges.get(name, {}).get(key)
            if series is not None:
                return list(series.points)
            c = self._counters.get(name, {}).get(key)
        return list(c.points) if c is not None else []

    def rate(self, name: str, labels: Optional[Dict[str, Any]] = None,
             window_s: float = 60.0,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed increase/second over a counter ring — or over a
        cumulative gauge (e.g. ``tony_step_phase_seconds``, where the
        rate of cumulative seconds is a fraction of wall time). Counter
        resets (a value stepping backwards, e.g. a replaced executor)
        contribute their post-reset value, Prometheus-style. Returns
        0.0 when the family exists but has no in-window points, None
        when the family/labels are unknown (unevaluable)."""
        key = _labels_key(labels)
        with self._lock:
            c = self._counters.get(name, {}).get(key)
            if c is not None:
                pts = list(c.points)
            else:
                series = self._gauges.get(name, {}).get(key)
                if series is None:
                    return None
                pts = list(series.points)
        now = now if now is not None else time.monotonic()
        window_s = max(1e-9, float(window_s))
        return _window_increase(pts, now - window_s) / window_s

    def quantile_over(self, name: str,
                      labels: Optional[Dict[str, Any]] = None,
                      window_s: float = 60.0, q: float = 0.99,
                      now: Optional[float] = None) -> Optional[float]:
        """Windowed quantile: exact (interpolated rank over the raw
        observation ring) for local histograms; bucket-interpolated over
        a snapshot diff for beacon-shipped histograms. None when there
        are no in-window observations (unevaluable, not zero)."""
        key = _labels_key(labels)
        now = now if now is not None else time.monotonic()
        cutoff = now - max(0.0, float(window_s))
        with self._lock:
            h = self._hists.get(name, {}).get(key)
            raw = list(h.raw) if h is not None else None
            ring = self._hist_snap_rings.get(name, {}).get(key)
            snaps = list(ring) if ring is not None else []
        if raw is not None:
            vals = sorted(v for ts, v in raw if ts >= cutoff)
            if not vals:
                return None
            rank = max(0.0, min(1.0, float(q))) * (len(vals) - 1)
            lo = int(rank)
            hi = min(lo + 1, len(vals) - 1)
            return vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)
        if not snaps or snaps[-1][0] < cutoff:
            return None
        newest = snaps[-1][1]
        base: Optional[Dict[str, Any]] = None
        for ts, snap in snaps:
            if ts < cutoff:
                base = snap
        bounds = [float(b) for b in newest.get("buckets", [])]
        counts = [float(c) for c in newest.get("counts", [])]
        counts += [0.0] * (len(bounds) + 1 - len(counts))
        if base is not None and \
                [float(b) for b in base.get("buckets", [])] == bounds:
            bcounts = [float(c) for c in base.get("counts", [])]
            bcounts += [0.0] * (len(bounds) + 1 - len(bcounts))
            counts = [max(0.0, c - b) for c, b in zip(counts, bcounts)]
        if sum(counts) <= 0 or not bounds:
            return None
        return _bucket_quantile(bounds, counts, q)

    def drop_labels(self, match: Dict[str, Any]) -> None:
        """Drop every series/counter/histogram whose labels contain all of
        ``match`` (a finished retry epoch's task series must not linger as
        frozen gauges in the exposition)."""
        want = set(_labels_key(match))
        with self._lock:
            for store in (self._gauges, self._counters, self._hists,
                          self._hist_snaps, self._hist_snap_rings):
                for fam in store.values():
                    for key in [k for k in fam if want <= set(k)]:
                        del fam[key]

    # -- exposition ------------------------------------------------------
    def render(self) -> str:
        with self._lock:
            gauges = {n: dict(f) for n, f in self._gauges.items()}
            counters = {n: dict(f) for n, f in self._counters.items()}
            hists = {n: dict(f) for n, f in self._hists.items()}
            hist_snaps = {n: dict(f) for n, f in self._hist_snaps.items()}
            helps = dict(self._help)
        lines: List[str] = []
        for name in sorted(gauges):
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} gauge")
            for key, series in sorted(gauges[name].items()):
                if series.latest is not None:
                    lines.append(f"{name}{format_labels(key)} "
                                 f"{_fmt_value(series.latest)}")
        for name in sorted(counters):
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} counter")
            for key, c in sorted(counters[name].items()):
                lines.append(f"{name}{format_labels(key)} "
                             f"{_fmt_value(c.value)}")
        all_hist_names = sorted(set(hists) | set(hist_snaps))
        for name in all_hist_names:
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} histogram")
            for key, h in sorted(hists.get(name, {}).items()):
                lines.extend(render_histogram_lines(name, key, h.snapshot()))
            for key, snap in sorted(hist_snaps.get(name, {}).items()):
                lines.extend(render_histogram_lines(name, key, snap))
        return "\n".join(lines) + "\n" if lines else ""

    # -- recover persistence ---------------------------------------------
    def save_counters(self, path: str) -> None:
        """Atomic counter snapshot — the recover seed (class docstring)."""
        with self._lock:
            payload = {name: {json.dumps(key): c.value
                              for key, c in fam.items()}
                       for name, fam in self._counters.items()}
        try:
            from tony_tpu.utils.durable import atomic_write

            atomic_write(path, json.dumps(payload).encode("utf-8"))
        except OSError:
            pass

    def load_counters(self, path: str) -> bool:
        """Seed counters from a previous life's snapshot; lazily applied as
        each counter is first touched (so label sets need no pre-walk)."""
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(payload, dict):
            return False
        with self._lock:
            self._saved_counters = {
                str(name): {str(k): float(v) for k, v in fam.items()}
                for name, fam in payload.items() if isinstance(fam, dict)}
        return True
