"""Version-compat shims over the installed jax.

Model/ops code is written against the current jax surface (``jax.shard_map``,
``jax.set_mesh``, abstract-mesh introspection); CI images and TPU-VM runtime
images lag by several releases. Every drift point is absorbed HERE, once —
call sites import from this module and stay clean of try/except ladders.

Covered drifts (installed floor: jax 0.4.x):
- ``shard_map``: top-level ``jax.shard_map`` vs
  ``jax.experimental.shard_map.shard_map``.
- ``set_mesh``: ``jax.set_mesh(mesh)`` (sharding-in-types context) vs the
  classic ``with mesh:`` physical-mesh context — on old jax the Mesh object
  itself is the context manager and jit consumes NamedShardings directly,
  so entering the physical mesh is the equivalent context.
- ``mesh_axis_size``: size of a named axis of the *currently bound* mesh
  (``jax.sharding.get_abstract_mesh()`` on new jax; the thread-resources
  physical mesh on old jax). Returns 1 when no mesh is bound or the axis
  is absent — callers branch to their unsharded path.
- ``partial_shard_map``: manual collectives over ONE axis with every other
  mesh axis left automatic (new: ``jax.shard_map(..., axis_names={ax})``;
  old: explicit mesh + ``auto=<other axes>``).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

try:  # jax >= 0.6: the supported top-level name
    from jax import shard_map as _raw_shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: the long-lived experimental home
    from jax.experimental.shard_map import shard_map as _raw_shard_map

import inspect

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_raw_shard_map).parameters)


def shard_map(f, **kw):
    """``shard_map`` accepting either spelling of the replication-check
    kwarg (``check_vma`` today, ``check_rep`` before the rename) and
    translating to whatever the installed jax takes."""
    if "check_vma" in kw and "check_vma" not in _SHARD_MAP_PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _SHARD_MAP_PARAMS:
        kw["check_vma"] = kw.pop("check_rep")
    return _raw_shard_map(f, **kw)

__all__ = ["shard_map", "set_mesh", "current_mesh", "mesh_axis_size",
           "partial_shard_map", "configure_cpu_collectives"]


def configure_cpu_collectives() -> None:
    """Multi-process CPU gangs (the virtual-mesh test substrate) need a
    cross-process collectives backend; on jax versions whose CPU default
    is "none" every sharded computation fails with "Multiprocess
    computations aren't implemented on the CPU backend". Select gloo when
    this process is part of a multi-process tony task on CPU. Safe to call
    any time before the first computation; silently a no-op where the
    option is gone (newer jax defaults to gloo)."""
    if int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1) <= 1:
        return
    platforms = (os.environ.get("JAX_PLATFORMS", "")
                 or str(jax.config.jax_platforms or "")).strip().lower()
    if platforms != "cpu":
        return
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is None:
            # gloo needs the distributed-runtime client; selecting it in
            # a process that never calls jax.distributed.initialize (a
            # gang member doing only local work) would CRASH CPU backend
            # creation instead of helping. Scripts initialize before
            # importing tony_tpu, so by the time we run the client is
            # there exactly when it should be.
            return
    except Exception:  # noqa: BLE001 — private API moved: don't guess
        return
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception:  # noqa: BLE001 — option removed: default is fine
        pass


def set_mesh(mesh):
    """Context manager binding ``mesh`` for the enclosed trace/execution.

    New jax: ``jax.set_mesh`` (also feeds ``get_abstract_mesh``). Old jax:
    the Mesh object is its own context manager and binds the
    thread-resources physical mesh, which is what ``mesh_axis_size`` and
    legacy collectives read.
    """
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def current_mesh() -> Optional[Any]:
    """The mesh bound by ``set_mesh`` (or None outside any mesh context)."""
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        m = get_abstract()
        return m if getattr(m, "axis_types", None) else None
    try:
        from jax._src import mesh as mesh_lib

        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:  # noqa: BLE001 — private API gone: no mesh context
        return None


def mesh_axis_size(axis_name: str) -> int:
    """Size of ``axis_name`` on the currently bound mesh; 1 when no mesh
    is bound or the mesh has no such axis (the unsharded fallback)."""
    m = current_mesh()
    if m is None:
        return 1
    shape = dict(getattr(m, "shape", {}) or {})
    return int(shape.get(axis_name, 1))


def partial_shard_map(fn, axis_name: str, in_specs, out_specs):
    """``shard_map`` manual over exactly ``axis_name``; every other axis of
    the bound mesh stays automatic (partial-manual collectives — the MoE
    expert-exchange shape). Must run under ``set_mesh``."""
    if hasattr(jax, "shard_map") and hasattr(jax, "set_mesh"):
        # New jax: the abstract mesh is ambient; axis_names selects the
        # manual subset.
        return jax.shard_map(fn, axis_names={axis_name},
                             in_specs=in_specs, out_specs=out_specs)
    m = current_mesh()
    if m is None:
        raise RuntimeError(
            f"partial_shard_map over {axis_name!r} needs a bound mesh "
            f"(wrap the call in compat.set_mesh(mesh))")
    # Old jax: partial-auto (`auto=`) + all_to_all hard-aborts the SPMD
    # partitioner ("Check failed: target.IsManualSubgroup()"), so fall back
    # to FULL manual over every mesh axis with the given specs — inputs are
    # replicated over the non-manual axes (correct, at the cost of
    # redundant compute/memory on those axes; the new-jax path keeps them
    # automatic). check_rep=False: the replication check predates this
    # nesting and false-positives on it.
    return shard_map(fn, mesh=m, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)
