"""Remote storage abstraction behind job staging and localization.

The reference stages the job bundle to HDFS and localizes it into every
container (``TonyClient.processFinalTonyConf`` :189-228,
``util/HdfsUtils.java:115-160``), with delegation tokens fetched for every
referenced namenode and shipped with the job
(``security/TokenCache.java:44-51``). The TPU-native analogue is an object
store: the client **puts** the bundle under a job prefix, executors on
remote TPU VMs **get** it — no shared filesystem is ever assumed once a
remote store is configured.

- ``Store`` — the minimal interface (put/get file+tree, list, exists),
  addressed by URL.
- ``LocalFsStore`` — ``file://`` (and bare paths): the single-host and
  NFS-mount path.
- ``GcsStore`` — ``gs://``: the REAL client, speaking the GCS JSON API
  over HTTPS (stdlib urllib — no SDK dependency): media + resumable
  uploads, ``alt=media`` downloads, paginated listing, bounded retry on
  429/5xx, bearer auth from the job credential / environment / the GCE
  metadata server (the TPU-VM production path). ``TONY_GCS_ENDPOINT``
  overrides the API host so the client's wire behavior is testable against
  an in-process server in egress-free CI (tests/gcs_fake_server.py).
- ``FakeGcsStore`` — ``gs://`` when ``TONY_FAKE_GCS_ROOT`` is set (CI):
  GCS **flat-namespace** semantics — objects are keys, not paths; there
  are no directories, empty or otherwise (a "directory" exists exactly
  while keys live under it) — backed by url-encoded key files under a
  local root, so filesystem habits (mkdir-then-assume, rename) cannot
  silently pass in CI and fail on real GCS. Token checks emulate the
  delegation-token contract: a bucket root marked with ``.require_token``
  rejects access unless the caller presents the matching credential.

Store selection (``get_store``): ``file://``/bare → LocalFsStore; ``gs://``
→ FakeGcsStore iff ``TONY_FAKE_GCS_ROOT`` is set, else the real GcsStore.

Credential passthrough (the TokenCache analogue): the client stamps the
storage credential into the frozen config; the coordinator exports it to
executors as ``TONY_STORAGE_TOKEN`` so they can fetch the frozen config
itself from the store before they have read it. For the real GcsStore the
same env var carries an OAuth2 access token; without it the metadata
server supplies one on GCP.
"""

from __future__ import annotations

import abc
import json
import os
import shutil
import time
from http.client import HTTPException
from typing import Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest
from urllib.parse import quote, unquote, urlparse

from tony_tpu import faults
from tony_tpu.retry import RetryPolicy, call_with_retry
from tony_tpu.utils import durable
from tony_tpu.utils.gcp import GcpBearer

STORAGE_TOKEN_ENV = "TONY_STORAGE_TOKEN"
FAKE_GCS_ROOT_ENV = "TONY_FAKE_GCS_ROOT"
GCS_ENDPOINT_ENV = "TONY_GCS_ENDPOINT"
REQUIRE_TOKEN_MARKER = ".require_token"


class StoreAuthError(PermissionError):
    """Credential missing or rejected by the store."""


def is_url(s: str) -> bool:
    return "://" in (s or "")


def credential_from_env() -> Optional[str]:
    return os.environ.get(STORAGE_TOKEN_ENV) or None


def get_store(url: str, credential: Optional[str] = None) -> "Store":
    """Factory: dispatch on scheme (see module docstring). With fault
    injection active (tony_tpu/faults.py), the store is wrapped so the
    ``storage.put``/``storage.get`` sites fire and injected transients are
    absorbed by the shared retry policy — exactly the path a real GCS
    503 burst takes through GcsStore's own bounded retry."""
    scheme = urlparse(url).scheme if is_url(url) else ""
    if scheme in ("", "file"):
        store: Store = LocalFsStore()
    elif scheme == "gs":
        cred = credential or credential_from_env()
        if os.environ.get(FAKE_GCS_ROOT_ENV):
            store = FakeGcsStore(credential=cred)
        else:
            store = GcsStore(credential=cred)
    else:
        raise ValueError(f"no store for scheme {scheme!r} (url {url!r})")
    if faults.active() is not None:
        return RetryingStore(store)
    return store


class Store(abc.ABC):
    """Minimal object-store surface; paths are URLs of the store's scheme."""

    @abc.abstractmethod
    def put_file(self, local_path: str, url: str) -> None: ...

    @abc.abstractmethod
    def get_file(self, url: str, local_path: str) -> None: ...

    @abc.abstractmethod
    def exists(self, url: str) -> bool: ...

    @abc.abstractmethod
    def isdir(self, url: str) -> bool:
        """True iff the URL is a prefix with anything under it (object
        stores have no directories — this is the prefix question)."""

    @abc.abstractmethod
    def list(self, url: str) -> List[str]:
        """Immediate child names under a prefix (empty if absent)."""

    @abc.abstractmethod
    def _keys_under(self, url: str) -> List[Tuple[str, str]]:
        """(relative_key, full_url) for every object under the prefix —
        the primitive put_tree/get_tree ride on."""

    def put_tree(self, local_dir: str, url: str) -> None:
        for root, _, files in os.walk(local_dir):
            for f in files:
                p = os.path.join(root, f)
                rel = os.path.relpath(p, local_dir).replace(os.sep, "/")
                self.put_file(p, join(url, rel))

    def get_tree(self, url: str, local_dir: str) -> None:
        keys = self._keys_under(url)
        if not keys:
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(local_dir, exist_ok=True)
        base = os.path.realpath(local_dir)
        for rel, full in keys:
            dest = os.path.realpath(
                os.path.join(local_dir, rel.replace("/", os.sep)))
            if dest != base and not dest.startswith(base + os.sep):
                # '..' (or absolute) segments are legal object-key bytes;
                # a hostile bucket must not become an arbitrary file write
                # on the coordinator (zip-slip).
                raise ValueError(
                    f"object key {rel!r} escapes destination {local_dir!r}")
            self.get_file(full, dest)


#: transfer-level retry for injected/transient faults above any store
#: implementation (the GcsStore additionally retries at the HTTP layer)
STORE_RETRY = RetryPolicy(max_attempts=5, base_delay_s=0.2, max_delay_s=5.0)


class RetryingStore(Store):
    """Fault-site + retry wrapper over any Store (installed by
    ``get_store`` when fault injection is active).

    ``storage.put``/``storage.get`` injections surface here as
    ConnectionError and are absorbed by the shared full-jitter policy;
    real transient transport errors from the inner store ride the same
    path. Genuinely terminal errors (missing object, rejected credential,
    malformed URL) propagate immediately. ``put_tree``/``get_tree`` are
    the base-class per-file loops, so every file of a tree transfer gets
    the same protection."""

    def __init__(self, inner: Store, policy: RetryPolicy = STORE_RETRY):
        self.inner = inner
        self.policy = policy

    def _retrying(self, what: str, fn):
        return call_with_retry(
            fn, self.policy,
            retry_on=(OSError, HTTPException),
            give_up_on=(FileNotFoundError, StoreAuthError, ValueError),
            what=what)

    def put_file(self, local_path: str, url: str) -> None:
        def attempt():
            faults.check("storage.put")
            self.inner.put_file(local_path, url)
        self._retrying(f"put {url}", attempt)

    def get_file(self, url: str, local_path: str) -> None:
        def attempt():
            faults.check("storage.get")
            self.inner.get_file(url, local_path)
        self._retrying(f"get {url}", attempt)

    def exists(self, url: str) -> bool:
        return self.inner.exists(url)

    def isdir(self, url: str) -> bool:
        return self.inner.isdir(url)

    def list(self, url: str) -> List[str]:
        return self.inner.list(url)

    def _keys_under(self, url: str):
        return self.inner._keys_under(url)

    def __getattr__(self, name: str):
        # Store-specific extras (LocalFsStore.open, endpoints, ...)
        return getattr(self.inner, name)


class LocalFsStore(Store):
    """``file://`` URLs and bare paths — identity mapping onto the local
    (or NFS-mounted) filesystem."""

    def _resolve(self, url: str) -> str:
        if is_url(url):
            p = urlparse(url)
            if p.scheme != "file":
                raise ValueError(f"LocalFsStore got {url!r}")
            return (p.netloc or "") + p.path
        return url

    def put_file(self, local_path: str, url: str) -> None:
        dest = self._resolve(url)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy2(local_path, dest)

    def get_file(self, url: str, local_path: str) -> None:
        src = self._resolve(url)
        if not os.path.isfile(src):
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                    exist_ok=True)
        shutil.copy2(src, local_path)

    def put_tree(self, local_dir: str, url: str) -> None:
        dest = self._resolve(url)
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)

    def get_tree(self, url: str, local_dir: str) -> None:
        src = self._resolve(url)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(local_dir, exist_ok=True)
        shutil.copytree(src, local_dir, dirs_exist_ok=True)

    def open(self, url: str, mode: str = "rb"):
        path = self._resolve(url)
        if any(m in mode for m in "wa"):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def exists(self, url: str) -> bool:
        return os.path.exists(self._resolve(url))

    def isdir(self, url: str) -> bool:
        return os.path.isdir(self._resolve(url))

    def list(self, url: str) -> List[str]:
        path = self._resolve(url)
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def _keys_under(self, url: str):
        src = self._resolve(url)
        out = []
        for root, _, files in os.walk(src):
            for f in files:
                p = os.path.join(root, f)
                rel = os.path.relpath(p, src).replace(os.sep, "/")
                out.append((rel, join(url, rel)))
        return out


def _split_gs(url: str) -> Tuple[str, str]:
    p = urlparse(url)
    if p.scheme != "gs" or not p.netloc:
        raise ValueError(f"gs store got {url!r}")
    return p.netloc, p.path.lstrip("/")


def _as_prefix(key: str) -> str:
    """Key → listing prefix: 'a/b' and 'a/b/' both mean everything under
    'a/b/'; the bucket root is the empty prefix."""
    return key.rstrip("/") + "/" if key else ""


class GcsStore(Store):
    """Real ``gs://`` client over the GCS JSON API (stdlib HTTP only).

    Production auth order: explicit credential (the job's
    ``TONY_STORAGE_TOKEN``) → ``GOOGLE_OAUTH_ACCESS_TOKEN`` → the GCE/TPU-VM
    metadata server, cached and refreshed 60 s before expiry — the
    TPU-native analogue of the reference's delegation-token fetch
    (``TokenCache.java:44-51``). Requests without any obtainable token go
    out anonymous (public buckets); 401/403 surface as StoreAuthError.

    Wire behavior deliberately covered by contract tests against a local
    JSON-API server (``TONY_GCS_ENDPOINT`` override): resumable uploads in
    256 KiB-aligned chunks with 308 handling, paginated listing
    (``nextPageToken``), bounded retry with backoff on 429/5xx and
    transport errors.
    """

    #: files at or above this size upload via a resumable session
    RESUMABLE_THRESHOLD = 8 * 1024 * 1024
    #: resumable chunk size — must be a multiple of 256 KiB per the API
    CHUNK = 8 * 1024 * 1024

    def __init__(self, credential: Optional[str] = None,
                 endpoint: Optional[str] = None,
                 retries: int = 4, backoff_s: float = 1.0):
        self.endpoint = (endpoint or os.environ.get(GCS_ENDPOINT_ENV)
                         or "https://storage.googleapis.com").rstrip("/")
        self._auth = GcpBearer(credential)
        self.retries = retries
        self.backoff_s = backoff_s
        # Exponential backoff with FULL JITTER (tony_tpu/retry.py): a
        # whole gang hitting the same 429/503 burst must de-correlate its
        # retries, not re-synchronize on a fixed doubling schedule.
        self._policy = RetryPolicy(max_attempts=retries + 1,
                                   base_delay_s=backoff_s,
                                   max_delay_s=max(backoff_s * 8, 30.0))

    # -- auth ----------------------------------------------------------
    def _bearer(self) -> Optional[str]:
        # Shared resolution (explicit → env → metadata server, cached with
        # negative cache): utils/gcp.py, also used by the TPU provisioner.
        return self._auth.token()

    # -- http ----------------------------------------------------------
    def _request(self, method: str, url: str, data: Optional[bytes] = None,
                 headers: Optional[Dict[str, str]] = None,
                 ok: Tuple[int, ...] = (200,),
                 stream_to: Optional[str] = None,
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """One HTTP call with auth + bounded retry. Returns
        (status, body, lowercased headers); statuses in ``ok`` (plus 308,
        the resumable-continue signal) return, 404 raises FileNotFoundError,
        401/403 StoreAuthError (after one cached-token refresh — access
        tokens expire mid-job and a >1 h run must not fail its final
        upload on a stale cache), anything retryable retries then raises.
        With ``stream_to`` the body is copied straight to that path instead
        of buffered (multi-GB bundle/checkpoint downloads must not live in
        memory)."""
        refreshed_auth = False
        attempt = 0
        # `attempt` counts RETRYABLE failures only; the single-shot auth
        # refresh must not be able to exhaust the budget (a 401 on the
        # last attempt previously fell through to an assertion).
        while True:
            hdrs = dict(headers or {})
            tok = self._bearer()
            if tok:
                hdrs["Authorization"] = f"Bearer {tok}"
            req = urlrequest.Request(url, data=data, headers=hdrs,
                                     method=method)
            try:
                with urlrequest.urlopen(req, timeout=60) as r:
                    rh = {k.lower(): v for k, v in r.headers.items()}
                    if stream_to is not None:
                        with open(stream_to, "wb") as f:
                            shutil.copyfileobj(r, f, length=1024 * 1024)
                        return (r.status, b"", rh)
                    return (r.status, r.read(), rh)
            except urlerror.HTTPError as e:
                body = e.read()
                if e.code in ok or e.code == 308:
                    return (e.code, body,
                            {k.lower(): v for k, v in e.headers.items()})
                if e.code == 404:
                    raise FileNotFoundError(f"{url} not in store") from e
                if e.code in (401, 403):
                    if not refreshed_auth and self._auth.explicit is None:
                        # Cached env/metadata token may simply have
                        # expired: drop it and retry once with a fresh one.
                        refreshed_auth = True
                        self._auth.invalidate()
                        continue
                    raise StoreAuthError(
                        f"GCS denied {method} {url}: HTTP {e.code} "
                        f"({'token rejected' if tok else 'no credential'})"
                    ) from e
                if e.code not in (408, 429) and e.code < 500:
                    raise
                last = e
            except (urlerror.URLError, OSError, HTTPException) as e:
                # OSError/HTTPException (not just URLError): a reset or
                # truncated read can surface MID-BODY — from r.read() or
                # the stream_to copy — and those long transfers are
                # exactly where transient faults land.
                last = e
            if attempt >= self.retries:
                raise IOError(f"GCS {method} {url} failed after "
                              f"{self.retries + 1} attempts: {last}")
            time.sleep(self._policy.delay_s(attempt))
            attempt += 1

    def _obj_url(self, bucket: str, key: str, media: bool = False) -> str:
        if not key:
            # '…/o/' with an empty name is a 400-class API error; callers
            # that can mean a bucket root (exists) must branch before here.
            raise ValueError(f"gs://{bucket} has no object name")
        return (f"{self.endpoint}/storage/v1/b/{quote(bucket, safe='')}"
                f"/o/{quote(key, safe='')}" + ("?alt=media" if media else ""))

    # -- Store ---------------------------------------------------------
    def put_file(self, local_path: str, url: str) -> None:
        bucket, key = _split_gs(url)
        size = os.path.getsize(local_path)
        if size >= self.RESUMABLE_THRESHOLD:
            return self._put_resumable(local_path, bucket, key, size)
        with open(local_path, "rb") as f:
            data = f.read()
        self._request(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{quote(bucket, safe='')}"
            f"/o?uploadType=media&name={quote(key, safe='')}",
            data=data,
            headers={"Content-Type": "application/octet-stream"})

    def _put_resumable(self, local_path: str, bucket: str, key: str,
                       size: int) -> None:
        """Resumable upload: initiate a session, then PUT 256 KiB-aligned
        chunks; 308 + Range tells us how far the server got (so a dropped
        chunk re-sends from the server's watermark, not from zero)."""
        _, _, hdrs = self._request(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{quote(bucket, safe='')}"
            f"/o?uploadType=resumable&name={quote(key, safe='')}",
            data=b"",
            headers={"X-Upload-Content-Length": str(size),
                     "Content-Type": "application/json"})
        session = hdrs.get("location")
        if not session:
            raise IOError(f"resumable initiate for gs://{bucket}/{key} "
                          f"returned no session URI")
        offset = 0
        stalled = 0
        with open(local_path, "rb") as f:
            while True:
                if offset >= size:
                    # Every byte acknowledged yet no 2xx finalize — a
                    # nonconforming server; "success" here would leave no
                    # object behind for executors to fetch.
                    raise IOError(
                        f"resumable upload of gs://{bucket}/{key}: server "
                        f"acknowledged all {size} bytes but never "
                        f"finalized the object")
                f.seek(offset)
                chunk = f.read(min(self.CHUNK, size - offset))
                end = offset + len(chunk)
                status, _, hdrs = self._request(
                    "PUT", session, data=chunk,
                    headers={"Content-Range":
                             f"bytes {offset}-{end - 1}/{size}"},
                    ok=(200, 201, 308))
                if status != 308:
                    return          # 200/201: object finalized
                # 308 = not finished; Range carries the server's committed
                # watermark (ABSENT = zero bytes persisted — per the
                # protocol, never advance blindly). Follow the watermark
                # wherever it is, but bound non-progress: a server that
                # never advances must become an error, not a spin.
                rng = hdrs.get("range", "")
                new_offset = (int(rng.rsplit("-", 1)[1]) + 1
                              if "-" in rng else 0)
                if new_offset > offset:
                    stalled = 0
                else:
                    stalled += 1
                    if stalled > 3:
                        raise IOError(
                            f"resumable upload of gs://{bucket}/{key} "
                            f"stalled at byte {offset}/{size} (no "
                            f"watermark progress after {stalled} attempts)")
                offset = new_offset

    def get_file(self, url: str, local_path: str) -> None:
        bucket, key = _split_gs(url)
        os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                    exist_ok=True)
        tmp = local_path + ".tmp-dl"
        try:
            self._request("GET", self._obj_url(bucket, key, media=True),
                          stream_to=tmp)
        except BaseException:
            try:
                os.unlink(tmp)      # no half-downloaded leftovers
            except OSError:
                pass
            raise
        # Promote the finished download durably: the content-hash skip
        # manifest (utils/localize.py) may later trust this file by
        # size+mtime alone, so a torn rename must never look localized.
        durable.fsync_path(tmp)
        durable.durable_replace(tmp, local_path)

    def exists(self, url: str) -> bool:
        bucket, key = _split_gs(url)
        if not key:
            # gs://bucket[/]: there is no object with an empty name (the
            # API would 400 on '…/o/'); answer via the prefix listing like
            # the other stores do (ADVICE r4).
            return self.isdir(url)
        try:
            self._request("GET", self._obj_url(bucket, key))
            return True
        except FileNotFoundError:
            return self.isdir(url)

    def isdir(self, url: str) -> bool:
        bucket, key = _split_gs(url)
        try:
            items, prefixes = self._list_page(bucket, _as_prefix(key),
                                              max_results=1, first_hit=True)
        except FileNotFoundError:
            return False        # unknown bucket: a boolean, not a throw
        return bool(items or prefixes)

    def _list_page(self, bucket: str, prefix: str, max_results: int = 1000,
                   delimiter: str = "/", first_hit: bool = False,
                   ) -> Tuple[List[str], List[str]]:
        """(object names, child prefixes) under a prefix, following
        nextPageToken pagination to the end — or, with ``first_hit``, to
        the first non-empty page (real GCS may return EMPTY pages that
        still carry a continuation token; an empty first page is not
        'nothing there')."""
        names: List[str] = []
        prefixes: List[str] = []
        token = ""
        while True:
            q = (f"prefix={quote(prefix, safe='')}&maxResults={max_results}"
                 + (f"&delimiter={quote(delimiter, safe='')}"
                    if delimiter else "")
                 + (f"&pageToken={quote(token, safe='')}" if token else ""))
            _, body, _ = self._request(
                "GET",
                f"{self.endpoint}/storage/v1/b/{quote(bucket, safe='')}/o?"
                + q)
            page = json.loads(body.decode() or "{}")
            names += [o["name"] for o in page.get("items", [])]
            prefixes += page.get("prefixes", [])
            token = page.get("nextPageToken", "")
            if not token or (first_hit and (names or prefixes)):
                return names, prefixes

    def list(self, url: str) -> List[str]:
        bucket, key = _split_gs(url)
        prefix = _as_prefix(key)
        try:
            names, prefixes = self._list_page(bucket, prefix)
        except FileNotFoundError:
            return []           # unknown bucket lists like a missing prefix
        children = {n[len(prefix):] for n in names if n != prefix}
        children |= {p[len(prefix):].rstrip("/") for p in prefixes}
        return sorted(c for c in children if c)

    def _keys_under(self, url: str):
        bucket, key = _split_gs(url)
        prefix = _as_prefix(key)
        names, _ = self._list_page(bucket, prefix, delimiter="")
        return [(n[len(prefix):], f"gs://{bucket}/{n}")
                for n in names if n != prefix and not n.endswith("/")]


class FakeGcsStore(Store):
    """``gs://`` with real GCS *semantics* on a local root (egress-free CI).

    Flat namespace: an object ``jobs/app1/bundle/f.txt`` is ONE key, stored
    as the url-encoded file ``$root/<bucket>/.objects/jobs%2Fapp1%2F...``.
    There are no directories — ``isdir``/``list`` are prefix queries over
    the key set, and an "empty directory" cannot exist (exactly like GCS,
    unlike a filesystem-tree fake, which would let mkdir-then-assume bugs
    pass CI and fail in production)."""

    OBJECTS = ".objects"

    def __init__(self, root: Optional[str] = None,
                 credential: Optional[str] = None):
        self.root = root or os.environ.get(FAKE_GCS_ROOT_ENV, "")
        if not self.root:
            raise ValueError(
                f"gs:// fake needs {FAKE_GCS_ROOT_ENV} (unset it to use the "
                f"real GcsStore client)")
        self.credential = credential

    def _check_auth(self, bucket: str) -> None:
        marker = os.path.join(self.root, bucket, REQUIRE_TOKEN_MARKER)
        if os.path.isfile(marker):
            with open(marker, encoding="utf-8") as f:
                expected = f.read().strip()
            if expected and self.credential != expected:
                raise StoreAuthError(
                    f"bucket {bucket!r} requires a credential "
                    f"({'wrong token' if self.credential else 'none given'})"
                )

    def _obj_path(self, url: str) -> Tuple[str, str, str]:
        bucket, key = _split_gs(url)
        self._check_auth(bucket)
        return (bucket, key,
                os.path.join(self.root, bucket, self.OBJECTS,
                             quote(key, safe="")))

    def _keys(self, bucket: str) -> List[str]:
        d = os.path.join(self.root, bucket, self.OBJECTS)
        if not os.path.isdir(d):
            return []
        return sorted(unquote(f) for f in os.listdir(d))

    def put_file(self, local_path: str, url: str) -> None:
        _, _, path = self._obj_path(url)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp-up"
        shutil.copy2(local_path, tmp)
        # Object visibility is atomic AND durable, like a real GCS PUT.
        durable.fsync_path(tmp)
        durable.durable_replace(tmp, path)

    def get_file(self, url: str, local_path: str) -> None:
        _, _, path = self._obj_path(url)
        if not os.path.isfile(path):
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                    exist_ok=True)
        shutil.copy2(path, local_path)

    def exists(self, url: str) -> bool:
        _, key, path = self._obj_path(url)
        return os.path.isfile(path) or self.isdir(url)

    def isdir(self, url: str) -> bool:
        bucket, key, _ = self._obj_path(url)
        prefix = _as_prefix(key)
        return any(k.startswith(prefix) for k in self._keys(bucket))

    def list(self, url: str) -> List[str]:
        bucket, key, _ = self._obj_path(url)
        prefix = _as_prefix(key)
        children = set()
        for k in self._keys(bucket):
            if not k.startswith(prefix):
                continue
            children.add(k[len(prefix):].split("/", 1)[0])
        return sorted(c for c in children if c)

    def _keys_under(self, url: str):
        bucket, key, _ = self._obj_path(url)
        prefix = _as_prefix(key)
        return [(k[len(prefix):], f"gs://{bucket}/{k}")
                for k in self._keys(bucket) if k.startswith(prefix)]

    @staticmethod
    def make_bucket(root: str, bucket: str,
                    require_token: str = "") -> None:
        """Test helper: create a bucket, optionally token-protected."""
        os.makedirs(os.path.join(root, bucket), exist_ok=True)
        if require_token:
            with open(os.path.join(root, bucket, REQUIRE_TOKEN_MARKER),
                      "w", encoding="utf-8") as f:
                f.write(require_token)


def join(url: str, *parts: str) -> str:
    """URL-aware path join (no normalization across the scheme)."""
    out = url.rstrip("/")
    for p in parts:
        out += "/" + p.strip("/")
    return out
