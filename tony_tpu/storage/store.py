"""Remote storage abstraction behind job staging and localization.

The reference stages the job bundle to HDFS and localizes it into every
container (``TonyClient.processFinalTonyConf`` :189-228,
``util/HdfsUtils.java:115-160``), with delegation tokens fetched for every
referenced namenode and shipped with the job
(``security/TokenCache.java:44-51``). The TPU-native analogue is an object
store: the client **puts** the bundle under a job prefix, executors on
remote TPU VMs **get** it — no shared filesystem is ever assumed once a
remote store is configured.

- ``Store`` — the minimal interface (put/get file+tree, open, list,
  exists), addressed by URL.
- ``LocalFsStore`` — ``file://`` (and bare paths): the single-host and
  NFS-mount path.
- ``FakeGcsStore`` — ``gs://``: GCS semantics (flat keys under buckets,
  token-authenticated) backed by a local root directory, because this
  environment has no egress. The *interface* is what multi-host correctness
  rides on: every byte crosses put/get, so swapping in a real GCS client
  changes one class. Token checks emulate the delegation-token contract:
  a bucket root marked with ``.require_token`` rejects access unless the
  caller presents the matching credential (see ``credential_from_env``).

Credential passthrough (the TokenCache analogue): the client stamps the
storage credential into the frozen config; the coordinator exports it to
executors as ``TONY_STORAGE_TOKEN`` so they can fetch the frozen config
itself from the store before they have read it.
"""

from __future__ import annotations

import abc
import os
import shutil
from typing import List, Optional
from urllib.parse import urlparse

STORAGE_TOKEN_ENV = "TONY_STORAGE_TOKEN"
FAKE_GCS_ROOT_ENV = "TONY_FAKE_GCS_ROOT"
REQUIRE_TOKEN_MARKER = ".require_token"


class StoreAuthError(PermissionError):
    """Credential missing or rejected by the store."""


def is_url(s: str) -> bool:
    return "://" in (s or "")


def credential_from_env() -> Optional[str]:
    return os.environ.get(STORAGE_TOKEN_ENV) or None


def get_store(url: str, credential: Optional[str] = None) -> "Store":
    """Factory: dispatch on scheme. ``file://`` and bare paths → local FS;
    ``gs://`` → the (fake) GCS store."""
    scheme = urlparse(url).scheme if is_url(url) else ""
    if scheme in ("", "file"):
        return LocalFsStore()
    if scheme == "gs":
        return FakeGcsStore(credential=credential or credential_from_env())
    raise ValueError(f"no store for scheme {scheme!r} (url {url!r})")


class Store(abc.ABC):
    """Minimal object-store surface; paths are URLs of the store's scheme."""

    @abc.abstractmethod
    def _resolve(self, url: str) -> str:
        """Map a URL to a backing filesystem path (backend detail)."""

    def put_file(self, local_path: str, url: str) -> None:
        dest = self._resolve(url)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy2(local_path, dest)

    def get_file(self, url: str, local_path: str) -> None:
        src = self._resolve(url)
        if not os.path.isfile(src):
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                    exist_ok=True)
        shutil.copy2(src, local_path)

    def put_tree(self, local_dir: str, url: str) -> None:
        dest = self._resolve(url)
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)

    def get_tree(self, url: str, local_dir: str) -> None:
        src = self._resolve(url)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"{url} not in store")
        os.makedirs(local_dir, exist_ok=True)
        shutil.copytree(src, local_dir, dirs_exist_ok=True)

    def open(self, url: str, mode: str = "rb"):
        path = self._resolve(url)
        if any(m in mode for m in "wa"):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def exists(self, url: str) -> bool:
        return os.path.exists(self._resolve(url))

    def isdir(self, url: str) -> bool:
        return os.path.isdir(self._resolve(url))

    def list(self, url: str) -> List[str]:
        """Child names under a prefix (empty if absent)."""
        path = self._resolve(url)
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))


class LocalFsStore(Store):
    """``file://`` URLs and bare paths — identity mapping."""

    def _resolve(self, url: str) -> str:
        if is_url(url):
            p = urlparse(url)
            if p.scheme != "file":
                raise ValueError(f"LocalFsStore got {url!r}")
            return (p.netloc or "") + p.path
        return url


class FakeGcsStore(Store):
    """``gs://bucket/key`` → ``$TONY_FAKE_GCS_ROOT/bucket/key`` with the
    GCS access contract (token-checked when the bucket demands it)."""

    def __init__(self, root: Optional[str] = None,
                 credential: Optional[str] = None):
        self.root = root or os.environ.get(FAKE_GCS_ROOT_ENV, "")
        if not self.root:
            raise ValueError(
                f"gs:// store needs {FAKE_GCS_ROOT_ENV} (no egress in this "
                f"environment; the fake is backed by a local root)")
        self.credential = credential

    def _check_auth(self, bucket: str) -> None:
        marker = os.path.join(self.root, bucket, REQUIRE_TOKEN_MARKER)
        if os.path.isfile(marker):
            with open(marker, encoding="utf-8") as f:
                expected = f.read().strip()
            if expected and self.credential != expected:
                raise StoreAuthError(
                    f"bucket {bucket!r} requires a credential "
                    f"({'wrong token' if self.credential else 'none given'})"
                )

    def _resolve(self, url: str) -> str:
        p = urlparse(url)
        if p.scheme != "gs" or not p.netloc:
            raise ValueError(f"FakeGcsStore got {url!r}")
        self._check_auth(p.netloc)
        return os.path.join(self.root, p.netloc, p.path.lstrip("/"))

    @staticmethod
    def make_bucket(root: str, bucket: str,
                    require_token: str = "") -> None:
        """Test helper: create a bucket, optionally token-protected."""
        os.makedirs(os.path.join(root, bucket), exist_ok=True)
        if require_token:
            with open(os.path.join(root, bucket, REQUIRE_TOKEN_MARKER),
                      "w", encoding="utf-8") as f:
                f.write(require_token)


def join(url: str, *parts: str) -> str:
    """URL-aware path join (no normalization across the scheme)."""
    out = url.rstrip("/")
    for p in parts:
        out += "/" + p.strip("/")
    return out
