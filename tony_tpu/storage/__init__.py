from tony_tpu.storage.store import (  # noqa: F401
    FakeGcsStore, GcsStore, LocalFsStore, Store, StoreAuthError, get_store,
    is_url)
