from tony_tpu.storage.store import (  # noqa: F401
    FakeGcsStore, LocalFsStore, Store, StoreAuthError, get_store, is_url)
