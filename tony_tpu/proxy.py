"""TCP port forwarder for notebook tunneling.

Reference: ``tony-proxy/.../ProxyServer.java`` — a deliberately dumb
thread-per-connection byte pump (:32-39 accept loop, ``Proxy.run`` :50-88
two-way copy). The notebook submitter starts one locally so the user's
browser reaches a Jupyter server running inside the job
(``NotebookSubmitter.java:118-139``).
"""

from __future__ import annotations

import logging
import socket
import threading

log = logging.getLogger(__name__)


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class ProxyServer:
    """Forward ``localhost:local_port`` → ``target_host:target_port``."""

    def __init__(self, target_host: str, target_port: int,
                 local_port: int = 0):
        self.target = (target_host, target_port)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", local_port))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="proxy-accept", daemon=True)

    def start(self) -> "ProxyServer":
        self._accept_thread.start()
        log.info("proxy 127.0.0.1:%d -> %s:%d", self.port, *self.target)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(self.target, timeout=10)
            except OSError as e:
                log.warning("proxy: connect to %s failed: %s", self.target, e)
                conn.close()
                continue
            for a, b in ((conn, upstream), (upstream, conn)):
                threading.Thread(target=_pump, args=(a, b),
                                 daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
