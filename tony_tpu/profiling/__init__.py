"""Steady-state step-time attribution: phase fractions → bottleneck verdict.

The observability layer ROADMAP item 4's perf PRs are measured against:
``telemetry.phase()`` records where each training step's wall time goes
(data_wait / h2d / step_compute / comms / ckpt_stall / eval + the
unattributed ``other``), the heartbeat beacon ships the totals to the
coordinator, and this package turns them into something an operator can
act on:

- ``verdict.classify`` — evidence-backed bottleneck classification
  (INPUT_BOUND / CKPT_BOUND / COMMS_BOUND / COMPUTE_BOUND /
  UNDERUTILIZED), shown live in ``tony-tpu top`` and attached to
  ``tony-tpu diagnose`` as a perf advisory;
- ``verdict.build_perf_report`` — the ``<job_dir>/perf.json`` artifact
  the coordinator writes at finish (phase totals sum exactly to the
  attributed wall);
- ``benchdiff`` — the regression gate over BENCH jsons
  (``tony-tpu bench diff`` / ``bench.py --against``), so a cold-start or
  per-phase regression is caught at bench time, not at the next manual
  re-anchor.
"""

from tony_tpu.profiling.benchdiff import diff_bench  # noqa: F401
from tony_tpu.profiling.verdict import (COMPUTE_BOUND,  # noqa: F401
                                        CKPT_BOUND, COMMS_BOUND,
                                        COORD_HEALTHY, COORD_VERDICTS,
                                        HEARTBEAT_BOUND, INPUT_BOUND,
                                        JOURNAL_BOUND, RENDEZVOUS_BOUND,
                                        RPC_BOUND, UNDERUTILIZED,
                                        VERDICTS, build_perf_report,
                                        classify, classify_coord,
                                        load_perf, phase_fractions,
                                        save_perf)
