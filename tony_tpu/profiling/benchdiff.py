"""Bench regression gate: compare two BENCH json documents with tolerance.

The r04→r05 cold-start regression (submit_to_first_step_s 9.8s → 15.3s)
sat unnoticed in the BENCH trajectory until a manual re-anchor read the
numbers side by side. This module is the mechanical version of that
read: ``tony-tpu bench diff <base.json> <candidate.json>`` (and
``bench.py --against``) walks both documents, pairs every comparable
numeric metric, and exits nonzero when the candidate is worse than the
base by more than the tolerance — including the per-phase breakdowns
(cold-start ``phases`` and steady-state ``step_phases``), so a future
regression is attributed to a phase from the jsons alone.

Accepted shapes: the raw ``bench.py`` output line (``{"metric", "value",
"detail": {...}}``) or the harness wrapper that nests it under
``"parsed"`` (BENCH_r*.json).

Direction is inferred from the metric name, never guessed from values:
throughput-like names (tokens_per_sec, samples_per_sec, mfu, value) are
higher-is-better; latency-like names (*_s under phases,
submit_to_first_step_s, seconds_per_step) are lower-is-better; anything
unrecognized (loss, params, batch) is skipped — the gate must never
flag a config echo as a perf regression.

Stdlib-only on purpose: CI's no-deps lint job runs the gate on two
checked-in fixtures so the gate itself can't rot.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: metric-name suffixes where bigger is better
_HIGHER = ("tokens_per_sec", "samples_per_sec", "mfu_vs_peak_bf16",
           "pct_of_synthetic", "steps_per_sec", "value",
           # BENCH_SCALE family (control-plane width, bench --suite
           # scale): sustained control throughput at width.
           "beats_per_sec", "records_per_sec",
           # BENCH_FLEET family (bench --suite fleet): chip-seconds
           # doing useful steps / chip-seconds held, and the warm-pool
           # adoption rate across tenants.
           "goodput_fraction", "warm_start_fraction",
           # BENCH_MIGRATE family (bench --suite migrate): share of the
           # synchronous save cost the async writer hides, and the
           # destination gang's warm-pool adoption rate.
           "ckpt_overlap_fraction", "warm_adoption_fraction",
           # BENCH_WHATIF family (bench --suite whatif): the
           # counterfactual's fractional queue-wait payoff on the
           # starved tenant, and how full the pool ran in the sim.
           "improvement_fraction", "utilization_fraction")
#: metric-name suffixes where smaller is better
_LOWER = ("submit_to_first_step_s", "probe_self_reported_s",
          "phase_total_s", "seconds_per_step", "mean_step_s",
          "comms_fraction",
          # BENCH_SCALE family: control-plane latency/stall metrics.
          "rendezvous_s", "tick_duration_s", "fsync_p99_s",
          "fsync_stall_fraction", "resize_latency_s",
          # BENCH_FLEET family: scheduler latency/churn metrics.
          "queue_wait_p50_s", "queue_wait_p99_s",
          "preemptions_per_job", "drain_s",
          # BENCH_MIGRATE family: the move's wall, training steps the
          # move lost (the e2e drills pin 0), and save()-blocking share
          # of the step loop under the async snapshot writer.
          "migration_wall_s", "steps_lost", "ckpt_stall_fraction",
          # BENCH_WHATIF family (bench --suite whatif, fleet time
          # machine): policy-parity divergences (must pin 0), the full
          # report's fold wall, the recorded mix's end-to-end span, and
          # per-kind hold seconds the counterfactual differ attributes.
          "parity_mismatches", "sim_wall_s", "makespan_s",
          "quota_hold_s", "capacity_hold_s", "fragmentation_hold_s",
          "preempt_wait_hold_s", "priority_hold_s")
#: path components under which every plain numeric leaf is seconds of a
#: phase breakdown → lower is better
_LOWER_CONTAINERS = ("phases", "step_phases_s", "phase_span_durations")

DEFAULT_TOLERANCE = 0.10

#: lower-is-better (seconds) metrics where BOTH sides sit under this are
#: host-jitter territory, not a regression signal — skipped entirely
#: (a 0.6ms→0.8ms phase wobble must not fail a bench run).
NOISE_FLOOR_S = 0.005


def _unwrap(doc: Dict[str, Any]) -> Dict[str, Any]:
    """BENCH_r*.json wraps the bench output under "parsed"."""
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _direction(path: Tuple[str, ...]) -> Optional[str]:
    leaf = path[-1]
    if any(leaf == s or leaf.endswith(s) for s in _HIGHER):
        return "higher"
    if any(leaf == s or leaf.endswith(s) for s in _LOWER):
        return "lower"
    if any(p in _LOWER_CONTAINERS for p in path[:-1]):
        return "lower"
    return None


def flatten_metrics(doc: Dict[str, Any]) -> Dict[str, Tuple[str, float]]:
    """{dotted.path: (direction, value)} for every comparable numeric
    leaf of a bench document."""
    out: Dict[str, Tuple[str, float]] = {}

    def walk(node: Any, path: Tuple[str, ...]) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (str(k),))
            return
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return
        direction = _direction(path)
        if direction is not None:
            out[".".join(path)] = (direction, float(node))

    walk(_unwrap(doc), ())
    return out


def diff_bench(base: Dict[str, Any], candidate: Dict[str, Any],
               tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Compare candidate against base. Returns ``{"compared": n,
    "regressions": [...], "improvements": [...], "missing": [...]}``;
    each row is ``{metric, direction, base, candidate, change_pct}``.
    A metric worse than base by more than ``tolerance`` (relative) is a
    regression; metrics absent from either side are listed, never
    flagged (a CPU smoke run lacks the TPU points by design)."""
    a = flatten_metrics(base)
    b = flatten_metrics(candidate)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    missing = sorted(set(a) - set(b))
    compared = 0
    for name in sorted(set(a) & set(b)):
        direction, base_v = a[name]
        _, cand_v = b[name]
        if base_v == 0:
            continue
        if direction == "lower" and max(base_v, cand_v) < NOISE_FLOOR_S:
            continue
        compared += 1
        rel = (cand_v - base_v) / abs(base_v)
        row = {"metric": name, "direction": direction,
               "base": base_v, "candidate": cand_v,
               "change_pct": round(100.0 * rel, 2)}
        worse = rel < -tolerance if direction == "higher" \
            else rel > tolerance
        better = rel > tolerance if direction == "higher" \
            else rel < -tolerance
        if worse:
            regressions.append(row)
        elif better:
            improvements.append(row)
    return {"compared": compared, "regressions": regressions,
            "improvements": improvements, "missing": missing,
            "tolerance": tolerance}


def _load(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: bench document must be a JSON object")
    return doc


def format_report(result: Dict[str, Any], base_name: str,
                  cand_name: str) -> str:
    lines = [f"bench diff: {base_name} -> {cand_name}  "
             f"({result['compared']} comparable metric(s), tolerance "
             f"{result['tolerance']:.0%})"]
    for row in result["regressions"]:
        arrow = "↓" if row["direction"] == "higher" else "↑"
        lines.append(
            f"  REGRESSION {row['metric']}: {row['base']:g} -> "
            f"{row['candidate']:g}  ({arrow}{abs(row['change_pct']):.1f}%"
            f", {row['direction']}-is-better)")
    for row in result["improvements"]:
        lines.append(
            f"  improved   {row['metric']}: {row['base']:g} -> "
            f"{row['candidate']:g}  ({row['change_pct']:+.1f}%)")
    if result["missing"]:
        lines.append(f"  (not in candidate: "
                     f"{', '.join(result['missing'][:8])}"
                     + (" …" if len(result["missing"]) > 8 else "") + ")")
    if not result["regressions"]:
        lines.append("  no regressions")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tony-tpu bench diff",
        description="Compare two bench jsons; exit 1 on regression.")
    p.add_argument("base", help="baseline bench json (raw or BENCH_r*)")
    p.add_argument("candidate", help="candidate bench json")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help=f"relative tolerance before a worse metric "
                        f"counts as a regression (default "
                        f"{DEFAULT_TOLERANCE})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the diff as JSON")
    args = p.parse_args(argv)
    try:
        result = diff_bench(_load(args.base), _load(args.candidate),
                            tolerance=args.tolerance)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        print(format_report(result, args.base, args.candidate))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
