"""Bottleneck verdicts over the step-phase ring: where do the lost MFU go.

The classifier consumes per-task phase FRACTIONS (seconds attributed to
each phase divided by the attributed wall — telemetry.phase_stats()
shipped on the heartbeat beacon) and returns one of five evidence-backed
verdicts, in the PR 5 rule-engine style: every verdict names the numbers
that fired it, because an operator must be able to check the
classifier's work before spending a week on async checkpointing.

Thresholds (module constants, tunable in one place):

- INPUT_BOUND: ``data_wait + h2d`` ≥ 15% of step wall — the input
  pipeline (host read, H2D transfer) stalls the device; overlap/prefetch
  is the fix, not a faster kernel.
- CKPT_BOUND: ``ckpt_stall`` ≥ 10% — synchronous checkpoint saves stall
  steps; async/overlapped checkpointing (ROADMAP item 4a) is the fix.
- COMMS_BOUND: ``comms`` ≥ 15% — collective waits (instrument DCN
  all-reduce with ``telemetry.phase("comms")``) dominate; overlap the
  gradient all-reduce.
- COMPUTE_BOUND: ``step_compute`` ≥ 70% and no waste class fired — the
  healthy verdict: the chip is the limit, go after kernels/precision.
- UNDERUTILIZED: unattributed ``other`` ≥ 30%, or nothing else fired —
  wall time is leaking into host-side gaps (python overhead, logging,
  un-instrumented eval); profile the host, not the device.

Waste classes outrank COMPUTE_BOUND; among fired waste classes the
largest fraction wins (the biggest recoverable slice is where to aim).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

INPUT_BOUND = "INPUT_BOUND"
CKPT_BOUND = "CKPT_BOUND"
COMMS_BOUND = "COMMS_BOUND"
COMPUTE_BOUND = "COMPUTE_BOUND"
UNDERUTILIZED = "UNDERUTILIZED"

#: every category the classifier can return (golden-matrix test anchor).
VERDICTS = (INPUT_BOUND, CKPT_BOUND, COMMS_BOUND, COMPUTE_BOUND,
            UNDERUTILIZED)

# --- control-plane verdicts (coordinator self-observation) -----------------
# classify_coord consumes the COORDINATOR's own per-tick phase fractions
# (coordinator/coordphases.py) and names which O(n) control-plane loop is
# eating the tick — the numbers that aim the width restructuring
# (ROADMAP item 5: batched heartbeats, group-commit journal, hierarchical
# beacon fan-in, incremental cluster-spec deltas).
JOURNAL_BOUND = "JOURNAL_BOUND"
HEARTBEAT_BOUND = "HEARTBEAT_BOUND"
RENDEZVOUS_BOUND = "RENDEZVOUS_BOUND"
RPC_BOUND = "RPC_BOUND"
COORD_HEALTHY = "COORD_HEALTHY"

#: every category classify_coord can return (golden-matrix test anchor).
COORD_VERDICTS = (JOURNAL_BOUND, HEARTBEAT_BOUND, RENDEZVOUS_BOUND,
                  RPC_BOUND, COORD_HEALTHY)

#: schema version stamped into perf.json — bump on breaking changes.
PERF_SCHEMA = 1

INPUT_THRESHOLD = 0.15
CKPT_THRESHOLD = 0.10
COMMS_THRESHOLD = 0.15
COMPUTE_THRESHOLD = 0.70
OTHER_THRESHOLD = 0.30

#: verdict → one-line operator guidance (rendered by top/diagnose).
_ADVICE = {
    INPUT_BOUND: "the input pipeline stalls the device — raise prefetch "
                 "depth / overlap H2D, not the kernels",
    CKPT_BOUND: "checkpoint saves stall steps — move to async/overlapped "
                "checkpointing or widen the save interval",
    COMMS_BOUND: "collective waits dominate — bucket/overlap the DCN "
                 "all-reduce: set tony.train.accum-steps > 1 and tune "
                 "tony.train.bucket-mb (parallel/grad_sync.py)",
    COMPUTE_BOUND: "the chip is the limit — opt into low-precision "
                   "matmuls (tony.train.matmul-dtype=int8 | fp8_e4m3) "
                   "and the fused conv trunk; geometry is the remaining "
                   "lever",
    UNDERUTILIZED: "step wall leaks into unattributed host time — "
                   "instrument eval/logging phases or profile the host",
}


def phase_fractions(cum: Dict[str, float],
                    wall_s: float) -> Dict[str, float]:
    """Fraction of the attributed wall per phase (``other`` included when
    present in ``cum``; zero wall → {})."""
    try:
        wall = float(wall_s)
    except (TypeError, ValueError):
        return {}
    if wall <= 0:
        return {}
    out: Dict[str, float] = {}
    for name, secs in (cum or {}).items():
        try:
            out[str(name)] = max(0.0, float(secs)) / wall
        except (TypeError, ValueError):
            continue
    return out


def classify(fractions: Dict[str, float]) -> Dict[str, Any]:
    """One verdict over a fraction map. Returns ``{category, summary,
    advice, confidence, evidence: [..], fractions}`` — evidence lines
    carry the exact numbers and thresholds that fired."""
    f = {k: float(v) for k, v in (fractions or {}).items()}
    data = f.get("data_wait", 0.0) + f.get("h2d", 0.0)
    ckpt = f.get("ckpt_stall", 0.0)
    comms = f.get("comms", 0.0)
    compute = f.get("step_compute", 0.0)
    other = f.get("other", 0.0)
    evidence: List[str] = []
    waste = []
    if data >= INPUT_THRESHOLD:
        waste.append((data, INPUT_BOUND,
                      f"data_wait+h2d = {data:.1%} of step wall "
                      f"(threshold {INPUT_THRESHOLD:.0%})"))
    if ckpt >= CKPT_THRESHOLD:
        waste.append((ckpt, CKPT_BOUND,
                      f"ckpt_stall = {ckpt:.1%} of step wall "
                      f"(threshold {CKPT_THRESHOLD:.0%})"))
    if comms >= COMMS_THRESHOLD:
        waste.append((comms, COMMS_BOUND,
                      f"comms = {comms:.1%} of step wall "
                      f"(threshold {COMMS_THRESHOLD:.0%})"))
    if waste:
        waste.sort(reverse=True)
        frac, category, line = waste[0]
        evidence.append(line)
        for _, other_cat, other_line in waste[1:]:
            evidence.append(f"also fired: {other_cat} ({other_line})")
        evidence.append(f"step_compute = {compute:.1%}")
        if category == COMMS_BOUND:
            # Prescribe the fix this repo ships, not generic advice: the
            # comms phase is recorded by grad_sync's bucketed sync, and
            # these are its knobs.
            evidence.append(
                "knobs: tony.train.accum-steps (raise the compute:sync "
                "ratio), tony.train.bucket-mb (bucket/overlap the "
                "all-reduce)")
        confidence = min(0.95, 0.5 + frac)
    elif other >= OTHER_THRESHOLD:
        category = UNDERUTILIZED
        evidence.append(f"unattributed (other) = {other:.1%} of step "
                        f"wall (threshold {OTHER_THRESHOLD:.0%})")
        evidence.append(f"step_compute = {compute:.1%}")
        confidence = min(0.9, 0.4 + other)
    elif compute >= COMPUTE_THRESHOLD:
        category = COMPUTE_BOUND
        evidence.append(f"step_compute = {compute:.1%} of step wall "
                        f"(threshold {COMPUTE_THRESHOLD:.0%}); no waste "
                        f"class above threshold")
        evidence.append(
            "knobs: tony.train.matmul-dtype=int8|fp8_e4m3 (quantized "
            "projections, loss-parity-gated) — see docs/operations.md "
            "'Spending the verdict'")
        confidence = min(0.9, compute)
    else:
        category = UNDERUTILIZED
        evidence.append(
            f"no phase dominates: step_compute = {compute:.1%}, "
            f"data_wait+h2d = {data:.1%}, ckpt_stall = {ckpt:.1%}, "
            f"comms = {comms:.1%}, other = {other:.1%} — attribution is "
            f"spread thin (instrument the missing phases)")
        confidence = 0.4
    return {
        "category": category,
        "summary": _ADVICE[category],
        "advice": _ADVICE[category],
        "confidence": round(confidence, 3),
        "evidence": evidence,
        "fractions": {k: round(v, 4) for k, v in f.items()},
    }


#: control-plane thresholds: fraction of the coordinator's tick wall a
#: loop must eat before it is indicted (the tick wall includes the
#: monitor sleep, so even 15% of wall means the loop dominates the
#: coordinator's ACTIVE time many times over).
COORD_JOURNAL_THRESHOLD = 0.15
COORD_HEARTBEAT_THRESHOLD = 0.15
COORD_RENDEZVOUS_THRESHOLD = 0.15
COORD_RPC_THRESHOLD = 0.25

#: control-plane verdict → the restructure it prescribes. These name the
#: FUTURE knobs on purpose: the PR-12 width work (ROADMAP item 5) spends
#: exactly these verdicts, the way PR 10 spent COMMS_BOUND.
_COORD_ADVICE = {
    JOURNAL_BOUND: "fsync-per-journal-record dominates the tick — "
                   "group-commit the journal (batch appends per fsync) "
                   "before growing the gang further",
    HEARTBEAT_BOUND: "per-beat work (heartbeat scan + beacon fold) "
                     "dominates — batch/coalesce heartbeats and move to "
                     "hierarchical (per-jobtype sub-aggregator) beacon "
                     "fan-in",
    RENDEZVOUS_BOUND: "the global rendezvous barrier dominates — "
                      "hierarchical registration and incremental "
                      "cluster-spec deltas instead of full re-broadcast",
    RPC_BOUND: "RPC dispatch itself dominates — batch the per-task "
               "control RPCs (one frame per host, not per task) or "
               "shard the serve plane",
    COORD_HEALTHY: "the control plane keeps up at this width — no "
                   "restructure indicated",
}


def classify_coord(fractions: Dict[str, float]) -> Dict[str, Any]:
    """One control-plane verdict over a coordinator phase-fraction map
    (coordphases.fractions()). Same contract as classify(): every
    verdict is evidence-backed with the numbers and thresholds that
    fired, and the advice names the knob to spend it on."""
    f = {k: float(v) for k, v in (fractions or {}).items()}
    journal = f.get("journal_fsync", 0.0)
    beats = f.get("hb_scan", 0.0) + f.get("beacon_fold", 0.0)
    rendezvous = f.get("rendezvous_barrier", 0.0)
    rpc = f.get("rpc_serve", 0.0)
    idle = f.get("idle", 0.0)
    evidence: List[str] = []
    fired = []
    if journal >= COORD_JOURNAL_THRESHOLD:
        fired.append((journal, JOURNAL_BOUND,
                      f"journal_fsync = {journal:.1%} of tick wall "
                      f"(threshold {COORD_JOURNAL_THRESHOLD:.0%})"))
    if beats >= COORD_HEARTBEAT_THRESHOLD:
        fired.append((beats, HEARTBEAT_BOUND,
                      f"hb_scan+beacon_fold = {beats:.1%} of tick wall "
                      f"(threshold {COORD_HEARTBEAT_THRESHOLD:.0%})"))
    if rendezvous >= COORD_RENDEZVOUS_THRESHOLD:
        fired.append((rendezvous, RENDEZVOUS_BOUND,
                      f"rendezvous_barrier = {rendezvous:.1%} of tick "
                      f"wall (threshold "
                      f"{COORD_RENDEZVOUS_THRESHOLD:.0%})"))
    if rpc >= COORD_RPC_THRESHOLD:
        fired.append((rpc, RPC_BOUND,
                      f"rpc_serve = {rpc:.1%} of tick wall (threshold "
                      f"{COORD_RPC_THRESHOLD:.0%})"))
    if fired:
        fired.sort(reverse=True)
        frac, category, line = fired[0]
        evidence.append(line)
        for _, other_cat, other_line in fired[1:]:
            evidence.append(f"also fired: {other_cat} ({other_line})")
        evidence.append(f"idle = {idle:.1%}")
        confidence = min(0.95, 0.5 + frac)
    else:
        category = COORD_HEALTHY
        evidence.append(
            f"no control-plane loop above threshold: journal_fsync = "
            f"{journal:.1%}, hb_scan+beacon_fold = {beats:.1%}, "
            f"rendezvous_barrier = {rendezvous:.1%}, rpc_serve = "
            f"{rpc:.1%}, idle = {idle:.1%}")
        confidence = min(0.9, 0.4 + idle)
    return {
        "category": category,
        "summary": _COORD_ADVICE[category],
        "advice": _COORD_ADVICE[category],
        "confidence": round(confidence, 3),
        "evidence": evidence,
        "fractions": {k: round(v, 4) for k, v in f.items()},
    }


def build_perf_report(app_id: str,
                      per_task: Dict[str, Dict[str, Any]],
                      status: str = "") -> Dict[str, Any]:
    """The ``perf.json`` document: job-level phase totals (seconds, sum
    EXACTLY equals ``wall_s`` — the acceptance invariant), the job
    verdict over wall-weighted aggregate fractions, and per-task
    fractions + verdicts. ``per_task`` maps task_id → the beacon's
    ``step_phases`` payload ({"cum": {phase: s}, "wall_s": s,
    "steps": n, ...})."""
    agg: Dict[str, float] = {}
    wall_total = 0.0
    steps_total = 0.0
    tasks: Dict[str, Any] = {}
    for task_id, ph in sorted((per_task or {}).items()):
        if not isinstance(ph, dict):
            continue
        cum = ph.get("cum") or {}
        try:
            wall = float(ph.get("wall_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            wall = 0.0
        fr = phase_fractions(cum, wall)
        row: Dict[str, Any] = {"wall_s": round(wall, 4),
                               "steps": ph.get("steps"),
                               "fractions": {k: round(v, 4)
                                             for k, v in fr.items()}}
        if fr:
            row["verdict"] = classify(fr)["category"]
        tasks[task_id] = row
        wall_total += wall
        try:
            steps_total += float(ph.get("steps", 0.0) or 0.0)
        except (TypeError, ValueError):
            pass
        for name, secs in cum.items():
            try:
                agg[str(name)] = agg.get(str(name), 0.0) + float(secs)
            except (TypeError, ValueError):
                continue
    fractions = phase_fractions(agg, wall_total)
    doc: Dict[str, Any] = {
        "schema": PERF_SCHEMA,
        "app_id": app_id,
        "status": status,
        "generated_ms": int(time.time() * 1000),
        "steps": steps_total,
        "wall_s": round(wall_total, 4),
        "phases_s": {k: round(v, 4) for k, v in sorted(agg.items())},
        "fractions": {k: round(v, 4) for k, v in sorted(fractions.items())},
        "verdict": classify(fractions) if fractions else None,
        "tasks": tasks,
    }
    return doc


def save_perf(path: str, doc: Dict[str, Any]) -> None:
    """Atomic replace — readers see the whole report or the previous one."""
    from tony_tpu.utils.durable import atomic_write

    atomic_write(path, json.dumps(doc, indent=1,
                                  sort_keys=True).encode("utf-8"))


def load_perf(path: str) -> Optional[Dict[str, Any]]:
    """Decoded perf.json, or None when absent/torn/not-an-object."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None
