"""Workflow-scheduler adapter: scheduler job properties → a submittable job.

Reference: ``tony-azkaban/.../TonyJob.java`` — an Azkaban jobtype that
collects every job property under the ``tony.`` prefix into a generated
``tony.xml`` (:83-96) and assembles the CLI argument list for
``TonyClient`` (``getMainArguments`` :130-167, args enumerated in
``TonyJobArg.java``). The TPU analogue is scheduler-agnostic: any workflow
engine (Airflow operator, Azkaban jobtype shim, cron wrapper) that can
hand over a flat properties dict gets back a frozen config + argv, or can
submit directly in-process.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from tony_tpu.conf.config import TonyTpuConfig
from tony_tpu.conf import keys as K

# Reference TonyJobArg.java: the workflow-level pass-through arguments.
PROP_EXECUTABLE = "executable"          # -executes
PROP_TASK_PARAMS = "task_params"        # -task_params
PROP_SRC_DIR = "src_dir"                # -src_dir
PROP_PYTHON_VENV = "python_venv"        # -python_venv
PROP_PYTHON_BINARY = "python_binary_path"
CONF_PREFIX = "tony."


@dataclasses.dataclass
class WorkflowJob:
    """The generated artifacts: what the scheduler actually launches."""
    conf: TonyTpuConfig
    conf_file: str                       # generated config path (json)
    argv: List[str]                      # `python -m tony_tpu.cli ...`


def build_job(props: Dict[str, str], workdir: str,
              job_name: str = "workflow-job") -> WorkflowJob:
    """Convert scheduler props into a generated config file + CLI argv
    (reference ``TonyJob.getJobProps``→``tony.xml`` :83-96 +
    ``getMainArguments`` :130-167).

    Every ``tony.*`` property passes through to the config verbatim; the
    reference's dedicated CLI args map to their config keys."""
    conf = TonyTpuConfig()
    for k, v in sorted(props.items()):
        if k.startswith(CONF_PREFIX):
            conf.set(k, v)
    mapped = {
        PROP_EXECUTABLE: K.APPLICATION_EXECUTABLE,
        PROP_TASK_PARAMS: K.APPLICATION_TASK_PARAMS,
        PROP_SRC_DIR: K.SRC_DIR,
        PROP_PYTHON_VENV: K.PYTHON_VENV,
        PROP_PYTHON_BINARY: K.PYTHON_BINARY_PATH,
    }
    for prop, key in mapped.items():
        if props.get(prop):
            v = props[prop]
            if prop in (PROP_SRC_DIR, PROP_PYTHON_VENV) and \
                    not os.path.isabs(v) and os.path.exists(v):
                # Path props mean "relative to the scheduler's CWD" — pin
                # them before the conf file (written to workdir) would
                # re-anchor them to workdir at submit time.
                v = os.path.abspath(v)
            conf.set(key, v)
    if not conf.get(K.APPLICATION_NAME) or \
            conf.get(K.APPLICATION_NAME) == "tony-tpu":
        conf.set(K.APPLICATION_NAME, job_name)

    os.makedirs(workdir, exist_ok=True)
    conf_file = os.path.join(workdir, f"{job_name}.tony.json")
    with open(conf_file, "w", encoding="utf-8") as f:
        json.dump(conf.as_dict(), f, indent=2, sort_keys=True)

    argv = ["python", "-m", "tony_tpu.cli", "submit",
            "--conf-file", conf_file, "--workdir", workdir]
    return WorkflowJob(conf=conf, conf_file=conf_file, argv=argv)


def run_job(props: Dict[str, str], workdir: str,
            job_name: str = "workflow-job",
            listener: Optional[object] = None) -> Tuple[int, str]:
    """In-process submit for engines that can host Python directly (the
    ``HadoopJavaJob`` embedding path): returns (exit_code, app_id)."""
    from tony_tpu.client import TonyTpuClient

    job = build_job(props, workdir, job_name)
    client = TonyTpuClient(job.conf, workdir=workdir)
    if listener is not None:
        client.add_listener(listener)
    code = client.start()
    return code, client.app_id
