"""Configuration key registry: every key, its default, type and documentation.

Parity target: reference ``TonyConfigurationKeys.java`` (287 LoC; dynamic
per-jobtype keys by regex :171-239) and ``resources/tony-default.xml``
(108 properties), whose agreement is enforced by
``TestTonyConfigurationFields.java:17-45``. Here the registry *is* the defaults
file — a single source of truth — and the parity test checks that the
documented defaults table (``tony_tpu/conf/defaults.md``) matches this module.

Naming: dotted lowercase, rooted at ``tony.`` like the reference, so that
reference configs translate mechanically (``tony.worker.instances`` keeps its
meaning; GPU resource keys become chip keys).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Pattern, Tuple


@dataclasses.dataclass(frozen=True)
class ConfigKey:
    name: str
    default: Any
    type: type
    doc: str
    multi_value: bool = False  # append-on-merge (reference MULTI_VALUE_CONF :285)


_REGISTRY: Dict[str, ConfigKey] = {}


def _key(name: str, default: Any, typ: type, doc: str, multi_value: bool = False) -> str:
    _REGISTRY[name] = ConfigKey(name, default, typ, doc, multi_value)
    return name


# --- application ----------------------------------------------------------
APPLICATION_NAME = _key(
    "tony.application.name", "tony-tpu", str, "Application display name.")
APPLICATION_FRAMEWORK = _key(
    "tony.application.framework", "jax", str,
    "ML framework runtime: jax | tensorflow | pytorch | mxnet | horovod | generic "
    "(reference MLFramework enum TonyConfigurationKeys.java:12-17; jax is new).")
APPLICATION_QUEUE = _key(
    "tony.application.queue", "default", str, "Scheduler queue / reservation pool.")
APPLICATION_TIMEOUT_S = _key(
    "tony.application.timeout-s", 0, int,
    "Whole-job wall-clock timeout in seconds; 0 disables "
    "(reference tony.application.timeout, TonyClient.java:874-882).")
APPLICATION_RETRY_COUNT = _key(
    "tony.application.retry-count", 0, int,
    "Coordinator-level whole-job retries for INFRA_TRANSIENT failures "
    "(reference tony.am.retry-count, ApplicationMaster.java:356-371). "
    "USER_ERROR failures are terminal on first occurrence unless "
    "retry-user-errors is set; PREEMPTION failures draw on their own "
    "budget (preemption-retry-count) without consuming this one.")
APPLICATION_PREEMPTION_RETRY_COUNT = _key(
    "tony.application.preemption-retry-count", 3, int,
    "Whole-job retries for PREEMPTION failures (slice host reclaimed, "
    "spot notice, save-on-SIGTERM exits). Preemption is expected infra "
    "churn, so these retries do NOT consume tony.application.retry-count "
    "— a job preempted twice still has its full transient-failure budget. "
    "0 disables free preemption retries (preemptions then fail the job "
    "when retry-count is exhausted).")
APPLICATION_RETRY_USER_ERRORS = _key(
    "tony.application.retry-user-errors", False, bool,
    "Reference-compat escape hatch: when true, USER_ERROR failures "
    "(nonzero user exits) also consume tony.application.retry-count, "
    "like TonY's undiscriminating whole-job retry. Default false: a "
    "deterministic user crash burns retry epochs for nothing.")
APPLICATION_BACKEND = _key(
    "tony.application.backend", "local", str,
    "Cluster substrate: local (subprocesses on this host, the MiniCluster "
    "analogue) | tpu-slice (gang over a leased multi-host slice, "
    "cluster/tpu.py — the analogue of YARN container allocation, "
    "ApplicationMaster.java:1051-1175).")
SLICE_PROVISIONER = _key(
    "tony.slice.provisioner", "fake", str,
    "tpu-slice backend only: fake (LocalSimHostChannel inventory for "
    "tests/CI) | ssh (StaticSshProvisioner over tony.slice.hosts) | "
    "gcloud (GcloudTpuProvisioner — the framework creates/deletes TPU "
    "nodes itself via the Cloud TPU API; see tony.gcloud.*).")
SLICE_NUM_HOSTS = _key(
    "tony.slice.num-hosts", 1, int,
    "tpu-slice backend only: hosts per slice lease (all-or-nothing grant; "
    "SURVEY.md §7(a) slice-lease atomicity).")
SLICE_HOSTS = _key(
    "tony.slice.hosts", "", str,
    "tpu-slice+ssh only: comma-separated ssh targets (TPU VM inventory).")
SLICE_REMOTE_PYTHON = _key(
    "tony.slice.remote-python", "python3", str,
    "tpu-slice+ssh only: the interpreter that runs executors ON the TPU "
    "VMs (the coordinator's sys.executable is a path on the wrong "
    "machine).")
SLICE_FAKE_INVENTORY = _key(
    "tony.slice.fake-inventory", 0, int,
    "tpu-slice+fake only: total fake hosts in the provisioner inventory; "
    "0 means same as tony.slice.num-hosts (deny-capacity tests set it "
    "lower).")
GCLOUD_PROJECT = _key(
    "tony.gcloud.project", "", str,
    "tpu-slice+gcloud only: GCP project the provisioner creates TPU nodes "
    "in (cluster/gcloud.py — the YARN-RM role, "
    "ApplicationMaster.java:1051-1070, re-designed as the Cloud TPU API).")
GCLOUD_ZONE = _key(
    "tony.gcloud.zone", "", str,
    "tpu-slice+gcloud only: zone for TPU nodes (e.g. us-central2-b).")
GCLOUD_ACCELERATOR_TYPE = _key(
    "tony.gcloud.accelerator-type", "", str,
    "tpu-slice+gcloud only: TPU accelerator type to create (e.g. "
    "v5litepod-16); its host count must equal tony.slice.num-hosts.")
GCLOUD_RUNTIME_VERSION = _key(
    "tony.gcloud.runtime-version", "tpu-ubuntu2204-base", str,
    "tpu-slice+gcloud only: TPU VM runtime image version.")
GCLOUD_NODE_PREFIX = _key(
    "tony.gcloud.node-prefix", "tony", str,
    "tpu-slice+gcloud only: created node names are "
    "<prefix>-<random>; the random suffix avoids collisions across "
    "concurrent jobs (409s retry with a fresh name).")
GCLOUD_SSH_USER = _key(
    "tony.gcloud.ssh-user", "", str,
    "tpu-slice+gcloud only: login user for ssh channels onto the node's "
    "VMs; empty = the coordinator's current user.")
GCLOUD_SPOT = _key(
    "tony.gcloud.spot", False, bool,
    "tpu-slice+gcloud only: create preemptible (spot) nodes. Preemption "
    "is detected via the node state and recovers through the normal "
    "re-lease + retry-epoch machinery (plus the in-VM advance-notice "
    "watcher, executor/preemption.py).")
GCLOUD_NETWORK = _key(
    "tony.gcloud.network", "", str,
    "tpu-slice+gcloud only: VPC network for the node; empty = project "
    "default.")
GCLOUD_CREATE_TIMEOUT_S = _key(
    "tony.gcloud.create-timeout-s", 900, int,
    "tpu-slice+gcloud only: bound on create-operation + READY polling "
    "before the acquire fails (and deletes the half-created node).")
GCLOUD_POLL_INTERVAL_S = _key(
    "tony.gcloud.poll-interval-s", 5.0, float,
    "tpu-slice+gcloud only: cadence for operation/READY polling and for "
    "the lease's node-state health checks.")
GCLOUD_QUEUED_RESOURCE = _key(
    "tony.gcloud.queued-resource", False, bool,
    "tpu-slice+gcloud only: acquire capacity via the queued-resources "
    "API (request waits in the provider's queue until granted — the "
    "path reservations and spot capacity commonly require) instead of "
    "a direct node create. tony.gcloud.create-timeout-s bounds the "
    "whole wait.")
GCLOUD_CHANNEL = _key(
    "tony.gcloud.channel", "ssh", str,
    "tpu-slice+gcloud only: how to reach the node's VMs: ssh (production) "
    "| localsim (test substrate: each API-reported endpoint becomes a "
    "local process host, so the full create/preempt/delete lifecycle is "
    "e2e-testable against the fake API server).")
GCLOUD_API_ENDPOINT = _key(
    "tony.gcloud.api-endpoint", "", str,
    "tpu-slice+gcloud only: Cloud TPU API endpoint override (tests point "
    "this at tests/tpu_api_fake_server.py; empty = "
    "https://tpu.googleapis.com, or the TONY_TPU_API_ENDPOINT env var).")
APPLICATION_PROFILER_ENABLED = _key(
    "tony.application.profiler-enabled", False, bool,
    "Export TONY_PROFILE_DIR (under the job history dir) to the chief "
    "task so tony_tpu.profiler.trace_window captures XLA traces there; "
    "the portal lists them per job (SURVEY.md §5 tracing — the TPU-native "
    "complement to the reference's TB-only observability).")
APPLICATION_ENABLE_PREPROCESS = _key(
    "tony.application.enable-preprocess", False, bool,
    "Run the coordinator-local command as a preprocessing stage before "
    "scheduling any gang (reference tony.application.enable-preprocess, "
    "ApplicationMaster.doPreprocessingJob :714-766).")
COORDINATOR_COMMAND = _key(
    "tony.coordinator.command", "", str,
    "Command the coordinator runs in-process: the preprocessing stage when "
    "enable-preprocess is set, or the whole job in single-node mode (no "
    "jobtypes configured). Reference AM-local execution, "
    "ApplicationMaster.java:714.")
APPLICATION_TENSORBOARD_COMMAND = _key(
    "tony.application.tensorboard-command", "", str,
    "Command the CHIEF executor spawns alongside its user process with "
    "TB_PORT exported (e.g. 'tensorboard --logdir ... --port $TB_PORT'); "
    "killed when the task ends. The chief's TB URL is registered with the "
    "coordinator either way (reference TaskExecutor.java:311-319, "
    "ApplicationMaster.java:935-951; launching TB was user-script territory "
    "in the reference examples).")
APPLICATION_CHECKPOINT_DIR = _key(
    "tony.application.checkpoint-dir", "", str,
    "Shared checkpoint directory exported to every task as "
    "TONY_CHECKPOINT_DIR; with whole-job retry, user scripts restore from "
    "CheckpointManager.latest_step() there to resume across session epochs "
    "(the reference leaves this wholly to user code — SURVEY.md §5).")
APPLICATION_PREPARE_STAGE = _key(
    "tony.application.prepare-stage", "", str,
    "Comma list of jobtypes forming the prepare stage of the DAG "
    "(reference Utils.java:372-406).", multi_value=True)
APPLICATION_TRAINING_STAGE = _key(
    "tony.application.training-stage", "", str,
    "Comma list of jobtypes forming the training stage of the DAG.",
    multi_value=True)
APPLICATION_UNTRACKED_JOBTYPES = _key(
    "tony.application.untracked.jobtypes", "ps", str,
    "Jobtypes whose processes run forever and do not gate completion "
    "(reference TonyConfigurationKeys.java:252-253).", multi_value=True)
APPLICATION_STOP_ON_FAILURE_JOBTYPES = _key(
    "tony.application.stop-on-failure-jobtypes", "", str,
    "Jobtypes whose single-task failure fails the whole job immediately "
    "(reference TonySession.java:251-271).", multi_value=True)
APPLICATION_FAIL_ON_WORKER_FAILURE = _key(
    "tony.application.fail-on-worker-failure-enabled", False, bool,
    "If true, any tracked task failure fails the job without waiting "
    "(reference TonySession.java:251-271).")
APPLICATION_NUM_CLIENTS_TO_WAIT = _key(
    "tony.application.wait-for-client-finish", True, bool,
    "Coordinator waits for the client's finish signal before tearing down "
    "(reference ApplicationMaster.java:684).")
APPLICATION_SECURITY_ENABLED = _key(
    "tony.application.security.enabled", False, bool,
    "Enable token auth on the control-plane RPC "
    "(reference ApplicationMaster.java:433-452).")
SECURITY_TLS_CERT = _key(
    "tony.application.security.tls-cert", "", str,
    "PEM certificate path: set together with tls-key to wrap the "
    "control-plane RPC (and the portal, if started with it) in TLS. "
    "Clients PIN this exact cert (self-signed pairs need no CA); the "
    "path must be readable on every host (shared fs or staged).")
SECURITY_TLS_KEY = _key(
    "tony.application.security.tls-key", "", str,
    "PEM private-key path for tls-cert — needed only where servers run "
    "(the coordinator / portal host), never on task hosts.")

JAX_COMPILE_CACHE_DIR = _key(
    "tony.jax.compilation-cache-dir", "~/.cache/tony-tpu/jaxcache", str,
    "Persistent XLA compile cache exported to jax tasks as "
    "JAX_COMPILATION_CACHE_DIR (host-stable path, expanded on the task "
    "host, so repeat jobs skip first-compile — most of the cold "
    "submit-to-first-step). The task's own env wins; empty disables.")

# --- task / executor ------------------------------------------------------
TASK_HEARTBEAT_INTERVAL_MS = _key(
    "tony.task.heartbeat-interval-ms", 1000, int,
    "Executor→coordinator heartbeat cadence "
    "(reference TonyConfigurationKeys.java:143-144).")
TASK_MAX_MISSED_HEARTBEATS = _key(
    "tony.task.max-missed-heartbeats", 25, int,
    "Missed heartbeats before a task is deemed dead "
    "(reference TonyConfigurationKeys.java:145-147).")
TASK_METRICS_INTERVAL_MS = _key(
    "tony.task.metrics-interval-ms", 5000, int,
    "Resource-metrics sampling cadence (reference :149-150).")
TASK_REGISTRATION_TIMEOUT_S = _key(
    "tony.task.registration-timeout-s", 900, int,
    "Gang rendezvous timeout: all tasks must register within this window "
    "(reference tony.application.registration-timeout default 15 min, "
    "TonyConfigurationKeys.java:243-244).")
TASK_EXECUTOR_EXECUTION_TIMEOUT_S = _key(
    "tony.task.execution-timeout-s", 0, int,
    "Per-task user-process timeout; 0 disables "
    "(reference tony.task.executor.execution-timeout-ms).")
TASK_REUSE_PORT = _key(
    "tony.task.reuse-port", False, bool,
    "Hold the rendezvous port with SO_REUSEPORT between registration and "
    "user-process bind (reference ReusablePort.java:151-236).")
TASK_PORT_FILE = _key(
    "tony.task.port-file", "", str,
    "Optional file the executor writes its reserved rendezvous port to.")
TASK_COORDINATOR_LOSS_HEARTBEATS = _key(
    "tony.task.coordinator-loss-heartbeats", 3, int,
    "Consecutive FAILED heartbeat calls before the executor flips from "
    "heartbeating to reconnect mode (re-resolve the coordinator address, "
    "re-register with the existing task_id/port). 0 disables "
    "coordinator-loss detection (an executor then just logs failed "
    "beats, the pre-recovery behaviour).")
TASK_ORPHAN_DEADLINE_S = _key(
    "tony.task.orphan-deadline-s", 120, int,
    "How long an executor keeps the user process alive while it cannot "
    "reach ANY coordinator. A coordinator restart inside this window is "
    "invisible to training (the executor re-registers and carries on); "
    "past it the executor concludes it is orphaned, delivers the "
    "TERM-grace-KILL ladder to the user process group, and exits — no "
    "headless gang may keep burning TPU time forever.")
TASK_PROGRESS_TIMEOUT_S = _key(
    "tony.task.progress-timeout-s", 0, int,
    "Progress-based hang detection (coordinator/liveness.py): a task "
    "whose step counter (telemetry.step() beacons riding heartbeats) "
    "stops advancing for this long is declared HUNG — stack-dumped via "
    "the executor's dump signal, then TERM-grace-KILLed into an "
    "INFRA_TRANSIENT retry epoch. Warmup-aware: the deadline only arms "
    "once a task has reported its FIRST step, so compile/restore time "
    "never counts; tasks with no progress instrumentation keep "
    "heartbeat-only liveness (one-time warning, never a false kill). "
    "0 disables. Size it well above the longest legitimate gap between "
    "steps (eval pauses, checkpoint saves).")
TASK_PROGRESS_WARMUP_S = _key(
    "tony.task.progress-warmup-s", 300, int,
    "How long after registration a task may run without ever reporting "
    "a step counter before the coordinator emits the one-time "
    "TASK_PROGRESS_UNINSTRUMENTED warning and settles for heartbeat-only "
    "liveness. Only a warning gate — an uninstrumented task is never "
    "killed for lack of progress.")
TASK_HANG_DUMP_GRACE_S = _key(
    "tony.task.hang-dump-grace-s", 5, int,
    "Diagnostics window between declaring a task HUNG and killing it: "
    "the dump directive rides the next heartbeat response, the executor "
    "signals the user process group, and the pre-registered faulthandler "
    "dumps all-thread stacks into the task log. A step advance inside "
    "the window cancels the verdict.")
TASK_STRAGGLER_FRACTION = _key(
    "tony.task.straggler-fraction", 0.0, float,
    "Gang-level straggler policing (coordinator/liveness.py): a task "
    "whose step rate stays below this fraction of its jobtype's median "
    "rate for a sustained straggler-window-s emits TASK_STRAGGLER with "
    "its rate vs. the median. 0 disables. A 1-task gang can never "
    "straggle (its own rate is the median). Disable (or keep 0) for "
    "intentionally asymmetric gangs — heterogeneous batch sizes, "
    "pipeline stages with unequal work.")
TASK_STRAGGLER_WINDOW_S = _key(
    "tony.task.straggler-window-s", 60, int,
    "Sliding window for straggler step-rate estimation AND the sustain "
    "requirement: the below-fraction condition must hold continuously "
    "this long before TASK_STRAGGLER fires (momentary dips — GC, a slow "
    "batch — never flag).")
TASK_STRAGGLER_RESTART = _key(
    "tony.task.straggler-restart", False, bool,
    "Proactive straggler restart (off by default): a flagged straggler "
    "is killed into an INFRA_TRANSIENT retry epoch, on the theory that "
    "a fresh process/host beats a gang crawling at the straggler's "
    "pace. Leave off unless step rates are expected to be uniform.")

# --- elastic gangs (coordinator/elastic.py) -------------------------------
ELASTIC_ENABLED = _key(
    "tony.elastic.enabled", False, bool,
    "Elastic gang resizing: on host loss / preemption of a task of the "
    "elastic jobtype (or an explicit `tony-tpu resize`), the coordinator "
    "drains the survivors at a step barrier (a RESIZE directive rides the "
    "heartbeat response; user processes checkpoint-and-park via their "
    "save-on-SIGTERM handlers), re-meshes the gang at the new cardinality "
    "under a bumped, fenced membership generation, and training continues "
    "the SAME epoch from the last checkpoint — a bounded pause instead of "
    "a restart-with-replay. Off (default): host loss fails the epoch into "
    "the ordinary retry machinery.")
ELASTIC_JOBTYPE = _key(
    "tony.elastic.jobtype", "worker", str,
    "The jobtype whose gang is elastic (exactly one; the chief member — "
    "index 0 / the `chief` jobtype — is never shrunk away, and its loss "
    "is NOT absorbable: chief failure keeps its fail-the-epoch policy).")
ELASTIC_MIN_TASKS = _key(
    "tony.elastic.min-tasks", 1, int,
    "Floor on the elastic gang's size: a shrink (host-loss absorption or "
    "explicit resize) below this is refused — the loss then falls through "
    "to the ordinary failure-domain retry machinery. Size it to the "
    "smallest gang whose per-task memory still fits the resharded model.")
ELASTIC_DRAIN_GRACE_S = _key(
    "tony.elastic.drain-grace-s", 15, int,
    "TERM→KILL window for draining a survivor's user process at a resize: "
    "the save-on-SIGTERM handler (checkpoint/manager.py "
    "install_preemption_handler) gets this long to make its final save "
    "durable before the executor escalates. Exported to executors as "
    "the user-process kill grace for resize drains.")
ELASTIC_BARRIER_TIMEOUT_S = _key(
    "tony.elastic.barrier-timeout-s", 120, int,
    "Bound on a whole resize operation: drain of the survivors plus the "
    "re-registration barrier at the new cardinality. A resize that "
    "cannot complete inside this window fails the epoch INFRA_TRANSIENT "
    "into the ordinary retry machinery (which relaunches at the "
    "configured size) — a stuck resize must not hang the job forever.")

# --- tracing / live metrics (tony_tpu/tracing.py, tony_tpu/metrics.py) ---
TRACE_ENABLED = _key(
    "tony.trace.enabled", True, bool,
    "Distributed tracing across the control plane: client submit span, "
    "coordinator lifecycle/epoch/rendezvous/task spans, executor "
    "register/user-process/first-step spans, stitched into one tree per "
    "job via trace context on every RPC frame. The span log "
    "(trace.spans.jsonl) lives in the job history dir next to the jhist "
    "stream; export with `tony-tpu trace <app>` (Perfetto JSON) or the "
    "portal /trace/<app> timeline. Off = zero overhead (null spans).")
TRACE_RPC_SPANS = _key(
    "tony.trace.rpc-spans", "significant", str,
    "Server-side per-RPC spans: 'significant' (default — registration, "
    "results, kill; periodic methods like heartbeats and metrics pushes "
    "are aggregated into the RPC latency histograms instead of spamming "
    "the span log), 'all' (every method — debugging only; heartbeats "
    "arrive once per second per task), or 'off' (histograms only).")
METRICS_RING_POINTS = _key(
    "tony.metrics.ring-points", 512, int,
    "Ring-buffer depth of each in-memory gauge time series in the "
    "coordinator MetricsRegistry (sparklines for `tony-tpu top`, "
    "short-window rates). Bounded by design: Prometheus owns long-term "
    "storage; the registry is the scrape source, not a TSDB.")
METRICS_EXPORT_INTERVAL_S = _key(
    "tony.metrics.export-interval-s", 2.0, float,
    "Cadence at which the coordinator renders the Prometheus exposition "
    "into <job_dir>/metrics.prom (the portal /metrics scrape source) and "
    "snapshots counters for recovery. Control-plane-rate, not per-step.")

# --- alerting & SLOs (tony_tpu/alerts/) ------------------------------------
ALERTS_ENABLED = _key(
    "tony.alerts.enabled", True, bool,
    "Evaluate the default alert packs: job-scope rules on the "
    "coordinator monitor tick, fleet-scope rules on the fleet daemon "
    "tick. Both run behind the never-blocks-the-tick degrade contract "
    "(an evaluator crash disables alerting for that process life with "
    "one warning, never the tick). See docs/operations.md "
    "'Alerting & SLOs'.")
ALERTS_FOR_S = _key(
    "tony.alerts.for-s", 10.0, float,
    "Base for-duration (hysteresis) of the job-scope default pack: a "
    "breach must persist this long in `pending` before the rule fires — "
    "one bad tick never pages. Slower rules (input-bound, fsync-p99) "
    "use a multiple of this.")
ALERTS_FLEET_FOR_S = _key(
    "tony.alerts.fleet-for-s", 60.0, float,
    "For-duration of the fleet-scope default pack. Deliberately long: "
    "a fleet alert is a capacity/goodput story measured in minutes, "
    "not a single-tick blip.")
ALERTS_HEARTBEAT_AGE_S = _key(
    "tony.alerts.heartbeat-age-s", 30.0, float,
    "heartbeat-age rule threshold: page when any task's "
    "tony_task_heartbeat_age_seconds exceeds this — the gang is about "
    "to lose a member (the liveness reaper fires at "
    "max-missed-heartbeats x interval; this alert leads it).")
ALERTS_DATA_WAIT_FRACTION = _key(
    "tony.alerts.data-wait-fraction", 0.5, float,
    "input-bound rule threshold: warn when the windowed rate of the "
    "cumulative data_wait step phase (= fraction of wall time spent "
    "waiting on input) exceeds this — the live form of the post-hoc "
    "INPUT_BOUND verdict.")
ALERTS_FSYNC_P99_S = _key(
    "tony.alerts.fsync-p99-s", 0.05, float,
    "journal-fsync-p99 rule threshold (seconds): warn when the "
    "windowed p99 of tony_journal_fsync_seconds breaches it. Default "
    "aims ROADMAP item 3 by numbers — BENCH_SCALE_r01 measured p99 "
    "63ms at 512 virtual tasks, the JOURNAL_BOUND regime.")
ALERTS_MIN_STEPS_PER_SEC = _key(
    "tony.alerts.min-steps-per-sec", 0.0, float,
    "step-time-slo floor: a task sample below this steps/s rate is "
    "'bad' for the SLO's error budget. 0 disarms the SLO (the default "
    "— a universal floor would misfire across model sizes); set it "
    "per job from the model's known-good rate.")
ALERTS_SLO_OBJECTIVE = _key(
    "tony.alerts.slo-objective", 0.9, float,
    "SLO objective for the default burn-rate rules: the error budget "
    "is 1-objective (0.9 → 10% of samples may breach before the "
    "budget is spent).")
ALERTS_WINDOW_LONG_S = _key(
    "tony.alerts.window-long-s", 300.0, float,
    "Long burn-rate window of the job-scope SLOs (the fleet pack "
    "scales it up). Both windows must burn past the factor to fire — "
    "long resists blips, short makes recovery resolve fast.")
ALERTS_WINDOW_SHORT_S = _key(
    "tony.alerts.window-short-s", 60.0, float,
    "Short burn-rate window of the job-scope SLOs (the fleet pack "
    "scales it up).")
ALERTS_BURN_FACTOR = _key(
    "tony.alerts.burn-factor", 2.0, float,
    "Burn-rate factor: fire when the error budget burns at this "
    "multiple of the steady-state rate on BOTH windows (2.0 = the "
    "budget would be gone in half the objective period).")
ALERTS_GOODPUT_FLOOR = _key(
    "tony.alerts.goodput-floor", 0.5, float,
    "goodput-slo floor: a fleet-wide tony_fleet_goodput_fraction "
    "sample below this is 'bad' for the fleet SLO's budget — "
    "chip-seconds burning on overhead, not train steps.")
ALERTS_QUARANTINE_PER_MIN = _key(
    "tony.alerts.quarantine-rate-per-min", 3.0, float,
    "quarantine-spike rule threshold: warn when host quarantines are "
    "applied faster than this per minute (windowed rate of "
    "tony_fleet_quarantines_total) — a correlated hardware event or a "
    "flapping health scorer.")
ALERTS_QUEUE_WAIT_P99_S = _key(
    "tony.alerts.queue-wait-p99-s", 600.0, float,
    "queue-wait-p99 rule threshold (seconds): warn when the windowed "
    "p99 submit-to-grant wait breaches it — the pool is starved or "
    "fragmented.")

# --- control-plane self-observation (coordinator/coordphases.py) ----------
COORD_PHASE_RING_TICKS = _key(
    "tony.coord.phase-ring-ticks", 256, int,
    "Ring depth of the coordinator's own per-tick phase attribution "
    "(hb_scan / journal_fsync / beacon_fold / prom_export / rpc_serve / "
    "rendezvous_barrier — coordinator/coordphases.py): recent-window "
    "tick duration and phase fractions are computed over this many "
    "monitor ticks. Bounded by design, like the step-phase ring.")

# --- width harness (cluster/local.py virtual mode, bench --suite scale) ---
SCALE_VIRTUAL_EXECUTORS = _key(
    "tony.scale.virtual-executors", False, bool,
    "LocalSim width harness: the local backend launches each task as an "
    "in-process beat-only virtual executor (executor/virtual.py) instead "
    "of a subprocess — real RPC frames, real journal records, real "
    "heartbeat/beacon traffic, NO user process — so rendezvous, "
    "heartbeat and resize paths are exercised at 128–1024 tasks per box "
    "in CI-sized time (bench.py --suite scale). Never for real "
    "training: the tasks only pretend to step.")
SCALE_VIRTUAL_STEPS_PER_S = _key(
    "tony.scale.virtual-steps-per-s", 5.0, float,
    "Synthetic step rate a virtual executor's progress beacon reports "
    "(keeps progress-liveness and the metrics fold exercised at width).")
SCALE_VIRTUAL_RUN_S = _key(
    "tony.scale.virtual-run-s", 0.0, float,
    "How long a virtual executor beats before reporting exit 0 over the "
    "real register_execution_result path; 0 = beat until killed (the "
    "bench's sustain window stops the job explicitly).")
SCALE_VIRTUAL_PUMP_THREADS = _key(
    "tony.scale.virtual-pump-threads", 8, int,
    "Worker threads of the shared virtual-executor beat pump: hundreds "
    "of virtual tasks multiplex their register/heartbeat/result calls "
    "over this many threads (and RPC connections) — a thread per "
    "virtual task would not reach 1024 tasks per box.")

# --- on-demand device profiling (tony_tpu/telemetry.py capture agent) -----
PROFILE_ENABLED = _key(
    "tony.profile.enabled", True, bool,
    "On-demand device profiling: `tony-tpu profile <app>` rides a "
    "PROFILE directive on the heartbeat response, the target task arms "
    "jax.profiler at its next step boundary for N steps, and the trace "
    "artifact lands under <job_dir>/profile/ (portal /profile/<app>). "
    "Off = profile.start RPCs are refused (the static chief-only "
    "tony.application.profiler-enabled contract is unaffected).")
PROFILE_DEFAULT_STEPS = _key(
    "tony.profile.default-steps", 5, int,
    "Steps one on-demand capture brackets when `tony-tpu profile` is "
    "invoked without --steps. Captures start and stop at step "
    "boundaries, so N steps means N whole steps of device timeline.")
PROFILE_MAX_ARTIFACTS = _key(
    "tony.profile.max-artifacts", 8, int,
    "Ceiling on on-demand trace artifacts per job: profile.start is "
    "refused once <job_dir>/profile holds this many ondemand-* capture "
    "dirs (device traces are tens of MB each; an unbounded poll loop "
    "must not fill the history volume). Delete old dirs to make room.")

# --- automatic failure diagnosis (tony_tpu/diagnosis/) --------------------
DIAGNOSIS_ENABLED = _key(
    "tony.diagnosis.enabled", True, bool,
    "On any non-SUCCEEDED finish the coordinator assembles an incident "
    "bundle (events + journal + spans + metrics + log tails with "
    "extracted tracebacks/stack dumps + scrubbed config), runs the rule "
    "engine over it, writes <job_dir>/incident.json and emits "
    "JOB_DIAGNOSED with the verdict (category, blamed task, evidence). "
    "Read it with `tony-tpu diagnose <app>` or the portal "
    "/diagnose/<app>. Off = no automatic diagnosis (the CLI/portal can "
    "still run the engine post-hoc on the history dir).")
DIAGNOSIS_LOG_TAIL_BYTES = _key(
    "tony.diagnosis.log-tail-bytes", 65536, int,
    "How much of each task log's TAIL the diagnosis collector reads "
    "(seek-based — multi-GB logs cost only this much memory) when "
    "hunting tracebacks, stack dumps and OOM markers.")

# --- rpc ------------------------------------------------------------------
RPC_CALL_TIMEOUT_S = _key(
    "tony.rpc.call-timeout-s", 10.0, float,
    "Per-call send/recv deadline on executor control-plane RPCs. A "
    "WEDGED coordinator (accepts connections, never answers) then "
    "surfaces as an INFRA_TRANSIENT RpcTimeout instead of hanging the "
    "heartbeat thread forever — which is what lets coordinator-loss "
    "detection fire at all. 0 disables (unbounded waits).")
RPC_MAX_RETRIES = _key(
    "tony.rpc.max-retries", 10, int,
    "Transport-level reconnect budget per executor RPC call (reference "
    "10 fixed-sleep attempts, ApplicationRpcClient.java:66-76; here with "
    "exponential full-jitter backoff). Recovery tests lower it so "
    "coordinator-loss detection fires in seconds, not minutes.")
RPC_RETRY_SLEEP_S = _key(
    "tony.rpc.retry-sleep-s", 2.0, float,
    "Cap on any one transport retry sleep (the backoff envelope's "
    "max delay; base is a quarter of it).")

# --- coordinator ----------------------------------------------------------
COORDINATOR_MONITOR_INTERVAL_MS = _key(
    "tony.coordinator.monitor-interval-ms", 500, int,
    "Coordinator main monitoring loop cadence (reference AM 5 s loop "
    "ApplicationMaster.java:646; faster here — it is cheap in-process).")
COORDINATOR_HOST_KEY = _key(
    "tony.coordinator.host", "127.0.0.1", str,
    "Bind host for the coordinator control-plane server.")
COORDINATOR_PORT_KEY = _key(
    "tony.coordinator.port", 0, int,
    "Bind port for the coordinator control-plane server (0 = ephemeral).")
COORDINATOR_STOP_GRACE_S = _key(
    "tony.coordinator.stop-grace-s", 15, int,
    "Grace period when stopping running tasks "
    "(reference ApplicationMaster.java:694-711).")
COORDINATOR_JOURNAL_ENABLED = _key(
    "tony.coordinator.journal-enabled", True, bool,
    "Write-ahead session journal (session.journal.jsonl in the job "
    "history dir): every task state transition, registration, epoch "
    "reset and failure verdict is appended fsync'd, so a crashed "
    "coordinator can be restarted with --recover and resume the SAME "
    "epoch instead of losing the job (the YARN "
    "keepContainersAcrossApplicationAttempts analogue). Appends are "
    "control-plane-rate (per task transition, not per step); disable "
    "only on filesystems where fsync is pathological.")
COORDINATOR_REREGISTRATION_GRACE_S = _key(
    "tony.coordinator.reregistration-grace-s", 60, int,
    "Recovery grace window: how long a coordinator started with "
    "--recover waits for the surviving executors to re-register their "
    "existing task_id/host/port before declaring the gang lost "
    "(INFRA_TRANSIENT, normal retry-epoch machinery).")

# --- client ---------------------------------------------------------------
CLIENT_POLL_INTERVAL_MS = _key(
    "tony.client.poll-interval-ms", 1000, int,
    "Client app-report poll cadence (reference TonyClient.java:840-843).")
MAX_TOTAL_INSTANCES = _key(
    "tony.application.max-total-instances", -1, int,
    "Quota: maximum total task instances; -1 = unlimited "
    "(reference TonyClient.java:598-667).")
MAX_TOTAL_CHIPS = _key(
    "tony.application.max-total-chips", -1, int,
    "Quota: maximum total TPU chips across all jobtypes; -1 = unlimited "
    "(replaces the reference's GPU quota keys).")
SRC_DIR = _key(
    "tony.application.src-dir", "", str,
    "Directory of user code zipped and shipped to every task "
    "(reference tony.src.dir, TonyClient.java:189-228).")
PYTHON_VENV = _key(
    "tony.application.python-venv", "", str,
    "Optional archived Python environment localized for tasks "
    "(reference tony.python.venv).")
PYTHON_BINARY_PATH = _key(
    "tony.application.python-binary-path", "python3", str,
    "Python interpreter used to build task commands when `tony.<job>.command` "
    "is not given (reference TonyClient.buildTaskCommand :454-475).")
EXECUTION_ENV = _key(
    "tony.application.execution-env", "", str,
    "Comma list of KEY=VALUE pairs exported into every task environment "
    "(reference tony.execution.env).", multi_value=True)
CONTAINER_RESOURCES = _key(
    "tony.application.resources", "", str,
    "Comma list of extra files (SRC[::NAME][#archive]) localized to all tasks "
    "(reference LocalizableResource.java:20-30).", multi_value=True)

# --- history / events -----------------------------------------------------
HISTORY_LOCATION = _key(
    "tony.history.location", "", str,
    "Root directory for job history (empty = <workdir>/tony-history) "
    "(reference tony.history.location).")
HISTORY_MOVER_INTERVAL_S = _key(
    "tony.history.mover-interval-s", 300, int,
    "Intermediate→finished history mover cadence "
    "(reference HistoryFileMover.java:74-121, 5 min).")
HISTORY_PURGER_INTERVAL_S = _key(
    "tony.history.purger-interval-s", 21600, int,
    "History retention purger cadence (reference 6 h).")
HISTORY_RETENTION_DAYS = _key(
    "tony.history.retention-days", 30, int,
    "Days of finished history kept (reference 30 days).")
KEEP_FAILED_DIRS = _key(
    "tony.keep-failed-task-dirs", False, bool,
    "Keep working dirs of failed tasks for debugging.")

# --- TPU topology ---------------------------------------------------------
TPU_TOPOLOGY = _key(
    "tony.tpu.topology", "", str,
    "Requested slice topology, e.g. 'v5p-32' or '2x2x4'; empty = use all "
    "locally visible devices. The mesh builder consumes this (SURVEY.md §7.7).")
TPU_MESH_SHAPE = _key(
    "tony.tpu.mesh-shape", "", str,
    "Logical mesh axes as 'name=size,name=size' over the canonical axes "
    "dp/fsdp/pp/ep/sp/tp (tony_tpu.parallel.MeshSpec.from_string), e.g. "
    "'fsdp=4,tp=2'. One size may be -1 (inferred). Empty = pure-dp mesh "
    "over all devices.")

# --- training hot loop (parallel/grad_sync.py, ops/quant.py) --------------
TRAIN_ACCUM_STEPS = _key(
    "tony.train.accum-steps", 1, int,
    "Microbatched gradient accumulation: the global batch is split into "
    "this many microbatches per optimizer step (parallel/grad_sync.py "
    "jit_train_step_accum). Raises the compute:sync ratio — the first "
    "knob a COMMS_BOUND verdict prescribes. 1 = no accumulation.")
TRAIN_BUCKET_MB = _key(
    "tony.train.bucket-mb", 32, int,
    "Gradient-sync bucket size in MiB: accumulated grads are cross-slice "
    "all-reduced bucket-by-bucket in tree-flatten order (order-stable, "
    "so results match the monolithic psum), letting XLA overlap "
    "independent bucket collectives instead of serializing one monolith "
    "behind backward. A param larger than the bucket gets its own "
    "bucket. Smaller buckets = more overlap, more collective launches.")
TRAIN_MATMUL_DTYPE = _key(
    "tony.train.matmul-dtype", "", str,
    "Opt-in low-precision matmul path for the flagship transformer's "
    "attention/MLP projections (ops/quant.py): 'int8' (symmetric "
    "per-channel, 2x MXU rate on v5e) | 'fp8_e4m3'. Forward-only: "
    "backward stays in the activation dtype (straight-through), the "
    "embedding/LM head are never quantized, and an unsupported backend "
    "degrades to bf16 with a one-time warning on the metrics beacon. "
    "Empty = bitwise-identical bf16/f32 behaviour (the knob off IS the "
    "old code path). Unsafe for loss-scale-sensitive runs — see "
    "docs/operations.md 'Spending the verdict'.")

# --- fault injection (tony_tpu/faults.py) ---------------------------------
FAULT_SEED = _key(
    "tony.fault.seed", 0, int,
    "Seed for the deterministic fault-injection harness: per-site RNGs "
    "are seeded with (seed, site), and the shared retry-backoff jitter "
    "is seeded too, so a rehearsed failure replays identically.")


def fault_key(site: str) -> str:
    """Conf key for an injection site: 'rpc.send' → 'tony.fault.rpc-send',
    'user.slow_step' → 'tony.fault.user-slow-step' (key names are
    dash-only; site names keep their python-ish underscores)."""
    return f"tony.fault.{site.replace('.', '-').replace('_', '-')}"


# One registered key per injection site (tony_tpu/faults.py SITES); the
# value is a spec like 'first:2', 'at:3', 'every:5', 'p:0.3,session:0'.
FAULT_RPC_CONNECT = _key(
    "tony.fault.rpc-connect", "", str,
    "Inject a connection failure before RPC client connects "
    "(spec grammar: tony_tpu/faults.py).")
FAULT_RPC_SEND = _key(
    "tony.fault.rpc-send", "", str,
    "Inject a dropped-connection failure before an RPC request is sent.")
FAULT_RPC_SLOW = _key(
    "tony.fault.rpc-slow", "", str,
    "Inject latency into RPC client calls: firings delay the request by "
    "'amt:X' seconds before it is sent — the deterministic exercise for "
    "trace spans and the RPC latency histograms (a slow-control-plane "
    "rehearsal that never drops a frame).")
FAULT_HEARTBEAT = _key(
    "tony.fault.heartbeat", "", str,
    "Make the executor silently skip heartbeats that fire this spec "
    "(the conf-driven generalization of TONY_TEST_NUM_HB_MISS).")
FAULT_EXECUTOR_SPAWN = _key(
    "tony.fault.executor-spawn", "", str,
    "Fail the backend's executor process spawn (launch-path fault).")
FAULT_STORAGE_PUT = _key(
    "tony.fault.storage-put", "", str,
    "Inject a transient store error on put_file (absorbed by the shared "
    "retry policy — the GCS 503-burst rehearsal).")
FAULT_STORAGE_GET = _key(
    "tony.fault.storage-get", "", str,
    "Inject a transient store error on get_file.")
FAULT_CHECKPOINT_SAVE = _key(
    "tony.fault.checkpoint-save", "", str,
    "Fail CheckpointManager.save before the write starts.")
FAULT_COORDINATOR_CRASH = _key(
    "tony.fault.coordinator-crash", "", str,
    "Hard-kill the coordinator process (os._exit, no teardown — the "
    "SIGKILL shape) from inside its monitor loop when the spec fires; "
    "the call counter is monitor iterations. Drives the journal + "
    "--recover path from the deterministic harness.")
FAULT_EXECUTOR_REREGISTER = _key(
    "tony.fault.executor-reregister", "", str,
    "Drop an executor's re-registration attempt during coordinator-loss "
    "reconnect (raises like a transport reset; the reconnect loop "
    "retries until the orphan deadline).")
FAULT_USER_HANG = _key(
    "tony.fault.user-hang", "", str,
    "Freeze the user process's PROGRESS while it keeps running (and its "
    "executor keeps heartbeating): telemetry.step recordings that fire "
    "this spec are silently dropped, so the step counter stops advancing "
    "— the exact shape progress-based hang detection must catch. "
    "'after:N' freezes everything past the first N steps.")
FAULT_USER_SLOW_STEP = _key(
    "tony.fault.user-slow-step", "", str,
    "Skew one task's step rate: telemetry.step recordings that fire this "
    "spec are delayed by 'amt:X' seconds, driving the task's rate below "
    "the gang median — the straggler-policing drill. Combine with the "
    "'task:<job>:<idx>' filter to slow a single gang member.")
FAULT_POOL_LEASE = _key(
    "tony.fault.pool-lease", "", str,
    "Fail the backend's warm-pool lease attempt before the RPC (refused "
    "lease / unreachable daemon shape); the launch must degrade to a "
    "cold spawn, never a job failure.")
FAULT_POOL_STALE = _key(
    "tony.fault.pool-stale", "", str,
    "Simulate the pool daemon's stale-generation lease refusal (a "
    "superseded epoch trying to lease); the launch degrades to a cold "
    "spawn. The daemon also enforces the REAL check from the generation "
    "carried in each lease.")
FAULT_POOL_ADOPT = _key(
    "tony.fault.pool-adopt", "", str,
    "Kill a granted lease at adoption time (leased executor dead before "
    "the task starts); the backend discards the lease at the daemon — "
    "a dirty lease is never reused — and cold-spawns.")
FAULT_HOST_LOSS = _key(
    "tony.fault.host-loss", "", str,
    "Simulate sudden host death from inside the executor: a firing "
    "SIGKILLs the user process group and hard-exits the executor "
    "(os._exit 137) — everything on the 'host' dies at once, the shape "
    "elastic shrink-and-continue must absorb. The call counter is "
    "heartbeats, so 'task:worker:2,after:20' kills one virtual host a "
    "deterministic ~20 beats in.")
FAULT_RESIZE_BARRIER = _key(
    "tony.fault.resize-barrier", "", str,
    "Fail the post-remesh re-registration barrier of an elastic resize "
    "(checked once per resize, right after the new topology is applied): "
    "the resize aborts into an INFRA_TRANSIENT epoch failure — the "
    "ordinary retry machinery relaunches at the configured size.")
FAULT_RESIZE_REMESH = _key(
    "tony.fault.resize-remesh", "", str,
    "Fail the application of an elastic resize's new topology (checked "
    "once per resize, before the member set is rebuilt): the resize "
    "aborts into an INFRA_TRANSIENT epoch failure.")
FAULT_QUANT_PROBE = _key(
    "tony.fault.quant-probe", "", str,
    "Fail the quantized-matmul backend support probe (ops/quant.py): a "
    "firing makes resolve_mode treat the requested int8/fp8 path as "
    "unsupported on this backend — the model must degrade to the bf16 "
    "path with a one-time warning riding the metrics beacon, never fail "
    "the job.")
FAULT_COORD_SLOW_TICK = _key(
    "tony.fault.coord-slow-tick", "", str,
    "Inject latency into the coordinator's monitor tick: firings stall "
    "the tick by 'amt:X' seconds before any per-tick work runs — the "
    "overloaded-control-plane shape the coordinator's own phase "
    "accounting (tony_coord_phase_seconds, tick duration in `top`) must "
    "surface. The call counter is monitor iterations, like "
    "coordinator.crash.")
FAULT_FLEET_GRANT = _key(
    "tony.fault.fleet-grant", "", str,
    "Fail a fleet grant at apply time (tony_tpu/fleet/daemon.py), after "
    "the placement decision but before the job is spawned — the "
    "unspawnable-grant shape. The job stays QUEUED and is retried on a "
    "later tick; a grant failure must never lose a submission.")
FAULT_FLEET_PREEMPT = _key(
    "tony.fault.fleet-preempt", "", str,
    "Fail a fleet preempt-to-reclaim at apply time, before the victim's "
    "elastic shrink RPC is issued — the unreachable-victim shape. The "
    "preemption (and the grant waiting on it) is retried on a later "
    "tick; the victim keeps running undisturbed.")
FAULT_FLEET_LEDGER = _key(
    "tony.fault.fleet-ledger", "", str,
    "Fail a fleet goodput-ledger fold (tony_tpu/fleet/ledger.py via the "
    "daemon) — the corrupt-artifact shape. The fleet degrades to "
    "counters-only (no goodput gauges, ledger omitted from status) with "
    "a one-time warning; the scheduler tick never blocks or fails.")
FAULT_FLEET_EXPLAIN = _key(
    "tony.fault.fleet-explain", "", str,
    "Fail the write of a REC_FLEET_DECISION journal record (the "
    "scheduler decision explainer's write-ahead stream) — the full-disk "
    "shape on the observability path. The decision is still applied to "
    "the in-memory ring and the FLEET_JOB_HELD event still fires; one "
    "warning, scheduling unaffected.")
FAULT_CKPT_ASYNC_WRITE = _key(
    "tony.fault.ckpt-async-write", "", str,
    "Fail the checkpoint manager's background writer before a snapshot "
    "is serialized (tony_tpu/checkpoint/manager.py) — the torn "
    "in-flight-async-save shape. The step is NOT committed (no "
    "manifest); restore falls back to the last committed step and "
    "training continues — an async save failure must never crash the "
    "job.")
FAULT_MIGRATE_SNAPSHOT = _key(
    "tony.fault.migrate-snapshot", "", str,
    "Fail a live migration at the snapshot seal (checked once per "
    "migration, after the gang drained but before the topology moves): "
    "the migration aborts into an INFRA_TRANSIENT epoch failure — the "
    "ordinary retry ladder relaunches on the ORIGINAL slice, so a "
    "failed migration is never worse than a plain host loss.")
FAULT_MIGRATE_ADOPT = _key(
    "tony.fault.migrate-adopt", "", str,
    "Fail a live migration at destination adoption (checked once per "
    "migration, after the topology moved but before the destination "
    "executors launch) — the unadoptable-target shape; the migration "
    "aborts into an INFRA_TRANSIENT epoch failure and the retry "
    "machinery relaunches.")
FAULT_SLICE_PREEMPT = _key(
    "tony.fault.slice-preempt", "", str,
    "Mark one fleet-held slice as dying on the reclaim-notice poll "
    "(tony_tpu/fleet/daemon.py) — the queued-resource spot-reclaim "
    "advance notice. The fleet must proactively migrate tenants off "
    "the dying slice instead of absorbing the loss; the call counter "
    "is daemon ticks.")
FAULT_PROFILE_CAPTURE = _key(
    "tony.fault.profile-capture", "", str,
    "Fail an on-demand device capture at the step boundary that would "
    "arm jax.profiler (unsupported runtime / profiler crash shape): the "
    "task reports PROFILE_FAILED on its next beat and training "
    "continues — capture must never kill or stall the job.")
FAULT_RPC_PARTITION = _key(
    "tony.fault.rpc-partition", "", str,
    "Cut the RPC wire asymmetrically (tony_tpu/rpc/wire.py): 'dir:c2s' "
    "drops request frames before they are sent (the callee never sees "
    "them), 'dir:s2c' drops RESPONSE frames after the callee already "
    "processed the request — its side effects land, the caller sees a "
    "reset and retries. 'peer:NAME' scopes the cut to one labelled "
    "wire (coordinator/pool/fleet). No dir: token = both directions.")
FAULT_DISK_FULL = _key(
    "tony.fault.disk-full", "", str,
    "Raise ENOSPC on a durable AppendLog append (utils/durable.py) — "
    "the journal-disk-full shape. Writers must degrade LOUDLY: the "
    "coordinator monitor folds it into a terminal INFRA verdict, the "
    "fleet daemon stops instead of scheduling against a dead journal, "
    "and --recover replays the committed prefix.")
FAULT_DISK_TORN = _key(
    "tony.fault.disk-torn", "", str,
    "Tear a durable write (utils/durable.py): an AppendLog append "
    "writes a partial record then fails EIO, and atomic_write drops "
    "the rename (the old bytes survive) — the power-cut-mid-write "
    "shape the replay-of-prefix readers must absorb.")
FAULT_HOST_FLAKY = _key(
    "tony.fault.host-flaky", "", str,
    "Make one pool host flaky (fleet daemon health tick): each firing "
    "attributes an INFRA_TRANSIENT failure to the host and kills the "
    "job running on it — the recurring-bad-hardware shape. Pin the "
    "host with 'task:<host>' (e.g. 'prob:0.4,task:s0h2'); the health "
    "ledger must quarantine it and retries must route around it.")
FAULT_HEALTH_PROBE = _key(
    "tony.fault.health-probe", "", str,
    "Fail a preflight host probe (fleet/health.preflight_probe), "
    "filtered per host via 'task:<host>'. The grant must self-repair: "
    "cordon the failing host and substitute a spare before anything "
    "spawns on it.")
FAULT_ALERTS_EVAL = _key(
    "tony.fault.alerts-eval", "", str,
    "Fail an alert-pack evaluation (coordinator monitor tick or fleet "
    "daemon tick, tony_tpu/alerts/) — the broken-evaluator shape. The "
    "tick must degrade: alerting disables for the rest of that process "
    "life with one warning; scheduling/monitoring never block.")

# --- warm executor pool (tony_tpu/pool.py) --------------------------------
POOL_DIR = _key(
    "tony.pool.dir", "", str,
    "Directory of a running warm-executor pool (tony-tpu pool start). "
    "When set, the local backend tries to ADOPT a pre-warmed executor "
    "(Python up, tony_tpu + jax imported, compile cache mounted) via a "
    "pool.lease RPC before cold-spawning; any pool failure degrades to "
    "the cold path. Empty = no pool. Do NOT point jobs at a pool started "
    "under different credentials or execution env — warm workers carry "
    "the environment of their spawn time (see docs/operations.md).")
POOL_SIZE = _key(
    "tony.pool.size", 2, int,
    "Warm executors the pool daemon keeps ready. Each lease consumes one "
    "permanently (used/crashed workers are discarded, never re-pooled); "
    "the daemon replenishes in the background.")
POOL_MAX_LEASE_AGE_S = _key(
    "tony.pool.max-lease-age-s", 600, int,
    "Hygiene ceiling on warm-worker age: a worker older than this is "
    "never leased and is recycled by the daemon (bounds credential/env "
    "drift between pool start and adoption — a rotated storage token or "
    "changed execution env reaches new workers within this window).")
POOL_PRELOAD = _key(
    "tony.pool.preload", "jax", str,
    "Comma-separated modules each warm worker imports while idle (on top "
    "of the always-preloaded executor stack). 'jax' also initializes the "
    "backend — the multi-second cold-start slice the pool exists to "
    "hide. Empty = interpreter + tony_tpu only.")

# --- fleet: persistent multi-job gang scheduler (tony_tpu/fleet/) ---------
FLEET_DIR = _key(
    "tony.fleet.dir", "", str,
    "Directory of a running fleet daemon (tony-tpu fleet start) — the "
    "persistent cluster scheduler that owns a shared slice pool and "
    "gang-schedules many jobs against it with priorities, per-tenant "
    "quotas, bin-packing and preempt-to-reclaim (the YARN-RM role the "
    "reference outsourced, SURVEY §1 L4/L3). Empty = <workdir>/fleet "
    "for the fleet CLI verbs.")
FLEET_SLICES = _key(
    "tony.fleet.slices", 1, int,
    "TPU slices the fleet pool owns. Each slice contributes "
    "tony.fleet.hosts-per-slice hosts; a sub-slice job is bin-packed "
    "into ONE slice (gang locality), a larger job takes whole slices "
    "plus a best-fit remainder.")
FLEET_HOSTS_PER_SLICE = _key(
    "tony.fleet.hosts-per-slice", 8, int,
    "Hosts per pool slice. The policy engine accounts grants in hosts; "
    "granted jobs launch with tony.worker.instances = granted hosts.")
FLEET_QUOTAS = _key(
    "tony.fleet.quotas", "", str,
    "Per-tenant host quotas as 'tenant=hosts,tenant=hosts'. A tenant at "
    "its quota QUEUES (quota-denied submissions never block other "
    "tenants' grants — no head-of-line quota starvation); absent "
    "tenants are unlimited. Empty = no quotas.")
FLEET_TICK_INTERVAL_S = _key(
    "tony.fleet.tick-interval-s", 0.5, float,
    "Fleet scheduler loop cadence: job completion polling, grant/"
    "preempt plan application, grow-back restores, and the fleet.prom/"
    "fleet.status.json export all run on this tick.")
FLEET_POOL_DIR = _key(
    "tony.fleet.pool-dir", "", str,
    "Warm executor pool (tony_tpu/pool.py) the fleet points EVERY "
    "granted job at (tony.pool.dir is set on the grant's conf): each "
    "tenant's resubmit then adopts pre-warmed executors instead of "
    "cold-spawning. Empty = granted jobs keep whatever pool their own "
    "conf names (usually none).")
FLEET_COMPILE_CACHE_ROOT = _key(
    "tony.fleet.compile-cache-root", "", str,
    "Root of the shared per-model XLA compile-cache mounts: a grant "
    "whose submission names a model gets tony.jax.compilation-cache-dir "
    "= <root>/<model>, so every tenant resubmitting the same model — "
    "not just the first — hits the warm-compile path. Empty = no "
    "shared cache injection.")
FLEET_PREEMPT_MIN_HOSTS = _key(
    "tony.fleet.preempt-min-hosts", 1, int,
    "Default floor a preempt-to-reclaim shrink may take an elastic "
    "victim down to when the submission does not name its own "
    "min_hosts. Victims are shrunk via the coordinator's elastic "
    "resize (drain→remesh, no epoch burned), never killed.")
FLEET_DECISION_RING = _key(
    "tony.fleet.decision-ring", 64, int,
    "Bound on the per-job scheduler-decision ring behind `tony-tpu "
    "fleet explain`: the last N hold-reason transitions (quota / "
    "capacity / fragmentation / priority-held / preempt-wait) are kept "
    "in memory per job; the full history is in the REC_FLEET_DECISION "
    "journal records.")
FLEET_LEDGER_INTERVAL_S = _key(
    "tony.fleet.ledger-interval-s", 5.0, float,
    "Cadence of the goodput-ledger refresh for RUNNING jobs (terminal "
    "jobs fold exactly once at finish). Each refresh reads the running "
    "jobs' span trees / perf artifacts into queued/startup/train/stall "
    "phase accounting — too hot for every scheduler tick at 50 jobs, "
    "cheap at this interval.")
FLEET_SIM_PREEMPTION = _key(
    "tony.fleet.sim-preemption", True, bool,
    "What-if simulator toggle (`tony-tpu fleet whatif --set`): False "
    "re-runs the recorded workload with every gang RIGID (min_hosts "
    "forced to 0, so the preemption planner finds no elastic victims "
    "and defrag finds no movers). Measures how much of the recorded "
    "goodput the elastic-shrink machinery actually bought.")
FLEET_SIM_DEFRAG = _key(
    "tony.fleet.sim-defrag", True, bool,
    "What-if simulator toggle: False disables defragmentation "
    "migrations in the counterfactual — a fragmentation-held job waits "
    "for natural drains instead of a planned one-mover consolidation. "
    "Attributes fragmentation-hold seconds to the defrag planner.")
FLEET_SIM_RESTORE = _key(
    "tony.fleet.sim-restore", True, bool,
    "What-if simulator toggle: False disables grow-back restores — "
    "preempted jobs stay at their shrunk size to job end. Shows how "
    "much queue-idle capacity the restore path actually recycles.")

# --- fleet host health (tony_tpu/fleet/health.py) -------------------------
HEALTH_ENABLED = _key(
    "tony.health.enabled", True, bool,
    "Master switch for the fleet host-health subsystem: the "
    "failure-attribution ledger, quarantine state machine, preflight "
    "probes and slice blast-radius detection. Off = every host is "
    "always placeable (the pre-health fleet).")
HEALTH_HALF_LIFE_S = _key(
    "tony.health.score-half-life-s", 300.0, float,
    "Half-life of a host's failure-attribution score: each attributed "
    "infra failure adds its kind weight, and the total decays by half "
    "every this-many seconds — a burst quarantines, ancient history "
    "does not.")
HEALTH_SUSPECT_THRESHOLD = _key(
    "tony.health.suspect-threshold", 1.0, float,
    "Decayed score at which a host turns SUSPECT — still placeable, "
    "but counted toward the slice blast-radius correlation window.")
HEALTH_QUARANTINE_THRESHOLD = _key(
    "tony.health.quarantine-threshold", 3.0, float,
    "Decayed score at which a host is QUARANTINED: removed from the "
    "placement pool (journaled as REC_FLEET_HEALTH so --recover "
    "resumes the same cordon set) until its cooldown expires into "
    "probation.")
HEALTH_QUARANTINE_S = _key(
    "tony.health.quarantine-s", 120.0, float,
    "Base quarantine cooldown. After it expires the host enters "
    "PROBATION and must run one clean canary lease to rejoin the "
    "pool; a failed canary re-quarantines with this cooldown doubled "
    "(exponential backoff).")
HEALTH_PROBATION_PRIORITY = _key(
    "tony.health.probation-canary-priority", 0, int,
    "Maximum job priority allowed to carry a probation canary host: "
    "only jobs at or below it may have one cordoned-but-recovering "
    "host substituted into their placement (at most one per slice), "
    "so re-admission risk lands on preemptible work.")
HEALTH_BLAST_N = _key(
    "tony.health.slice-blast-n", 2, int,
    "Correlated-failure threshold: this many distinct hosts of one "
    "slice going suspect-or-worse inside tony.health.slice-blast-"
    "window-s marks the whole slice sick — it is cordoned and its "
    "jobs are evacuated by live migration.")
HEALTH_BLAST_WINDOW_S = _key(
    "tony.health.slice-blast-window-s", 120.0, float,
    "Sliding window (seconds of attributed-failure evidence age) for "
    "the slice blast-radius correlation above.")

# --- portal ---------------------------------------------------------------
PORTAL_PORT = _key(
    "tony.portal.port", 19886, int,
    "History web portal port (reference tony-portal Play app).")

APPLICATION_EXECUTABLE = _key(
    "tony.application.executable", "", str,
    "User training script; jobtypes without an explicit command run "
    "'<python> <executable> <task-params>' (reference "
    "TonyClient.buildTaskCommand :454-475).")
APPLICATION_TASK_PARAMS = _key(
    "tony.application.task-params", "", str,
    "Extra arguments appended to the default task command.")
REMOTE_STORE = _key(
    "tony.storage.remote-store", "", str,
    "URL prefix of an object store for job staging (gs://bucket/prefix or "
    "file:///mount/prefix). When set, the client PUTs the bundle, "
    "resources, venv, and frozen config under <prefix>/<app_id>/ and "
    "executors GET them — no shared filesystem is assumed (the HDFS "
    "upload/localize analogue, HdfsUtils.java:115-160). Empty = local "
    "job-dir staging.")
STORAGE_TOKEN = _key(
    "tony.storage.token", "", str,
    "Storage credential for submit-time staging. SCRUBBED from the frozen "
    "config before it is written (the artifact is world-readable via the "
    "portal and the store); it reaches executors by env passthrough as "
    "TONY_STORAGE_TOKEN — the separate-token-file discipline of the "
    "reference (security/TokenCache.java:44-51). Empty = read from the "
    "TONY_STORAGE_TOKEN env at submit.")
INTERNAL_CONF_URL = _key(
    "tony.internal.conf-url", "", str,
    "Set by the client at submit when a remote store is configured: store "
    "URL of the frozen config; executors fetch it before reading any "
    "other key (which is why the credential travels by env, not config).")
INTERNAL_BUNDLE_DIR = _key(
    "tony.internal.bundle-dir", "", str,
    "Set by the client at submit: staged src-dir bundle that executors "
    "localize into each task working dir (reference HDFS localization, "
    "LocalizableResource.java / Utils.extractResources :710-723).")
INTERNAL_APP_ID = _key(
    "tony.internal.app-id", "", str,
    "Set by the client at submit: the application id.")
INTERNAL_RESOURCES = _key(
    "tony.internal.resources", "", str,
    "Set by the client at submit: staged SRC[::NAME][#archive] specs for "
    "executors to localize (reference LocalizableResource grammar).",
    multi_value=True)
INTERNAL_VENV = _key(
    "tony.internal.venv", "", str,
    "Set by the client at submit: staged python-venv archive, unpacked to "
    "./venv in every task working dir (reference TonyClient.java:189-228).")
INTERNAL_VERSION = _key(
    "tony.internal.version", "", str,
    "Stamped by the client at submit: framework package version "
    "(reference VersionInfo injection, TonyClient.java:152).")
INTERNAL_REVISION = _key(
    "tony.internal.revision", "", str,
    "Stamped by the client at submit: git revision of the framework build "
    "(reference util/VersionInfo.java:149).")
INTERNAL_BRANCH = _key(
    "tony.internal.branch", "", str,
    "Stamped by the client at submit: git branch of the framework build.")
INTERNAL_FLEET_TRACE_ID = _key(
    "tony.internal.fleet-trace-id", "", str,
    "Stamped by the fleet daemon on every grant's conf: the fleet-wide "
    "trace id (tony_tpu/tracing.py). The client adopts it as the job's "
    "trace id instead of minting a fresh one, so one `tony-tpu trace "
    "--fleet` export renders every job in the pool — queue spans, "
    "grants, job lifetimes, preempt/grow-back resizes — on ONE "
    "timeline. Empty = the job mints its own trace id (non-fleet "
    "submits).")
INTERNAL_FLEET_TRACE_PARENT = _key(
    "tony.internal.fleet-trace-parent", "", str,
    "Stamped by the fleet daemon on every grant's conf: span id of the "
    "fleet.job span this grant opened. Recorded as the fleet_parent "
    "attr on the job's client.submit root span (an attr, not a span "
    "parent — the job's own span tree stays self-contained for the "
    "trace-parent invariant; the --fleet export stitches by shared "
    "trace id).")

# --- per-jobtype dynamic keys (reference TonyConfigurationKeys.java:171-239)
INSTANCES_FORMAT = "tony.{job}.instances"
COMMAND_FORMAT = "tony.{job}.command"
CHIPS_FORMAT = "tony.{job}.chips"          # replaces tony.X.gpus
VCORES_FORMAT = "tony.{job}.vcores"
MEMORY_FORMAT = "tony.{job}.memory"
MAX_INSTANCES_FORMAT = "tony.{job}.max-instances"
DEPENDS_ON_FORMAT = "tony.{job}.depends-on"
ENV_FORMAT = "tony.{job}.env"
# Replaces tony.X.node-label. On the tpu-slice backend the reserved pool
# "coordinator" places the jobtype on the coordinator's machine (CPU
# ps/db-style tasks in a TPU gang — heterogeneous DAGs, SURVEY.md §7(d)).
NODE_POOL_FORMAT = "tony.{job}.node-pool"
# Container image for the jobtype's executors (reference per-job docker
# support, TonyConfigurationKeys.java:178-239 + Utils docker env :729-776).
# The backend wraps the executor launch in `docker run` (host networking;
# task workdir bind-mounted; task env passed with -e). TPU device access
# additionally needs a privileged image with /dev/accel* — bake jax[tpu]
# and tony-tpu into the image.
DOCKER_IMAGE_FORMAT = "tony.{job}.docker-image"

_JOB_KEY_RE: Pattern[str] = re.compile(
    r"^tony\.([a-z][a-z0-9_]*)\.(instances|command|chips|vcores|memory|"
    r"max-instances|depends-on|env|node-pool|docker-image)$")

_RESERVED_NON_JOB_SEGMENTS = {
    "application", "task", "coordinator", "client", "history", "tpu", "portal",
    "keep-failed-task-dirs", "internal", "fault", "rpc", "trace", "metrics",
    "diagnosis", "pool", "elastic", "profile", "train", "coord", "scale",
    "fleet", "health", "alerts",
}


def registry() -> Dict[str, ConfigKey]:
    """The static key registry (name → ConfigKey)."""
    return dict(_REGISTRY)


def defaults_markdown() -> str:
    """Render the documented defaults table. ``tony_tpu/conf/defaults.md``
    must be exactly this output — the parity test regenerates and compares
    (the analogue of ``TestTonyConfigurationFields.java:17-45`` enforcing
    keys-class ↔ ``tony-default.xml`` agreement). Regenerate with
    ``python -m tony_tpu.conf.keys``."""
    lines = [
        "# tony-tpu configuration defaults",
        "",
        "Generated from `tony_tpu/conf/keys.py` — do not edit by hand; run",
        "`python -m tony_tpu.conf.keys` to regenerate. Parity with the key",
        "registry is test-enforced (reference discipline:",
        "`TestTonyConfigurationFields.java:17-45`).",
        "",
        "| Key | Default | Type | Multi-value |",
        "|---|---|---|---|",
    ]
    for name in sorted(_REGISTRY):
        k = _REGISTRY[name]
        default = "(empty)" if k.default == "" else repr(k.default)
        lines.append(f"| `{name}` | {default} | {k.type.__name__} | "
                     f"{'yes' if k.multi_value else ''} |")
    lines += [
        "",
        "Dynamic per-jobtype keys (reference "
        "`TonyConfigurationKeys.java:171-239`):",
        "",
    ]
    for fmt in (INSTANCES_FORMAT, COMMAND_FORMAT, CHIPS_FORMAT,
                VCORES_FORMAT, MEMORY_FORMAT, MAX_INSTANCES_FORMAT,
                DEPENDS_ON_FORMAT, ENV_FORMAT, NODE_POOL_FORMAT,
                DOCKER_IMAGE_FORMAT):
        lines.append(f"- `{fmt.format(job='<jobtype>')}`")
    lines.append("")
    return "\n".join(lines)


def is_multi_value(name: str) -> bool:
    k = _REGISTRY.get(name)
    return bool(k and k.multi_value)


def parse_job_key(name: str) -> Optional[Tuple[str, str]]:
    """Return (jobtype, attribute) if `name` is a dynamic per-jobtype key.

    Mirrors the reference's regex-driven jobtype discovery
    (``TonyConfigurationKeys.getJobTypes``, :171-176).
    """
    m = _JOB_KEY_RE.match(name)
    if not m:
        return None
    job = m.group(1)
    if job in _RESERVED_NON_JOB_SEGMENTS:
        return None
    return job, m.group(2)


def coerce(name: str, value: Any) -> Any:
    """Coerce a raw (possibly string) value to the registered key type.
    An empty string means "unset" and falls back to the key's default
    (Hadoop Configuration getInt semantics — found by the config
    round-trip property test)."""
    key = _REGISTRY.get(name)
    if key is None:
        jk = parse_job_key(name)
        if jk and jk[1] in ("instances", "chips", "vcores", "max-instances"):
            if value in ("", None):
                # Empty = unset: keep it empty so each call site's get_int
                # default applies (vcores→1, max-instances→-1/unlimited) —
                # a hardcoded 0 here would turn "no cap" into a zero cap.
                return ""
            try:
                return int(value)
            except (TypeError, ValueError) as e:
                raise ValueError(f"config key {name!r} needs an integer, "
                                 f"got {value!r}") from e
        return value
    if value in ("", None) and key.type in (int, bool, float):
        return key.default
    if key.type is bool and isinstance(value, str):
        return value.strip().lower() in ("true", "1", "yes", "on")
    if key.type is int and not isinstance(value, bool):
        try:
            return int(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"config key {name!r} needs an integer, "
                             f"got {value!r}") from e
    if key.type is float and not isinstance(value, bool):
        try:
            return float(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"config key {name!r} needs a number, "
                             f"got {value!r}") from e
    if key.type is str:
        return str(value)
    return value


if __name__ == "__main__":
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "defaults.md")
    with open(path, "w", encoding="utf-8") as f:
        f.write(defaults_markdown())
    print(f"wrote {path}")
