"""Layered configuration with a frozen "final config" artifact.

Reference model (``TonyClient.initTonyConf`` :483-517 and
``processFinalTonyConf`` :189-228): defaults ← job config file ← explicit
``-conf k=v`` overrides ← site file, frozen into a single ``tony-final.xml``
that is localized to the AM and every container, so every process reads one
source of truth (``ApplicationMaster.java:216``, ``TaskExecutor.java:269``).

This build keeps the exact layering but uses JSON/YAML instead of Hadoop XML,
and the frozen artifact is ``tony-final.json`` (constants.FINAL_CONFIG_FILE).
Multi-value keys append across layers (reference ``TonyClient.java:498-510``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from tony_tpu import constants
from tony_tpu.conf import keys as K


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class JobType:
    """A gang of identical tasks (reference per-jobtype dynamic keys,
    ``TonyConfigurationKeys.java:171-239``)."""

    name: str
    instances: int = 0
    command: str = ""
    chips: int = 0
    vcores: int = 1
    memory: str = "2g"
    depends_on: Tuple[str, ...] = ()
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    node_pool: str = ""
    docker_image: str = ""

    @property
    def is_chief_type(self) -> bool:
        return self.name == constants.CHIEF_JOB_NAME


def _load_file(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml  # baked in

        data = yaml.safe_load(text) or {}
    else:
        data = json.loads(text or "{}")
    if not isinstance(data, dict):
        raise ConfigError(f"config file {path} must contain a mapping")
    return _flatten(data)


def _flatten(data: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
    """Allow nested mappings in config files: {"tony": {"worker": {"instances": 2}}}
    flattens to dotted keys."""
    out: Dict[str, Any] = {}
    for k, v in data.items():
        name = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, Mapping):
            out.update(_flatten(v, name))
        else:
            out[name] = v
    return out


class TonyTpuConfig:
    """Dict-backed layered configuration."""

    def __init__(self, initial: Optional[Mapping[str, Any]] = None):
        self._conf: Dict[str, Any] = {}
        for key in K.registry().values():
            self._conf[key.name] = key.default
        if initial:
            for k, v in initial.items():
                self.set(k, v)

    # -- layering ---------------------------------------------------------
    @classmethod
    def from_layers(
        cls,
        config_file: Optional[str] = None,
        overrides: Iterable[str] = (),
        site_dir: Optional[str] = None,
    ) -> "TonyTpuConfig":
        """defaults ← config_file ← overrides(k=v) ← site file.

        Mirrors ``TonyClient.initTonyConf`` :483-517 (the site file is the
        last layer there too: ``$TONY_CONF_DIR/tony-site.xml``).
        """
        conf = cls()
        if config_file:
            conf.merge(_load_file(config_file))
            conf._resolve_file_relative_paths(os.path.dirname(
                os.path.abspath(config_file)))
        for kv in overrides:
            if "=" not in kv:
                raise ConfigError(f"override must be key=value, got {kv!r}")
            k, v = kv.split("=", 1)
            conf.set(k.strip(), v)
        site_dir = site_dir or os.environ.get("TONY_TPU_CONF_DIR", "")
        if site_dir:
            for fname in ("tony-site.json", "tony-site.yaml"):
                p = os.path.join(site_dir, fname)
                if os.path.exists(p):
                    conf.merge(_load_file(p))
                    break
        return conf

    def merge(self, other: Mapping[str, Any]) -> None:
        for k, v in other.items():
            self.set(k, v)

    def _resolve_file_relative_paths(self, base_dir: str) -> None:
        """Relative paths in a job config resolve against the config
        file's directory, not the caller's CWD — so
        ``submit --conf-file examples/mnist-jax/mnist.json`` works from
        anywhere (the examples all say ``src-dir: "."``). Only applied to
        values that exist under the file's dir with the right kind
        (src-dir: directory, venv: file); anything else is left for CWD
        resolution (the CLI-flag behavior)."""
        def resolve(v: str, want) -> str:
            if not v or os.path.isabs(v):
                return v
            cand = os.path.normpath(os.path.join(base_dir, v))
            return cand if want(cand) else v

        for key, want in ((K.SRC_DIR, os.path.isdir),
                          (K.PYTHON_VENV, os.path.isfile)):
            v = str(self.get(key, "") or "")
            resolved = resolve(v, want)
            if resolved != v:
                self.set(key, resolved)
        # Container resources share the same file-relative intent; their
        # SRC[::NAME][#archive] annotations must survive the rewrite.
        specs = self.get_list(K.CONTAINER_RESOURCES)
        if specs:
            from tony_tpu.utils.localize import LocalizableResource

            import dataclasses as _dc

            out = []
            for spec in specs:
                try:
                    r = LocalizableResource.parse(spec)
                except ValueError:
                    out.append(spec)     # staging reports the bad spec
                    continue
                r = _dc.replace(r, source=resolve(r.source, os.path.exists))
                out.append(r.unparse())
            if out != specs:
                self.unset(K.CONTAINER_RESOURCES)
                for spec in out:
                    self.set(K.CONTAINER_RESOURCES, spec)

    # -- access -----------------------------------------------------------
    def set(self, name: str, value: Any) -> None:
        if (name.startswith("tony.") and name not in K.registry()
                and K.parse_job_key(name) is None):
            # Arbitrary keys pass through (reference Hadoop Configuration
            # semantics), but a tony.* key that matches nothing is almost
            # always a typo — say so instead of silently ignoring it.
            import logging
            logging.getLogger(__name__).warning(
                "config key %r matches no registered key or jobtype "
                "pattern — possible typo (value kept as passthrough)", name)
        value = K.coerce(name, value)
        if K.is_multi_value(name) and self._conf.get(name):
            existing = str(self._conf[name])
            incoming = str(value)
            if existing and incoming and incoming not in existing.split(","):
                value = f"{existing},{incoming}"
        self._conf[name] = value

    def unset(self, name: str) -> None:
        """Remove a key entirely (e.g. scrubbing credentials before the
        config is frozen into a world-readable artifact)."""
        self._conf.pop(name, None)

    def get(self, name: str, default: Any = None) -> Any:
        if name in self._conf:
            return self._conf[name]
        key = K.registry().get(name)
        if key is not None:
            return key.default
        return default

    def get_int(self, name: str, default: int = 0) -> int:
        v = self.get(name, default)
        return int(v) if v is not None and v != "" else default

    def get_bool(self, name: str, default: bool = False) -> bool:
        v = self.get(name, default)
        if isinstance(v, str):
            return v.strip().lower() in ("true", "1", "yes", "on")
        return bool(v)

    def get_list(self, name: str) -> List[str]:
        v = self.get(name, "")
        if not v:
            return []
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._conf)

    # -- jobtypes ---------------------------------------------------------
    def job_types(self) -> Dict[str, JobType]:
        """Discover jobtypes from dynamic keys (reference
        ``TonyConfigurationKeys.getJobTypes`` + ``Utils.parseContainerRequests``
        :366-408)."""
        names = set()
        for name in self._conf:
            jk = K.parse_job_key(name)
            if jk:
                names.add(jk[0])
        jobs: Dict[str, JobType] = {}
        for job in sorted(names):
            instances = self.get_int(K.INSTANCES_FORMAT.format(job=job), 0)
            if instances <= 0:
                continue
            env_pairs = {}
            for kv in self.get_list(K.ENV_FORMAT.format(job=job)):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    env_pairs[k] = v
            jobs[job] = JobType(
                name=job,
                instances=instances,
                command=str(self.get(K.COMMAND_FORMAT.format(job=job), "") or ""),
                chips=self.get_int(K.CHIPS_FORMAT.format(job=job), 0),
                vcores=self.get_int(K.VCORES_FORMAT.format(job=job), 1),
                memory=str(self.get(K.MEMORY_FORMAT.format(job=job), "2g")),
                depends_on=tuple(self.get_list(K.DEPENDS_ON_FORMAT.format(job=job))),
                env=env_pairs,
                node_pool=str(self.get(K.NODE_POOL_FORMAT.format(job=job), "") or ""),
                docker_image=str(self.get(
                    K.DOCKER_IMAGE_FORMAT.format(job=job), "") or ""),
            )
        return jobs

    def untracked_jobtypes(self) -> Tuple[str, ...]:
        return tuple(self.get_list(K.APPLICATION_UNTRACKED_JOBTYPES))

    # -- validation -------------------------------------------------------
    def validate(self) -> None:
        """Quota + sanity checks (reference ``TonyClient.validateTonyConf``
        :598-667: instance and resource quota enforcement at submit time)."""
        jobs = self.job_types()
        if not jobs and not str(self.get(K.COORDINATOR_COMMAND, "") or "") \
                and not str(self.get(K.APPLICATION_EXECUTABLE, "") or ""):
            # Zero jobtypes is legal only for single-node mode, where the
            # coordinator itself runs the command (reference
            # ApplicationMaster.java:714 single-node path).
            raise ConfigError(
                "no jobtypes configured: set tony.<job>.instances >= 1 "
                "(or a coordinator-local command for single-node mode)")
        total_instances = sum(j.instances for j in jobs.values())
        max_total = self.get_int(K.MAX_TOTAL_INSTANCES, -1)
        if max_total >= 0 and total_instances > max_total:
            raise ConfigError(
                f"requested {total_instances} instances exceeds quota "
                f"{max_total} ({K.MAX_TOTAL_INSTANCES})")
        total_chips = sum(j.instances * j.chips for j in jobs.values())
        max_chips = self.get_int(K.MAX_TOTAL_CHIPS, -1)
        if max_chips >= 0 and total_chips > max_chips:
            raise ConfigError(
                f"requested {total_chips} chips exceeds quota {max_chips} "
                f"({K.MAX_TOTAL_CHIPS})")
        for j in jobs.values():
            cap = self.get_int(K.MAX_INSTANCES_FORMAT.format(job=j.name), -1)
            if cap >= 0 and j.instances > cap:
                raise ConfigError(
                    f"jobtype {j.name}: {j.instances} instances exceeds "
                    f"max-instances {cap}")
            for dep in j.depends_on:
                if dep not in jobs:
                    raise ConfigError(
                        f"jobtype {j.name} depends on unknown jobtype {dep}")
        # TLS wants the pair: a cert without its key would crash the
        # SPAWNED coordinator before it writes its address file, and the
        # submitter would see only "coordinator address never appeared".
        tls_cert = str(self.get(K.SECURITY_TLS_CERT, "") or "")
        tls_key = str(self.get(K.SECURITY_TLS_KEY, "") or "")
        if bool(tls_cert) != bool(tls_key):
            raise ConfigError(
                f"{K.SECURITY_TLS_CERT} and {K.SECURITY_TLS_KEY} must be "
                f"set together (got cert={tls_cert!r}, key={tls_key!r})")

    # -- freeze / thaw ----------------------------------------------------
    def freeze(self, path: str) -> str:
        """Write the frozen final config artifact (``tony-final.json``),
        the single source of truth shipped to coordinator and executors
        (reference ``tony-final.xml``, Constants.java:139). Atomic +
        fsync'd (utils/durable.py): executors fetch this file while the
        coordinator may crash and be recovered at any moment — a torn
        config is a gang-wide poison pill."""
        from tony_tpu.utils.durable import atomic_write

        atomic_write(path, json.dumps(self._conf, indent=2,
                                      sort_keys=True).encode("utf-8"))
        return path

    @classmethod
    def load_final(cls, path: str) -> "TonyTpuConfig":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        conf = cls()
        conf._conf.update(data)  # already-coerced values; bypass multi-value append
        return conf
