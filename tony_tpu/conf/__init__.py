from tony_tpu.conf.config import TonyTpuConfig  # noqa: F401
from tony_tpu.conf import keys  # noqa: F401
