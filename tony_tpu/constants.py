"""Shared constant names: environment-variable contract, well-known job names,
file names, and test hooks.

Parity target: reference ``tony-core/src/main/java/com/linkedin/tony/Constants.java``
(env vars :44-62, job names :104-110, test hooks :116-121, file names :139).
The TPU build replaces the four per-framework rendezvous dialects with one
coordinator-address contract, but still exports the legacy framework variables
from the runtimes layer so TF / PyTorch / MXNet user scripts keep working.
"""

# ---------------------------------------------------------------------------
# Core task-identity environment contract (set by the coordinator when
# launching an executor; reference ApplicationMaster.java:1129-1141).
# ---------------------------------------------------------------------------
JOB_NAME = "TONY_JOB_NAME"            # jobtype of this task, e.g. "worker"
TASK_INDEX = "TONY_TASK_INDEX"        # index within the jobtype
TASK_NUM = "TONY_TASK_NUM"            # number of tasks of this jobtype
IS_CHIEF = "TONY_IS_CHIEF"            # "true" iff chief semantics apply
SESSION_ID = "TONY_SESSION_ID"        # retry epoch (reference TonySession.java:51)
APP_ID = "TONY_APP_ID"                # application id
COORDINATOR_HOST = "TONY_COORDINATOR_HOST"
COORDINATOR_PORT = "TONY_COORDINATOR_PORT"
METRICS_PORT = "TONY_METRICS_PORT"    # metrics RPC port on the coordinator
# Coordinator generation this executor was launched under (crash-recovery
# fencing, rpc/wire.py): adopted upward on reconnect, rejected downward.
COORDINATOR_GENERATION = "TONY_COORDINATOR_GENERATION"
# Membership generation of the gang topology this executor was launched
# under (elastic resize fencing, coordinator/elastic.py): bumped on every
# applied resize; survivors adopt the new value from the RESIZE directive
# riding the heartbeat response, and frames carrying a stale value with no
# resize in flight are fenced — a zombie from a pre-resize topology must
# not corrupt the re-meshed gang.
MEMBERSHIP_GEN = "TONY_MEMBERSHIP_GEN"
# Sorted member indices of this executor's jobtype gang at launch/adoption
# time (comma-separated), exported to the user process so elastic-aware
# training loops can map their stable task index to a dense rank.
GANG_MEMBERS = "TONY_GANG_MEMBERS"
# Path to the coordinator's address file (host/port/token JSON). Executors
# re-resolve the coordinator from it after a restart (the recovered
# coordinator binds a fresh ephemeral port and rewrites the file); only
# meaningful where the path is reachable (same host / shared fs) — absent
# or unreadable, reconnects retry the launch-time address.
COORDINATOR_ADDR_FILE = "TONY_COORDINATOR_ADDR_FILE"
# File the user process's telemetry reporter writes device stats to; the
# TaskMonitor tails it (set by the executor; see tony_tpu/telemetry.py).
METRICS_FILE = "TONY_METRICS_FILE"
# Override for the telemetry reporter's write cadence in seconds (default
# 3.0). Progress-liveness tests tighten it so the step counter publishes
# faster than the configured progress deadline.
TELEMETRY_INTERVAL_ENV = "TONY_TELEMETRY_INTERVAL_S"
# Signal number the executor exports into the user environment for
# hung-task diagnostics: `import tony_tpu` pre-registers a faulthandler
# all-thread stack dump on it (telemetry.install_stack_dump_handler), and
# the executor delivers it to the user process group when the coordinator
# declares the task HUNG (progress frozen, heartbeats alive). Default
# SIGUSR1; operators can pre-set it (tony.application.execution-env) to
# move the dump off a signal the user script needs.
STACKDUMP_SIGNAL = "TONY_STACKDUMP_SIGNAL"
# Distributed-tracing context (tony_tpu/tracing.py): the job's trace id
# and the parent span id for this process's root span. The client exports
# them to the coordinator; the coordinator exports them to executors with
# the task's lifecycle span as the parent — one stitched tree per job.
TRACE_ID_ENV = "TONY_TRACE_ID"
TRACE_PARENT_ENV = "TONY_TRACE_PARENT"
TASK_ID = "TONY_TASK_ID"              # "<jobtype>:<index>"
TASK_COMMAND = "TONY_TASK_COMMAND"    # user command for this task
EXECUTOR_CONF = "TONY_EXECUTOR_CONF"  # path to the frozen final config
# Warm-executor-pool adoption (tony_tpu/pool.py): set in the lease env by
# the pool daemon so an adopted executor can mark its spans (register span
# adopted=true, run span pooled=<worker id>) — the trace-visible proof a
# submit skipped the cold spawn. Absent on cold-spawned executors.
POOL_WORKER_ID = "TONY_POOL_WORKER_ID"

# Global-rank contract for the JAX runtime (computed over the whole gang).
GLOBAL_RANK = "TONY_GLOBAL_RANK"
GLOBAL_WORLD = "TONY_GLOBAL_WORLD"

# ---------------------------------------------------------------------------
# Framework rendezvous variables exported by runtimes
# (reference TaskExecutor.java:161-207, Constants.java:44-62).
# ---------------------------------------------------------------------------
TF_CONFIG = "TF_CONFIG"
CLUSTER_SPEC = "CLUSTER_SPEC"
# This task's own reserved rendezvous port (generic servers — notebooks,
# Ray heads — bind it; released to the user process before exec).
TASK_PORT = "TASK_PORT"

# PyTorch (reference Constants.java:50-54)
INIT_METHOD = "INIT_METHOD"
MASTER_ADDR = "MASTER_ADDR"
MASTER_PORT = "MASTER_PORT"
RANK = "RANK"
WORLD = "WORLD"
WORLD_SIZE = "WORLD_SIZE"

# MXNet (reference Constants.java:57-62)
DMLC_PS_ROOT_URI = "DMLC_PS_ROOT_URI"
DMLC_PS_ROOT_PORT = "DMLC_PS_ROOT_PORT"
DMLC_ROLE = "DMLC_ROLE"
DMLC_NUM_SERVER = "DMLC_NUM_SERVER"
DMLC_NUM_WORKER = "DMLC_NUM_WORKER"
DMLC_USE_KUBERNETES = "DMLC_USE_KUBERNETES"

# JAX coordination service (the one uniform TPU-native mechanism; replaces all
# of the above for JAX jobs — SURVEY.md §2.4).
JAX_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
JAX_NUM_PROCESSES = "JAX_NUM_PROCESSES"
JAX_PROCESS_ID = "JAX_PROCESS_ID"
JAX_COMPILATION_CACHE_DIR = "JAX_COMPILATION_CACHE_DIR"

# TensorBoard (reference Constants.java TB_PORT; TaskExecutor.java:83-95)
TB_PORT = "TB_PORT"

# Shared checkpoint dir for the session-retry resume contract (no reference
# analogue — checkpointing was user-code-only there, SURVEY.md §5).
CHECKPOINT_DIR = "TONY_CHECKPOINT_DIR"

# ---------------------------------------------------------------------------
# Well-known job (task-type) names (reference Constants.java:104-110).
# ---------------------------------------------------------------------------
CHIEF_JOB_NAME = "chief"
PS_JOB_NAME = "ps"
WORKER_JOB_NAME = "worker"
EVALUATOR_JOB_NAME = "evaluator"
SCHEDULER_JOB_NAME = "scheduler"   # MXNet
SERVER_JOB_NAME = "server"         # MXNet
NOTEBOOK_JOB_NAME = "notebook"
DRIVER_JOB_NAME = "driver"

# ---------------------------------------------------------------------------
# File-name constants (reference Constants.java:139 TONY_FINAL_XML and
# HistoryFileUtils.java:12-31 jhist naming).
# ---------------------------------------------------------------------------
FINAL_CONFIG_FILE = "tony-final.json"
# Write-ahead session journal, next to the history stream in the job dir
# (coordinator/journal.py — the crash-recovery source of truth).
JOURNAL_FILE = "session.journal.jsonl"
# Distributed-tracing span log, next to the jhist stream in the job dir
# (tony_tpu/tracing.py): coordinator-written JSON lines; executors ship
# their spans into it over the trace.push RPC.
TRACE_FILE = "trace.spans.jsonl"
# Rendered Prometheus text exposition, refreshed by the coordinator every
# tony.metrics.export-interval-s; the portal's /metrics scrape endpoint
# concatenates these across live jobs.
METRICS_PROM_FILE = "metrics.prom"
# Counter snapshot (tony_tpu/metrics.py save_counters): reloaded by a
# --recover coordinator so counters stay monotonic across recovery.
METRICS_COUNTERS_FILE = "metrics.counters.json"
# Automatic failure diagnosis (tony_tpu/diagnosis/): the incident
# document the coordinator writes on any non-SUCCEEDED finish — verdict
# category, blamed task, evidence, causal timeline. Atomically replaced;
# readers treat a torn/absent file as "recompute from the bundle".
INCIDENT_FILE = "incident.json"
# Warm-executor-pool daemon endpoint (tony_tpu/pool.py): host/port/token
# JSON in the pool dir, 0600 like the coordinator address file. Backends
# try a pool.lease against it before cold-spawning; absent file = no pool.
POOL_ADDR_FILE = "pool.addr"
# Fleet daemon endpoint (tony_tpu/fleet/): host/port/token/generation JSON
# in the fleet dir, 0600 — fleet.submit/status/cancel RPCs resolve it.
FLEET_ADDR_FILE = "fleet.addr"
# Write-ahead fleet journal (tony_tpu/fleet/journal.py): every submission,
# grant, preemption and job state transition, fsync'd BEFORE it is acted
# on — `tony-tpu fleet start --recover` replays it into the same queue
# state (same REC_*/torn-tail discipline as coordinator/journal.py).
FLEET_JOURNAL_FILE = "fleet.journal.jsonl"
# Scheduler status snapshot the daemon atomically replaces every tick
# (queue, grants, tenant occupancy) — the portal's /fleet view and any
# RPC-less reader consume this instead of dialing the daemon.
FLEET_STATUS_FILE = "fleet.status.json"
# Rendered Prometheus exposition of the tony_fleet_* families, refreshed
# every scheduler tick (the fleet-dir analogue of metrics.prom).
FLEET_PROM_FILE = "fleet.prom"
# Counter snapshot (tony_fleet_grants_total etc.), reloaded on
# `fleet start --recover` so fleet counters stay monotonic across daemon
# lives — same contract as METRICS_COUNTERS_FILE.
FLEET_COUNTERS_FILE = "fleet.counters.json"
# Fleet event stream (FLEET_JOB_QUEUED/GRANTED/PREEMPTED/...), JSON lines
# in the fleet dir; append-only across daemon lives (never finalized —
# the fleet is a daemon, not a job).
FLEET_EVENTS_FILE = "fleet.events.jsonl"
# Fleet-level incident document (tony_tpu/fleet/diagnose.py): the rule
# engine's verdict over the goodput ledger + scheduler decision records
# (STARVATION / QUOTA_SATURATED / FRAGMENTATION / PREEMPT_STORM /
# POOL_COLD / FLEET_HEALTHY), atomically replaced by the daemon every
# export and recomputed on demand by `tony-tpu fleet diagnose`. Readers
# treat a torn/absent file as "recompute from the fleet dir".
FLEET_INCIDENT_FILE = "fleet.incident.json"
# Host-health cordon set (tony_tpu/fleet/health.py): {"hosts": {host ->
# state}} atomically replaced by the fleet daemon on every export, in
# BOTH the fleet dir and the warm-pool dir — the pool daemon refuses
# leases for (and discards) workers on listed hosts, and offline tools
# read the live cordon set without dialing the daemon.
FLEET_CORDON_FILE = "health.cordon.json"
# Per-task exit report a POOLED executor writes into its task workdir at
# exit ({"exit_code": N}): the leased process is the pool daemon's child,
# not the backend's, so poll_completions reads this instead of waitpid.
POOL_EXIT_FILE = "pool-exit.json"
EVENTS_SUFFIX = ".jhist.jsonl"
INPROGRESS_SUFFIX = ".jhist.jsonl.inprogress"
HISTORY_INTERMEDIATE = "intermediate"
HISTORY_FINISHED = "finished"

# Env var naming which slice host a task/worker runs on (cluster
# backends set it at exec; pool workers echo it into ready.json so the
# pool daemon can refuse leases on health-cordoned hosts).
HOST_ID_ENV = "TONY_HOST_ID"

# Chief-only XLA trace destination (tony_tpu/profiler.py contract).
PROFILE_DIR = "TONY_PROFILE_DIR"
# Store URL the executor uploads captured traces to post-run (set when a
# remote store is configured — the chief's host can't write the
# coordinator's job dir directly; the coordinator pulls them back at stop).
PROFILE_UPLOAD = "TONY_PROFILE_UPLOAD"
# On-demand device profiling (tony-tpu profile <app>): path of the JSON
# request file the executor writes when a PROFILE directive rides the
# heartbeat response; the user process's telemetry reporter polls it and
# arms jax.profiler at the next step boundary (tony_tpu/telemetry.py).
PROFILE_REQUEST_ENV = "TONY_PROFILE_REQUEST_FILE"
# Basename of that request file in the task working dir (atomic replace;
# the reader tolerates a torn/absent file by ignoring it).
PROFILE_REQUEST_FILE = "profile-request.json"
# Step-time attribution report the coordinator writes into the job dir at
# finish (tony_tpu/profiling/verdict.py): per-phase seconds/fractions and
# the bottleneck verdict. Atomically replaced; torn/absent reads degrade
# to "no perf advisory".
PERF_FILE = "perf.json"

# ---------------------------------------------------------------------------
# Fault-injection test hooks, honoured by production code exactly like the
# reference's (Constants.java:116-121; see SURVEY.md §4.1 hook table).
# ---------------------------------------------------------------------------
TEST_COORDINATOR_CRASH = "TONY_TEST_COORDINATOR_CRASH"
# "<jobtype>" — coordinator kills one task of the type once chief registers
# (reference TEST_WORKER_TERMINATION, ApplicationMaster.java:1224-1235).
TEST_WORKER_TERMINATION = "TONY_TEST_WORKER_TERMINATION"
# "N" — executor silently skips its first N heartbeats
# (reference TaskExecutor.java:330-357).
TEST_NUM_HB_MISS = "TONY_TEST_NUM_HB_MISS"
# "job#idx#seconds" — executor sleeps after the user process exits
# (straggler skew; reference TaskExecutor.java:372-392).
TEST_EXECUTOR_SKEW = "TONY_TEST_EXECUTOR_SKEW"
# "seconds" — delay the coordinator's completion handling (races the
# heartbeat-unregister path; reference ApplicationMaster.java:1029-1038).
TEST_COMPLETION_DELAY = "TONY_TEST_COMPLETION_DELAY"
# any value — executor never registers (simulates an unreachable executor so
# the coordinator-side registration timeout is exercisable E2E; reference
# registration timeout, ApplicationMaster.java:791-888).
TEST_SKIP_REGISTRATION = "TONY_TEST_SKIP_REGISTRATION"
# "<host_id>" or "<host_id>#<path-glob>" — the TpuSliceBackend simulates
# sudden loss of that host (preemption/hardware death), once per job (fake
# provisioner only; exercises slice-lease invalidation → retry). The bare
# form fires on a short post-launch delay; the "#<glob>" form fires only
# once the glob matches an existing path — e.g. a durably committed
# checkpoint step — making preemption-AFTER-checkpoint deterministic
# (reference uses deterministic env-hook faults, Constants.java:116-121).
TEST_SLICE_FAIL_HOST = "TONY_TEST_SLICE_FAIL_HOST"

# Untracked jobtypes: run-forever tasks (parameter servers) whose exit does not
# gate job completion (reference TonyConfigurationKeys.java:252-253).
DEFAULT_UNTRACKED_JOBTYPES = (PS_JOB_NAME,)

# ---------------------------------------------------------------------------
# Kill-chain contract. YARN reaps the whole container process tree for free;
# without a NodeManager the supervisors here must reach the user tree
# themselves (reference stop-with-grace: ApplicationMaster.java:694-711).
# ---------------------------------------------------------------------------
# File (relative to a task's working dir) holding the process-group id of
# the USER command. The executor writes it the moment the user process
# starts, so backends can deliver the TERM→grace→KILL ladder to the user
# tree directly — an executor that was SIGKILLed can forward nothing.
USER_PGID_FILE = "user.pgid"
# Seconds the executor waits after forwarding SIGTERM to the user process
# group before escalating to SIGKILL (env override; default 5).
TASK_KILL_GRACE_ENV = "TONY_TASK_KILL_GRACE_S"

# Exit codes (reference common/TaskStatus semantics, TonySession.java:480-497).
EXIT_SUCCESS = 0
EXIT_FAILURE = 1
EXIT_KILLED = 137     # SIGKILL'd by supervisor / liveness monitor
# 128+SIGTERM: the exit of a task whose user process was TERM'd — the
# preemption-notice path (executor/preemption.py TERMs the user group;
# checkpoint/manager.install_preemption_handler exits with this after its
# final save). Classified as the PREEMPTION failure domain.
EXIT_PREEMPTED = 143
