from tony_tpu.coordinator.session import Session, Task, TaskStatus, SessionStatus  # noqa: F401
from tony_tpu.coordinator.scheduler import GangScheduler, SchedulerError  # noqa: F401
from tony_tpu.coordinator.coordinator import Coordinator  # noqa: F401
