"""Live job migration: drain → async snapshot → relaunch on another slice.

The reference's answer to "your slice is being reclaimed" was the whole
retry ladder: kill the gang, burn an attempt, relaunch wherever YARN put
you next (``ApplicationMaster.java:356-371``). This module composes the
primitives the elastic machinery already built into a MOVE instead:

- the **drain directive** (coordinator/elastic.py) parks the whole gang —
  every member's user process TERMs, its save-on-SIGTERM handler makes
  one final durable checkpoint (async writer, manifest-last:
  checkpoint/manager.py), and the executor waits at the barrier;
- at remesh the coordinator kills the parked source-slice executors,
  re-pins the job's ``node_pool`` to the target, and relaunches the SAME
  member indices there — destination executors adopt from the warm pool
  (tony_tpu/pool.py) when one serves the target, else cold-spawn;
- the restored state reshards into the destination mesh through the
  ordinary restore path (manifest ``saved_mesh_shape`` +
  ``parallel/sharding.reshard``) — a migration that changes topology is
  just a resize that also moved.

Write-ahead ``REC_MIGRATE`` records (coordinator/journal.py) bracket the
op — ``start`` before the drain directive, ``applied`` before the
destination launches, ``superseded`` when a mid-migration host loss
folds the move into an ordinary elastic shrink — so a coordinator
SIGKILLed mid-migration re-enters the op under ``--recover`` instead of
abandoning the job, and `tony-tpu check` can prove every start was
closed (migrate-dangling).

Failure ladder (THE invariant): every abort path degrades to the
ordinary elastic/retry machinery — ``migrate.snapshot`` /
``migrate.adopt`` faults, barrier timeouts and launch failures all land
in the same INFRA_TRANSIENT epoch retry a plain host loss takes. A
failed migration is never worse than losing a host.

This module owns the POLICY (may this job move, and what does the move
look like); the coordinator owns every side effect — directives, kills,
launches, journal, events — exactly like the resize split.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from tony_tpu.coordinator.elastic import ElasticManager
    from tony_tpu.coordinator.session import Session


class MigrateRefused(ValueError):
    """A migration request the policy rejects (no elastic machinery, no
    target, gang mid-resize...) — reported to the caller, never a job
    failure."""


@dataclasses.dataclass
class MigrationPlan:
    """A validated migration: the full live member set moves to
    ``target``. ``source`` is the slice the job sits on now (empty for
    jobs launched without a node-pool pin — local/virtual backends)."""

    job: str
    members: List[int]
    source: str
    target: str
    reason: str


def plan_migration(elastic: "ElasticManager", session: "Session",
                   target: str, job: str = "",
                   reason: str = "") -> MigrationPlan:
    """Validate a migrate request against the gang's state and return
    the plan. Raises MigrateRefused with the operator-readable reason
    when policy says no. Pure read — the coordinator acts on the plan
    via ``ElasticManager.begin(..., migrate=True)``."""
    if elastic is None or not elastic.enabled:
        raise MigrateRefused(
            "migration rides the elastic drain machinery — set "
            "tony.elastic.enabled=true")
    if job and job != elastic.job:
        raise MigrateRefused(
            f"jobtype {job!r} is not the elastic jobtype ({elastic.job})")
    if not elastic.established:
        raise MigrateRefused(
            "the gang has not completed its initial rendezvous yet")
    if elastic.resizing:
        op = elastic.op
        what = "migration" if op is not None and op.migrate else "resize"
        raise MigrateRefused(f"a {what} is already in progress")
    target = str(target or "").strip()
    if not target:
        raise MigrateRefused("no target slice given")
    source = ""
    job_spec = session.jobs.get(elastic.job)
    if job_spec is not None:
        source = str(job_spec.node_pool or "")
    if source and source == target:
        raise MigrateRefused(
            f"job already runs on slice {target!r}")
    members = sorted(t.index for t in session.all_tasks()
                     if t.job_name == elastic.job
                     and not t.status.terminal)
    if not members:
        raise MigrateRefused(f"no live {elastic.job} tasks to migrate")
    return MigrationPlan(job=elastic.job, members=members, source=source,
                         target=target,
                         reason=reason or f"migrate to {target}")
