"""Standalone coordinator process entrypoint.

The reference's ApplicationMaster runs as its own JVM in a YARN container
(``TonyClient`` builds the AM command, :710-729); here the client spawns
``python -m tony_tpu.coordinator`` and discovers its RPC endpoint through an
address file in the job dir (the analogue of the RM app report carrying the
AM host:port, ``TonyClient.initRpcClientAndLogAMUrl`` :922).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from tony_tpu import constants
from tony_tpu.cluster.local import LocalProcessBackend
from tony_tpu.conf.config import TonyTpuConfig
from tony_tpu.coordinator.coordinator import Coordinator
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.utils.durable import atomic_write


def _make_backend(conf, workdir):
    """Backend selection (tony.application.backend): local subprocesses or
    a leased multi-host slice (cluster/tpu.py)."""
    from tony_tpu.conf import keys as K

    kind = str(conf.get(K.APPLICATION_BACKEND, "local"))
    if kind == "local":
        if conf.get_bool(K.SCALE_VIRTUAL_EXECUTORS):
            # Width harness (bench --suite scale / tests/test_scale.py):
            # beat-only in-process virtual executors instead of real
            # subprocesses — control-plane traffic at 128–1024 tasks.
            from tony_tpu.cluster.local import VirtualExecutorBackend

            return VirtualExecutorBackend.from_conf(conf, workdir)
        # Warm-executor-pool seam (tony_tpu/pool.py): with tony.pool.dir
        # set, launches try a pool.lease before cold-spawning.
        pool_dir = os.path.expanduser(
            str(conf.get(K.POOL_DIR, "") or ""))
        return LocalProcessBackend(workdir, pool_dir=pool_dir)
    if kind == "tpu-slice":
        from tony_tpu.cluster.tpu import (FakeSliceProvisioner,
                                          StaticSshProvisioner,
                                          TpuSliceBackend)

        n_hosts = int(conf.get(K.SLICE_NUM_HOSTS, 1))
        prov_kind = str(conf.get(K.SLICE_PROVISIONER, "fake"))
        if prov_kind == "ssh":
            targets = [t.strip()
                       for t in str(conf.get(K.SLICE_HOSTS, "")).split(",")
                       if t.strip()]
            prov = StaticSshProvisioner(
                targets,
                python=str(conf.get(K.SLICE_REMOTE_PYTHON, "python3")))
        elif prov_kind == "fake":
            inv = int(conf.get(K.SLICE_FAKE_INVENTORY, 0)) or n_hosts
            prov = FakeSliceProvisioner(inv, os.path.join(workdir, "hosts"))
        elif prov_kind == "gcloud":
            # The framework acquires its own compute via the Cloud TPU API
            # (cluster/gcloud.py) — no operator-run create-tpu-slice.sh.
            from tony_tpu.cluster.gcloud import (GcloudTpuProvisioner,
                                                TpuApiClient,
                                                localsim_channel_factory)

            api = TpuApiClient(
                project=str(conf.get(K.GCLOUD_PROJECT, "")),
                zone=str(conf.get(K.GCLOUD_ZONE, "")),
                endpoint=str(conf.get(K.GCLOUD_API_ENDPOINT, "")) or None)
            factory = None
            if str(conf.get(K.GCLOUD_CHANNEL, "ssh")) == "localsim":
                factory = localsim_channel_factory(
                    os.path.join(workdir, "hosts"))
            prov = GcloudTpuProvisioner(
                api,
                accelerator_type=str(
                    conf.get(K.GCLOUD_ACCELERATOR_TYPE, "")),
                runtime_version=str(conf.get(K.GCLOUD_RUNTIME_VERSION, "")),
                node_prefix=str(conf.get(K.GCLOUD_NODE_PREFIX, "tony")),
                ssh_user=str(conf.get(K.GCLOUD_SSH_USER, "")),
                remote_python=str(
                    conf.get(K.SLICE_REMOTE_PYTHON, "python3")),
                create_timeout_s=float(
                    conf.get(K.GCLOUD_CREATE_TIMEOUT_S, 900)),
                poll_interval_s=float(
                    conf.get(K.GCLOUD_POLL_INTERVAL_S, 5.0)),
                spot=bool(conf.get(K.GCLOUD_SPOT, False)),
                network=str(conf.get(K.GCLOUD_NETWORK, "")),
                queued=bool(conf.get(K.GCLOUD_QUEUED_RESOURCE, False)),
                channel_factory=factory)
        else:
            raise ValueError(f"unknown tony.slice.provisioner {prov_kind!r}")
        return TpuSliceBackend(prov, n_hosts, workdir)
    raise ValueError(f"unknown tony.application.backend {kind!r}")


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    p = argparse.ArgumentParser(prog="tony-tpu-coordinator")
    p.add_argument("--conf", required=True, help="frozen tony-final.json")
    p.add_argument("--conf-wait-s", type=float, default=0.0,
                   help="poll up to this many seconds for --conf to "
                        "appear before loading it. The client spawns the "
                        "coordinator BEFORE staging finishes (overlapped "
                        "submit: interpreter boot + imports + backend "
                        "construction run concurrently with the bundle "
                        "copies) and freezes the config last — atomically, "
                        "so a partial file is never visible. 0 = legacy "
                        "fail-fast when the file is missing.")
    p.add_argument("--app-id", required=True)
    p.add_argument("--history-root", required=True)
    p.add_argument("--workdir", required=True,
                   help="task working directories root")
    p.add_argument("--addr-file", required=True,
                   help="file to write 'host port token' for the client")
    p.add_argument("--user", default="")
    p.add_argument("--recover", action="store_true",
                   help="replay the job's write-ahead session journal and "
                        "resume the surviving gang at its current epoch "
                        "instead of launching a fresh one (coordinator "
                        "crash recovery; see docs/operations.md)")
    args = p.parse_args(argv)

    if args.conf_wait_s > 0 and not os.path.exists(args.conf):
        from tony_tpu.utils import proc as procutil

        found = procutil.poll_till_non_null(
            lambda: os.path.exists(args.conf) or None,
            interval_s=0.05, timeout_s=args.conf_wait_s)
        if found is None:
            logging.getLogger(__name__).error(
                "frozen config %s never appeared within %.0fs — the "
                "client died mid-staging?", args.conf, args.conf_wait_s)
            return constants.EXIT_FAILURE
    conf = TonyTpuConfig.load_final(args.conf)
    backend = _make_backend(conf, args.workdir)
    try:
        coord = Coordinator(conf, args.app_id, backend, args.history_root,
                            user=args.user, recover=args.recover,
                            addr_file=args.addr_file)
    except Exception as e:  # noqa: BLE001 — e.g. JournalError on --recover
        logging.getLogger(__name__).error("coordinator startup failed: %s", e)
        return constants.EXIT_FAILURE
    host, port = "", 0

    # Start RPC before writing the address file so the client never dials a
    # dead endpoint; Coordinator.run() starts it too (idempotent).
    coord.rpc.start()
    host, port = coord.rpc.address
    # The file carries the RPC auth token: it must be 0600 from its very
    # first byte (atomic_write's mode applies to the temp file, no
    # chmod-after window), and executors re-resolve it during
    # coordinator-loss recovery — a torn addr file would strand them.
    atomic_write(args.addr_file,
                 json.dumps({"host": host, "port": port,
                             "token": coord.rpc_token or "",
                             "tls_cert": coord.tls_cert}).encode("utf-8"),
                 mode=0o600)

    status = coord.run()
    return 0 if status == SessionStatus.SUCCEEDED else constants.EXIT_FAILURE


if __name__ == "__main__":
    sys.exit(main())
