"""Elastic gang membership: shrink-and-continue on host loss, grow back live.

Every failure mode PRs 1–5 hardened still ended the same way: tear the
gang down and replay the epoch from a checkpoint. This module makes
worker-set membership ELASTIC instead (the design axis TF-Replicator and
Podracer treat as first-class — PAPERS.md): a preempted or dead host
costs a re-mesh, not an epoch.

The machinery composes the primitives earlier PRs built:

- **Drain directive** rides the heartbeat response exactly like PR 3's
  dump directive: survivors get ``{"resize": {mgen, action, members}}``,
  TERM their user process (whose save-on-SIGTERM handler —
  ``checkpoint/manager.install_preemption_handler`` — makes one final
  durable save: the "checkpoint at a step barrier"), and PARK: instead
  of reporting an exit, the executor re-registers its existing identity
  under the new membership generation and waits at the gang barrier.
- **Membership generation** (``mgen``) extends PR 2's coordinator
  generation fencing to topology: bumped on every resize, journaled,
  carried on register/heartbeat frames. A frame from a pre-resize
  topology with no resize in flight is fenced (the executor tears its
  task down) — a zombie member cannot corrupt the re-meshed gang.
- **Write-ahead journal** (PR 2): ``resize start`` lands before any
  directive, ``resize applied`` before any relaunch — a coordinator
  SIGKILLed mid-resize and restarted with ``--recover`` RE-ENTERS the
  drain and completes the resize instead of restarting the job.

State machine (one op at a time, held here; the coordinator drives it
from its monitor loop and owns every side effect — launches, kills,
journal, events):

    IDLE --begin()--> DRAIN --(all survivors parked/gone)--> [remesh]
         --mark_remeshed()--> BARRIER --(all registered)--> finish() --> IDLE

A member lost DURING the drain folds into the same op: membership drops
the index, ``mgen`` bumps again, and the already-parked survivors adopt
the newer generation through the directive channel (their stale-mgen
barrier polls return "keep polling", never a fence, while the op runs).

Thread-safety: directives and acks arrive on RPC handler threads, the
state machine advances on the coordinator monitor loop — everything
behind one lock, nothing blocking inside it (tonylint lock-blocking).
"""

from __future__ import annotations

import threading
import time
from typing import (TYPE_CHECKING, Any, Callable, Dict, Iterable, List,
                    Optional, Set)

if TYPE_CHECKING:
    from tony_tpu.conf.config import TonyTpuConfig
    from tony_tpu.coordinator.session import Session, Task

from tony_tpu.conf import keys as K
from tony_tpu.devtools.race import guarded

#: op phases
DRAIN = "drain"        # directives out; waiting for survivors to park
BARRIER = "barrier"    # topology applied; waiting for re-registration


class ResizeRefused(ValueError):
    """An explicit resize request the policy rejects (below min-tasks,
    elasticity disabled, gang not established...) — reported to the
    caller, never a job failure."""


class _Op:
    def __init__(self, mgen: int, job: str, members: List[int],
                 reason: str, started: float, target: str = "",
                 migrate: bool = False) -> None:
        self.mgen = mgen
        self.job = job
        self.members = sorted(members)
        self.reason = reason
        self.started = started
        self.phase = DRAIN
        # Live member tasks that must park (re-register under this mgen)
        # before the re-mesh may apply; release = live non-members told
        # to exit.
        self.awaiting: Set[str] = set()
        self.parked: Set[str] = set()
        self.release: Set[str] = set()
        self.size_before = 0
        # Live migration (coordinator/migrate.py): same drain/barrier
        # machinery, but at remesh the WHOLE parked gang is relaunched on
        # ``target`` (a node-pool/slice name) instead of in place.
        self.target = target
        self.migrate = migrate


@guarded
class ElasticManager:
    """Membership policy + resize-op state for ONE elastic jobtype."""

    #: tonyrace registry (devtools/race.py): the op advances on the
    #: monitor loop while directives/acks arrive on RPC threads — every
    #: ``_op`` touch holds the lock. ``mgen``/``established`` are atomic
    #: scalar rebinds (written under the lock, readable without).
    GUARDED_BY = {
        "_op": "_lock",
        "mgen": None,
        "established": None,
    }

    def __init__(self, conf: "TonyTpuConfig",
                 now_fn: Callable[[], float] = time.monotonic) -> None:
        self._now = now_fn
        self.enabled = conf.get_bool(K.ELASTIC_ENABLED)
        self.job = str(conf.get(K.ELASTIC_JOBTYPE, "worker") or "worker")
        self.min_tasks = max(1, conf.get_int(K.ELASTIC_MIN_TASKS, 1))
        self.drain_grace_s = conf.get_int(K.ELASTIC_DRAIN_GRACE_S, 15)
        self.barrier_timeout_s = conf.get_int(
            K.ELASTIC_BARRIER_TIMEOUT_S, 120)
        #: membership generation — monotonic for the job's whole life,
        #: 1 for the launch topology (journal-restored on --recover).
        self.mgen = 1
        #: the initial rendezvous completed at least once: resizes only
        #: make sense against an established gang (a loss before the
        #: first barrier opens is an ordinary rendezvous failure).
        self.established = False
        self._op: Optional[_Op] = None
        self._lock = threading.Lock()

    # -- queries ----------------------------------------------------------
    @property
    def resizing(self) -> bool:
        with self._lock:
            return self._op is not None

    @property
    def op(self) -> Optional[_Op]:
        with self._lock:
            return self._op

    def snapshot(self) -> Dict[str, object]:
        """Status-surface view (application report / metrics.live)."""
        with self._lock:
            out: Dict[str, object] = {"mgen": self.mgen,
                                      "job": self.job,
                                      "resizing": self._op is not None}
            if self._op is not None:
                out["target_size"] = len(self._op.members)
                out["phase"] = self._op.phase
                if self._op.migrate:
                    out["migrating_to"] = self._op.target
            return out

    # -- policy -----------------------------------------------------------
    def may_absorb(self, task: "Task", domain_value: str,
                   session: "Session") -> bool:
        """Would losing this task be absorbed as a shrink (or folded into
        the in-flight resize) instead of failing the epoch? Pure read —
        the coordinator acts via begin()/note_task_gone().

        Absorbable: elasticity on, gang established, the task belongs to
        the elastic jobtype, it is NOT the chief (the chief owns the
        checkpoint cadence and index 0 anchors dense rank 0 — its loss
        keeps the fail-the-epoch policy), the failure is infra-shaped
        (INFRA_TRANSIENT / PREEMPTION — a deterministic USER_ERROR crash
        must not silently shrink the gang), and the survivors stay at or
        above ``tony.elastic.min-tasks``.
        """
        if not self.enabled or not self.established:
            return False
        if task.job_name != self.job:
            return False
        if session.is_chief(task.job_name, task.index):
            return False
        if domain_value not in ("INFRA_TRANSIENT", "PREEMPTION"):
            return False
        with self._lock:
            if self._op is not None:
                # Mid-resize: a released task's exit is expected, and a
                # dying MEMBER folds into the op as a further shrink —
                # as long as the floor still holds.
                if task.task_id in self._op.release:
                    return True
                if task.index in self._op.members:
                    return len(self._op.members) - 1 >= self.min_tasks
                return False
        survivors = [t for t in session.all_tasks()
                     if t.job_name == self.job
                     and not t.status.terminal
                     and t.task_id != task.task_id]
        return len(survivors) >= self.min_tasks

    def at_size(self, size: int, session: "Session") -> bool:
        """Is the established gang ALREADY at ``size`` with no resize in
        flight? The idempotent-resize probe: a caller retrying a resize
        whose first RESPONSE was lost (asymmetric partition, daemon
        crash between the RPC and its journal record) must read
        already-there as success, not as a refusal to retry forever."""
        if not self.enabled or not self.established:
            return False
        with self._lock:
            if self._op is not None:
                return False
        live = [t.index for t in session.all_tasks()
                if t.job_name == self.job and not t.status.terminal]
        return len(live) == int(size)

    def plan_explicit(self, size: int, session: "Session") -> List[int]:
        """Member list for an operator resize to ``size`` — shrink drops
        the HIGHEST indices (never the chief at index 0), grow re-adds
        the smallest free indices. Raises ResizeRefused with the reason
        when policy says no."""
        if not self.enabled:
            raise ResizeRefused(
                "elasticity is disabled (set tony.elastic.enabled)")
        if not self.established:
            raise ResizeRefused("the gang has not completed its initial "
                                "rendezvous yet")
        if self.resizing:
            raise ResizeRefused("a resize is already in progress")
        if size < self.min_tasks:
            raise ResizeRefused(
                f"resize to {size} refused: below tony.elastic.min-tasks "
                f"({self.min_tasks})")
        live = sorted(t.index for t in session.all_tasks()
                      if t.job_name == self.job and not t.status.terminal)
        if not live:
            raise ResizeRefused(f"no live {self.job} tasks to resize")
        if size == len(live):
            raise ResizeRefused(f"gang already has {size} member(s)")
        if size < len(live):
            return live[:size]
        members = set(live)
        i = 0
        while len(members) < size:
            if i not in members:
                members.add(i)
            i += 1
        return sorted(members)

    # -- op lifecycle (driven by the coordinator) -------------------------
    def begin(self, members: List[int], live_tasks: "Iterable[Task]",
              reason: str, mgen: Optional[int] = None, target: str = "",
              migrate: bool = False) -> _Op:
        """Start a resize (or supersede the in-flight one with a smaller
        membership — the second host dying during a drain). Bumps the
        membership generation unless ``mgen`` pins it (recovery re-entry
        of a journaled in-flight resize). ``live_tasks`` are the elastic
        jobtype's current non-terminal tasks; members of the new set must
        park, the rest are released. ``migrate``/``target`` turn the op
        into a live migration: every member drains and the remesh
        relaunches the gang on the target slice (a plain ``begin`` that
        supersedes a migrate op folds the move into an ordinary shrink —
        a failed migration is never worse than a host loss)."""
        with self._lock:
            new_mgen = int(mgen) if mgen is not None else self.mgen + 1
            self.mgen = max(self.mgen, new_mgen)
            op = _Op(new_mgen, self.job, members, reason, self._now(),
                     target=target, migrate=migrate)
            prev = self._op
            if prev is not None:
                # Supersede: keep the ORIGINAL start time so the barrier
                # timeout bounds the whole disturbance, not each bump.
                op.started = prev.started
                op.size_before = prev.size_before
            member_set = set(op.members)
            for t in live_tasks:
                if t.index in member_set:
                    op.awaiting.add(t.task_id)
                else:
                    op.release.add(t.task_id)
            if prev is None:
                op.size_before = len(op.awaiting) + len(op.release)
            self._op = op
            return op

    def directive_for(self, task_id: str) -> Optional[dict]:
        """The resize directive to ride this task's next heartbeat
        response — re-sent every beat while the drain runs (idempotent:
        the executor dedups on mgen), so a lost response costs one
        heartbeat interval, not the resize."""
        with self._lock:
            op = self._op
            if op is None or op.phase != DRAIN:
                return None
            base = {"mgen": op.mgen, "size": len(op.members),
                    "members": list(op.members),
                    "grace_s": self.drain_grace_s}
            if task_id in op.release:
                return {**base, "action": "release"}
            if task_id in op.awaiting or task_id in op.parked:
                if op.migrate:
                    # A migrating executor must NOT wait at the barrier:
                    # the spec it would receive belongs to its fresh
                    # replacement on the destination slice (same task_id,
                    # same mgen), and relaunching here would put two
                    # incarnations of the gang in training at once. The
                    # marker tells it to ack the park and exit instead.
                    return {**base, "action": "drain", "migrate": True,
                            "target": op.target}
                return {**base, "action": "drain"}
            return None

    def ack_registration(self, task_id: str, mgen: int) -> bool:
        """A register frame arrived during the op: a survivor carrying
        the op's mgen counts as PARKED (its user process is down and it
        is waiting at the barrier). Returns True iff this ack newly
        parked a survivor."""
        with self._lock:
            op = self._op
            if op is None or int(mgen) != op.mgen:
                return False
            if task_id in op.awaiting:
                op.awaiting.discard(task_id)
                op.parked.add(task_id)
                return True
            return False

    def note_task_gone(self, task_id: str) -> None:
        """A task died or was reaped mid-op: stop waiting on it (its
        index, if still a member, gets a fresh launch at remesh)."""
        with self._lock:
            op = self._op
            if op is None:
                return
            op.awaiting.discard(task_id)
            op.parked.discard(task_id)
            op.release.discard(task_id)

    def is_released(self, task_id: str) -> bool:
        with self._lock:
            return self._op is not None and task_id in self._op.release

    def is_parked_for_migration(self, task_id: str) -> bool:
        """Did this task park under an in-flight migration's DRAIN? A
        migrating executor acks the park and then self-exits (its
        incarnation cannot follow the gang to the destination slice), so
        its backend completion is EXPECTED — absorbed like a released
        task's, never folded into a shrink that would abandon the move."""
        with self._lock:
            op = self._op
            return op is not None and op.migrate and op.phase == DRAIN \
                and task_id in op.parked

    @property
    def drain_complete(self) -> bool:
        with self._lock:
            op = self._op
            return op is not None and op.phase == DRAIN \
                and not op.awaiting

    def mark_remeshed(self) -> None:
        with self._lock:
            if self._op is not None:
                self._op.phase = BARRIER

    def timed_out(self) -> bool:
        with self._lock:
            op = self._op
            return op is not None and \
                self._now() - op.started > self.barrier_timeout_s

    def finish(self) -> Optional[_Op]:
        with self._lock:
            op, self._op = self._op, None
            return op

    abandon = finish

    def reset_for_epoch(self) -> None:
        """Retry epoch: the new gang relaunches at the configured size;
        membership state dies with the old gang. The generation itself
        stays monotonic so pre-reset zombies remain fenced."""
        with self._lock:
            self._op = None
            self.established = False

    # -- fencing ----------------------------------------------------------
    def fences_frame(self, task_known: bool,
                     mgen: Any) -> Optional[str]:
        """Should a register/heartbeat frame be rejected as stale
        topology? Returns the fence reason, or None to accept.

        - A frame for a task that is NOT in the current matrix (removed
          by a shrink) is always fenced: that executor belongs to a
          topology that no longer exists.
        - A known task's frame with a stale membership generation is
          fenced only when NO resize is in flight — during a resize the
          old generation is expected (the directive that teaches the new
          one may still be in flight).
        """
        if not self.enabled:
            return None
        if not task_known:
            return (f"not a member of membership generation {self.mgen} "
                    f"(removed by an elastic resize)")
        mg = int(mgen if mgen is not None else -1)
        if mg < 0:
            return None          # pre-elastic caller: compat-accepted
        with self._lock:
            if self._op is not None:
                return None
            if mg != self.mgen:
                return (f"stale membership generation {mg} "
                        f"(current {self.mgen})")
        return None
