"""The job coordinator: per-job controller process.

Reference model: ``ApplicationMaster.java`` (1238 LoC) — lifecycle
prepare→start→monitor→(reset/retry)→stop (:296-297, ``run`` :312):
registers RPC servers (:402-413), writes the frozen config + event stream to
the history dir (:456-457), launches executors, runs the heartbeat liveness
monitor (:188-208), applies whole-job retry by resetting the session with a
bumped session id (:356-371, :559-575), and waits for the client's finish
signal before tearing down (:684).

TPU-first deltas:
- No container-allocation matching: the backend launches whole gangs (slice
  leases are atomic — SURVEY.md §7 hard part (a)).
- One RPC server carries the application + metrics surfaces.
- The rendezvous the coordinator brokers doubles as the JAX coordination
  bootstrap: task 0's spec becomes ``JAX_COORDINATOR_ADDRESS`` downstream.

Fault-injection hooks honoured here (reference ``Constants.java:116-121``,
SURVEY.md §4.1): TEST_COORDINATOR_CRASH (AM crash analogue,
``ApplicationMaster.java:338-343``), TEST_WORKER_TERMINATION (:1224-1235),
TEST_COMPLETION_DELAY (:1029-1038).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from tony_tpu import constants, faults, tracing
from tony_tpu.alerts import AlertEngine, RegistrySource, default_job_pack
from tony_tpu.cluster.base import Backend, TaskLaunchSpec
from tony_tpu.metrics import MetricsRegistry
from tony_tpu.conf.config import TonyTpuConfig
from tony_tpu.conf import keys as K
from tony_tpu.coordinator import journal, liveness
from tony_tpu.coordinator.coordphases import CoordPhases
from tony_tpu.coordinator.elastic import (BARRIER, DRAIN, ElasticManager,
                                          ResizeRefused)
from tony_tpu.coordinator.journal import SessionJournal
from tony_tpu.coordinator.liveness import ProgressTracker
from tony_tpu.coordinator.migrate import MigrateRefused, plan_migration
from tony_tpu.coordinator.scheduler import GangScheduler
from tony_tpu.coordinator.session import (FailureDomain, Session,
                                          SessionStatus, Task, TaskStatus)
from tony_tpu.devtools.race import guarded
from tony_tpu.diagnosis.exitcodes import describe_exit
from tony_tpu.events.events import Event, EventHandler, EventType
from tony_tpu.events import history
from tony_tpu.rpc.wire import FencedError, RpcServer
from tony_tpu.utils import durable
from tony_tpu.utils.durable import DurableWriteError

log = logging.getLogger(__name__)


class CoordinatorCrash(RuntimeError):
    """Raised by the TEST_COORDINATOR_CRASH hook."""


class _RpcService:
    """The 7-method application surface + metrics, dispatched by RpcServer.

    Reference: ``tensorflow_cluster_service_protos.proto:11-19`` —
    getTaskInfos / getClusterSpec / registerWorkerSpec / registerTensorBoardUrl
    / registerExecutionResult / finishApplication / taskExecutorHeartbeat —
    plus the Writable metrics channel (``rpc/MetricsRpc.java``).
    """

    def __init__(self, coord: "Coordinator"):
        self._c = coord

    # NOTE: the reference's getTaskInfos/getClusterSpec RPCs are gone on
    # purpose (tonylint rpc-parity: dead surface). register_worker_spec
    # returns the cluster spec once the barrier opens, and
    # get_application_report carries per-task info — nothing ever called
    # the standalone methods.

    def register_worker_spec(self, task_id: str, host: str, port: int,
                             session_id: int = -1,
                             mgen: int = -1) -> Optional[dict]:
        return self._c.register_worker_spec(task_id, host, port,
                                            session_id=session_id,
                                            mgen=mgen)

    def register_tensorboard_url(self, task_id: str, url: str,
                                 session_id: int = -1) -> bool:
        return self._c.register_tensorboard_url(task_id, url,
                                                session_id=session_id)

    def register_execution_result(self, task_id: str, exit_code: int,
                                  session_id: int = -1,
                                  diagnostics: Optional[dict] = None) -> int:
        return self._c.register_execution_result(task_id, exit_code,
                                                 session_id=session_id,
                                                 diagnostics=diagnostics)

    def finish_application(self) -> str:
        self._c.client_signalled_finish.set()
        return self._c.final_status.value

    def task_executor_heartbeat(self, task_id: str, session_id: int = -1,
                                progress: Optional[dict] = None,
                                mgen: int = -1):
        return self._c.heartbeat(task_id, session_id=session_id,
                                 progress=progress, mgen=mgen)

    def resize_application(self, size: int, job: str = "") -> dict:
        """Operator-initiated elastic resize (`tony-tpu resize`)."""
        return self._c.resize_application(int(size), job=str(job or ""))

    def migrate_application(self, target: str, job: str = "",
                            reason: str = "") -> dict:
        """Live migration to another slice (`tony-tpu migrate`)."""
        return self._c.migrate_application(str(target or ""),
                                           job=str(job or ""),
                                           reason=str(reason or ""))

    def get_application_report(self) -> dict:
        return self._c.application_report()

    def kill_application(self) -> bool:
        """Client-initiated force kill (reference
        ``TonyClient.forceKillApplication`` :959)."""
        self._c.request_stop("killed by client")
        return True

    def metrics__push(self, task_id: str, metrics: dict) -> bool:
        return self._c.metrics_push(task_id, metrics)

    def metrics__get(self, task_id: str) -> Optional[dict]:
        return self._c.metrics_get(task_id)

    def metrics__live(self) -> dict:
        """Live per-task utilization snapshot (the `tony-tpu top` feed)."""
        return self._c.metrics_live()

    def profile__start(self, steps: int = 0, task: str = "") -> dict:
        """On-demand device capture (`tony-tpu profile <app>`): arm
        jax.profiler on a RUNNING task at its next step boundary."""
        return self._c.profile_start(int(steps or 0), str(task or ""))

    def profile__status(self) -> dict:
        """Poll surface for the profile CLI: every request + its state."""
        return self._c.profile_status()

    def trace__push(self, records) -> int:
        """Executor/client span intake: remote spans land in the job's
        span log, stitching the cross-process trace tree."""
        return self._c.ingest_trace_records(records)

    def alerts(self) -> dict:
        """Live alert state (`tony-tpu alerts <app>`, portal banner)."""
        return self._c.alerts_snapshot()


@guarded
class Coordinator:
    #: tonyrace registry (devtools/race.py + the guarded-by lint): the
    #: beat-path maps are written by RPC handler threads (heartbeat
    #: beacon fold, metrics.push, execution-result diagnostics) and read
    #: by other RPC threads (metrics.live) and the monitor tick
    #: (heartbeat expiry, report building, teardown) — every touch
    #: holds ``_hb_lock``; the profile directive map keeps its own lock.
    #: The None entries are audited single-writer/atomic rebinds: spans
    #: and scheduler state owned by the monitor thread, throttles, and
    #: status scalars whose readers tolerate old-or-new.
    GUARDED_BY = {
        "_last_hb": "_hb_lock",
        "metrics_store": "_hb_lock",
        "_task_diag": "_hb_lock",
        "_phase_latest": "_hb_lock",
        "_recovered_steps": "_hb_lock",
        "_progress_journal_t": "_hb_lock",
        "_profile_reqs": "_profile_lock",
        "_profile_seq": "_profile_lock",
        # -- audited, not lock-enforced (atomic/single-writer) ---------
        "tb_url": None,
        "final_status": None,
        "scheduler": None,
        "_stop_reason": None,
        "_reregistration_grace": None,
        "_infra_retries_used": None,
        "_preempt_retries_used": None,
        "_attempt": None,
        "_schedule_start": None,
        "_worker_termination_done": None,
        "_final_conf_path": None,
        "_alerts_degraded": None,
        "_prom_last_write": None,
        "_prom_thread": None,
        "_run_span": None,
        "_epoch_span": None,
        "_rendezvous_span": None,
        "session": None,
    }

    def __init__(self, conf: TonyTpuConfig, app_id: str, backend: Backend,
                 history_root: str, user: str = "",
                 rpc_token: Optional[str] = None,
                 recover: bool = False, addr_file: str = ""):
        self.conf = conf
        self.app_id = app_id
        self.backend = backend
        self.user = user or os.environ.get("USER", "unknown")
        self.history_root = history_root
        # Where this coordinator's host/port/token lands (written by
        # __main__/the client); exported to executors so they can
        # RE-resolve a restarted coordinator (new ephemeral port).
        self.addr_file = addr_file
        job_dir = history.intermediate_dir(history_root, app_id)
        self.job_dir = job_dir
        self.journal_path = os.path.join(job_dir, constants.JOURNAL_FILE)
        # --- crash recovery: replay the write-ahead journal BEFORE any
        # other state exists — the fencing generation must be known before
        # the RPC server is created, and the original started_ms before
        # the event stream reattaches to its in-progress file.
        self._recover_state: Optional[journal.ReplayState] = None
        if recover:
            self._recover_state = journal.replay(self.journal_path)
            if self._recover_state.torn_tail:
                log.warning("journal had a torn tail; recovered from the "
                            "%d-record prefix",
                            self._recover_state.records)
        st = self._recover_state
        # Generations are monotonic across coordinator lives: 1 for a
        # fresh job, last-journaled + 1 on every recovery. Carried in
        # every RPC frame (rpc/wire.py) — the split-brain fence.
        self.generation = (st.generation + 1) if st else 1
        self.session = Session(conf, session_id=st.session_id if st else 0)
        # Elastic membership (coordinator/elastic.py): None when the knob
        # is off — every elastic branch below is `self.elastic is not
        # None` gated, so non-elastic jobs pay nothing.
        self.elastic = ElasticManager(conf) \
            if conf.get_bool(K.ELASTIC_ENABLED) else None
        if st is not None:
            for job_name in sorted(st.scheduled_jobs):
                self.session.mark_job_scheduled(job_name)
            if self.elastic is not None:
                self.elastic.mgen = max(self.elastic.mgen,
                                        st.elastic_mgen)
                # The last APPLIED resize is the matrix the journal's
                # task records describe — rebuild it before folding them.
                for job_name, members in st.applied_members.items():
                    if job_name in self.session.jobs:
                        self.session.resize_job(job_name, members)
                # The last APPLIED migration moved the job: re-pin its
                # node pool so recovery relaunches land on the slice the
                # gang actually runs on, not the conf's original.
                for job_name, target in st.migrated_target.items():
                    if job_name in self.session.jobs:
                        self.session.jobs[job_name].node_pool = target
            for task_id, tr in st.tasks.items():
                self.session.restore_task(
                    task_id, TaskStatus(tr.status),
                    host=tr.host, port=tr.port, exit_code=tr.exit_code,
                    domain=(FailureDomain(tr.domain) if tr.domain
                            else None),
                    registered=tr.registered)
        self.scheduler: Optional[GangScheduler] = None
        self.metrics_store: Dict[str, dict] = {}
        # Executor-shipped postmortem context (register_execution_result
        # `diagnostics`): extracted user traceback + decoded exit signal,
        # folded into the task's TASK_FINISHED and the incident bundle.
        self._task_diag: Dict[str, dict] = {}
        self.tb_url: str = ""
        self.client_signalled_finish = threading.Event()
        self.final_status = SessionStatus.RUNNING
        self._stop_requested = threading.Event()
        self._stop_reason = ""
        # Recovery keeps the ORIGINAL start time: the history filename
        # grammar embeds it, and the recovered coordinator must reattach
        # to (and eventually finalize) the first life's in-progress file.
        self._started_ms = (st.started_ms if st and st.started_ms
                            else int(time.time() * 1000))
        # While True, the monitor runs the re-registration grace window
        # instead of the first-rendezvous registration timeout.
        self._reregistration_grace = st is not None
        # Per-domain retry budgets (coordinator/session.py FailureDomain):
        # INFRA_TRANSIENT draws on retry-count; PREEMPTION draws on its
        # own free budget first (expected churn must not exhaust the
        # budget kept for real failures); USER_ERROR is terminal unless
        # the reference-compat escape hatch is set.
        self._retries_total = conf.get_int(K.APPLICATION_RETRY_COUNT, 0)
        self._preempt_retries_total = conf.get_int(
            K.APPLICATION_PREEMPTION_RETRY_COUNT, 3)
        self._retry_user_errors = conf.get_bool(
            K.APPLICATION_RETRY_USER_ERRORS)
        self._infra_retries_used = st.infra_retries_used if st else 0
        self._preempt_retries_used = st.preempt_retries_used if st else 0
        self._attempt = st.session_id if st else 0
        # Deterministic fault injection (tony.fault.*): install for this
        # process; _task_env forwards the same spec to every executor.
        faults.install_from_conf(conf)
        self._last_hb: Dict[str, float] = {}
        self._hb_lock = threading.Lock()
        # Step-time attribution (tony_tpu/profiling/): the latest phase
        # beacon per task — cumulative per-phase seconds + attributed
        # wall. Values are replaced whole (never mutated), so readers
        # (metrics_live, the perf.json writer) take a dict() snapshot.
        self._phase_latest: Dict[str, dict] = {}
        # On-demand device profiling: task_id → request dict. Directives
        # ride heartbeat responses until the task's beacon reports a
        # terminal status (the PR 3 dump / PR 8 RESIZE pattern, deduped
        # executor-side by the monotonic request id).
        self._profile_reqs: Dict[str, dict] = {}
        self._profile_seq = 0
        self._profile_lock = threading.Lock()
        # Progress-based liveness on top of the heartbeat monitor
        # (coordinator/liveness.py): executors piggyback step-counter
        # beacons on heartbeats; this tracker turns frozen counters into
        # hang verdicts and rate skew into straggler events. On recovery,
        # journalled counters re-arm each task with a FRESH deadline as
        # it re-registers — the outage must not expire deadlines.
        self.progress = ProgressTracker(conf)
        self._recovered_steps: Dict[str, float] = \
            {tid: tr.steps for tid, tr in st.tasks.items()
             if tr.steps >= 0} if st else {}
        self._progress_journal_t: Dict[str, float] = {}
        self._schedule_start: float = 0.0
        self._worker_termination_done = False
        self._final_conf_path = ""

        # --- distributed tracing (tony_tpu/tracing.py): the coordinator
        # owns the job's span log, next to the jhist stream. A recovered
        # coordinator rejoins the ORIGINAL trace (id read back from the
        # log) so the outage shows up as a gap in one tree, not two trees.
        trace_path = os.path.join(job_dir, constants.TRACE_FILE)
        trace_id = tracing.existing_trace_id(trace_path) if st else ""
        self.tracer = tracing.Tracer(
            trace_id=trace_id or os.environ.get(constants.TRACE_ID_ENV)
            or None,
            service="coordinator", path=trace_path,
            enabled=conf.get_bool(K.TRACE_ENABLED, True))
        mode = str(conf.get(K.TRACE_RPC_SPANS, "significant") or "")
        self._rpc_span_mode = mode if mode in ("all", "significant",
                                               "off") else "significant"
        # Launch-path spans from the backend (pool.lease adoption) join
        # the same tree — the backend parents them under the task
        # lifecycle span id it finds in the launch env.
        try:
            backend.set_tracer(self.tracer)
        except Exception:  # noqa: BLE001 — tracing is never load-bearing
            pass
        self._run_span = tracing.NULL_SPAN
        self._epoch_span = tracing.NULL_SPAN
        self._rendezvous_span: Optional[object] = None
        self._task_spans: Dict[str, object] = {}
        # task_id → hosts that already failed it with an INFRA domain
        # this run (exclude-on-retry: a relaunch of the task is steered
        # off those hosts via TaskLaunchSpec.exclude_hosts — a retry
        # that lands back on the hardware that just killed it is a
        # burned epoch). USER_ERROR never records a host: the code
        # would fail anywhere.
        self._failed_hosts: Dict[str, List[str]] = {}

        # --- live metrics (tony_tpu/metrics.py): beacon-fed registry,
        # rendered as Prometheus exposition into <job_dir>/metrics.prom
        # (the portal's /metrics scrape source) on the export cadence.
        # Counters reload across --recover so they never step backwards.
        self.metrics = MetricsRegistry(
            ring_points=conf.get_int(K.METRICS_RING_POINTS, 512))
        self._counters_path = os.path.join(job_dir,
                                           constants.METRICS_COUNTERS_FILE)
        if st is not None:
            self.metrics.load_counters(self._counters_path)
        self._prom_path = os.path.join(job_dir, constants.METRICS_PROM_FILE)
        self._prom_interval_s = float(
            conf.get(K.METRICS_EXPORT_INTERVAL_S, 2.0) or 2.0)
        self._prom_last_write = 0.0
        # Prometheus rendering walks every series — milliseconds at
        # thousand-task width (measured by the prom_export phase below)
        # — so the render+write runs on a single-flight worker, never
        # on the monitor tick or a beat.
        self._prom_thread: Optional[threading.Thread] = None

        # --- control-plane self-observation (coordinator/coordphases.py):
        # the coordinator's OWN per-tick phase ring — hb_scan /
        # journal_fsync / beacon_fold / prom_export / rpc_serve /
        # rendezvous_barrier, sum-to-wall like step phases — exported as
        # tony_coord_* families and classified by the control-plane
        # verdicts (profiling/verdict.py classify_coord). This is the
        # measurement layer the width restructuring (ROADMAP item 5)
        # is aimed by.
        self.coordphases = CoordPhases(
            conf.get_int(K.COORD_PHASE_RING_TICKS, 256))
        self._coord_counter_prev: Dict[str, float] = {}

        # --- alerting (tony_tpu/alerts/): the job-scope rule pack,
        # evaluated on the monitor tick behind the never-blocks-the-tick
        # degrade contract (fault site "alerts.eval"). Every transition
        # is journaled write-ahead as REC_ALERT; on --recover the
        # replayed last-state-per-rule re-arms the engine, so a firing
        # alert survives a coordinator SIGKILL with no duplicate record.
        self._alerts_degraded = not conf.get_bool(K.ALERTS_ENABLED, True)
        self.alerts = AlertEngine(default_job_pack(conf))
        if st is not None and st.alerts:
            self.alerts.seed(st.alerts)

        if rpc_token is None and conf.get_bool(K.APPLICATION_SECURITY_ENABLED):
            import secrets
            rpc_token = secrets.token_hex(16)
        self.rpc_token = rpc_token
        tls = None
        self.tls_cert = str(conf.get(K.SECURITY_TLS_CERT, "") or "")
        if self.tls_cert:
            from tony_tpu.rpc.wire import server_tls_context
            tls = server_tls_context(
                self.tls_cert, str(conf.get(K.SECURITY_TLS_KEY, "")))
        self.rpc = RpcServer(
            _RpcService(self),
            host=str(conf.get(K.COORDINATOR_HOST_KEY)),
            port=conf.get_int(K.COORDINATOR_PORT_KEY, 0),
            token=rpc_token, tls=tls,
            generation=self.generation,
            on_superseded=self._on_superseded,
            on_request=self._on_rpc_request)

        self.events = EventHandler(
            job_dir, history.in_progress_name(app_id, self._started_ms,
                                              self.user),
            on_emit=self._on_event_emitted)
        # Write-ahead journal (crash recovery): opened for append in both
        # lives; the generation bump is the first record of each life so
        # even an immediately-recrashed coordinator leaves a fence trail.
        self.journal = SessionJournal(
            self.journal_path,
            enabled=conf.get_bool(K.COORDINATOR_JOURNAL_ENABLED, True),
            observer=self.coordphases.note_journal_append)
        self.journal.generation(self.generation)
        if st is None:
            self.journal.app(app_id, self._started_ms, self.user)

        hb_interval = conf.get_int(K.TASK_HEARTBEAT_INTERVAL_MS, 1000)
        max_missed = conf.get_int(K.TASK_MAX_MISSED_HEARTBEATS, 25)
        # Reference expiry formula: hbInterval * max(3, maxMisses)
        # (ApplicationMaster.java:205).
        self._hb_expiry_s = hb_interval * max(3, max_missed) / 1000.0

    # ------------------------------------------------------------------
    # Fencing
    # ------------------------------------------------------------------
    def _on_superseded(self, newer_generation: int) -> None:
        """A frame proved a successor coordinator exists (rpc/wire.py
        server-side generation check): THIS process is the zombie half of
        a split brain and must stand down without touching the gang —
        the successor owns it now."""
        log.error("superseded by coordinator generation %d (we are %d); "
                  "standing down", newer_generation, self.generation)
        self.request_stop(
            f"superseded by coordinator generation {newer_generation}")

    def _check_epoch(self, task_id: str, session_id) -> None:
        """Reject RPCs from a stale retry epoch. An executor surviving
        from a pre-reset session must not refresh the NEW epoch's task
        liveness or corrupt its results; the FencedError is terminal on
        the executor side (it kills its user process and exits).
        session_id < 0 = caller doesn't know (accepted — compat)."""
        sid = int(session_id if session_id is not None else -1)
        if sid >= 0 and sid != self.session.session_id:
            raise FencedError(
                f"task {task_id} belongs to session epoch {sid}; the "
                f"coordinator is at epoch {self.session.session_id}")

    # ------------------------------------------------------------------
    # Observability: tracing + live metrics
    # ------------------------------------------------------------------
    #: periodic methods excluded from per-RPC spans in 'significant' mode
    #: (they arrive ~1/s/task and belong in the latency histograms, not
    #: the span log; 'all' traces them anyway, 'off' traces nothing).
    _PERIODIC_RPC = frozenset((
        "task_executor_heartbeat", "metrics.push", "metrics.get",
        "metrics.live", "get_application_report", "trace.push"))

    def _on_rpc_request(self, method: str, seconds: float,
                        ok: bool) -> None:
        """RpcServer hook: every dispatched request lands in the server
        latency histogram + request counter, and significant ones get a
        span parented under the caller's trace context."""
        # Control-plane self-observation: the dispatch's wall (minus
        # whatever its handler already booked to a named phase — journal
        # appends, the beacon fold) lands in the rpc_serve tick phase,
        # and heartbeats feed the beats/s rate.
        self.coordphases.note_dispatch(method, seconds)
        app = {"app": self.app_id}
        self.metrics.histogram(
            "tony_rpc_server_seconds", {**app, "method": method},
            help="Coordinator-side RPC dispatch latency.").observe(seconds)
        self.metrics.counter(
            "tony_rpc_requests_total",
            {**app, "method": method, "ok": str(bool(ok)).lower()},
            help="RPC requests dispatched by the coordinator.").inc()
        if self._rpc_span_mode == "off" or not self.tracer.enabled:
            return
        if self._rpc_span_mode == "significant" \
                and method in self._PERIODIC_RPC:
            return
        ctx = tracing.get_rpc_context()
        end = tracing.now_us()
        self.tracer.emit(f"rpc.{method}", start_us=end - int(seconds * 1e6),
                         end_us=end,
                         parent=ctx[1] if ctx else self._run_span,
                         attrs={"ok": bool(ok)})

    def _on_event_emitted(self, event: Event) -> None:
        self.metrics.counter(
            "tony_events_total",
            {"app": self.app_id, "type": event.type.value},
            help="Job-history events emitted, by type.").inc()

    def _observe_beacon(self, task_id: str,
                        progress: Optional[dict]) -> None:
        """Fold a heartbeat's metrics beacon into the registry: the
        steady-state utilization series behind /metrics and `top`."""
        if not isinstance(progress, dict):
            return
        labels = {"app": self.app_id, "task": task_id}
        if "steps" in progress:
            try:
                self.metrics.gauge(
                    "tony_task_steps_completed", labels,
                    help="Step counter from the task's progress beacon."
                ).set(float(progress["steps"]))
            except (TypeError, ValueError):
                pass
        m = progress.get("metrics")
        if isinstance(m, dict):
            for src, name, help_ in (
                    ("steps_per_sec", "tony_task_steps_per_sec",
                     "Training steps per second (telemetry.step)."),
                    ("tokens_per_sec", "tony_task_tokens_per_sec",
                     "Tokens per second (telemetry.step tokens=)."),
                    ("mfu", "tony_task_mfu",
                     "Model FLOPs utilization vs peak bf16."),
                    ("hbm_bytes", "tony_task_hbm_bytes",
                     "Device HBM bytes in use (user process)."),
                    ("rss_bytes", "tony_task_rss_bytes",
                     "Process-tree resident set size bytes.")):
                if src in m:
                    try:
                        self.metrics.gauge(name, labels, help=help_).set(
                            float(m[src]))
                    except (TypeError, ValueError):
                        continue
        ph = progress.get("phases")
        if isinstance(ph, dict) and isinstance(ph.get("cum"), dict):
            for name, secs in ph["cum"].items():
                try:
                    self.metrics.gauge(
                        "tony_step_phase_seconds",
                        {**labels, "phase": str(name)},
                        help="Cumulative seconds of step wall time "
                             "attributed to each phase "
                             "(telemetry.phase; 'other' = unattributed)."
                    ).set(float(secs))
                except (TypeError, ValueError):
                    continue
            # Replaced whole under the beat lock; readers (metrics.live
            # on other RPC threads, the perf.json writer on the monitor)
            # snapshot under the same lock — the tonyrace bring-up
            # flagged this fold-vs-read pair as its coordinator hot spot.
            with self._hb_lock:
                self._phase_latest[task_id] = dict(ph)
        prof = progress.get("profile")
        if isinstance(prof, dict):
            self._observe_profile_beacon(task_id, prof)
        rpc = progress.get("rpc")
        if isinstance(rpc, dict):
            self.metrics.set_histogram_snapshot(
                "tony_rpc_client_seconds", labels, rpc,
                help="Executor-side RPC call latency (cumulative over "
                     "the executor's lifetime).")

    def _maybe_write_prom(self, force: bool = False) -> None:
        """Refresh <job_dir>/metrics.prom (atomic replace) + the counter
        snapshot, throttled to the export cadence — the file the portal
        serves live at /metrics. The gauge refresh (O(tasks)) stays on
        the caller; the RENDER (O(all series) — the measured bulk at
        width) runs on a single-flight worker thread so neither a beat
        nor a monitor tick pays it. ``force`` (teardown) renders
        synchronously: the final exposition must be on disk before the
        coordinator exits."""
        now = time.monotonic()
        if not force and now - self._prom_last_write < self._prom_interval_s:
            return
        self._prom_last_write = now
        with self.coordphases.phase("prom_export"):
            self._update_prom_gauges()
        if force:
            self._render_prom()
            return
        t = self._prom_thread
        if t is None or not t.is_alive():
            self._prom_thread = threading.Thread(
                target=self._render_prom, name="prom-export", daemon=True)
            self._prom_thread.start()

    def _update_prom_gauges(self) -> None:
        """The cheap half of an export: refresh the coordinator-owned
        gauges (per-task liveness, gang sizes, and the control-plane
        self-observation families) in the registry."""
        now = time.monotonic()
        app = {"app": self.app_id}
        self.metrics.gauge(
            "tony_coordinator_generation", app,
            help="Coordinator generation (bumps on --recover)."
        ).set(self.generation)
        self.metrics.gauge("tony_session_epoch", app,
                           help="Current retry epoch.").set(
            self.session.session_id)
        with self._hb_lock:
            hb = dict(self._last_hb)
        for task_id, last in hb.items():
            self.metrics.gauge(
                "tony_task_heartbeat_age_seconds",
                {**app, "task": task_id},
                help="Seconds since the task's last heartbeat — the same "
                     "signal the liveness monitor expires on.").set(
                now - last)
        counts: Dict[str, int] = {}
        for t in self.session.all_tasks():
            counts[t.status.value] = counts.get(t.status.value, 0) + 1
        for status, n in counts.items():
            self.metrics.gauge("tony_tasks", {**app, "status": status},
                               help="Tasks by status.").set(n)
        for name, job in self.session.jobs.items():
            self.metrics.gauge(
                "tony_gang_size", {**app, "job": name},
                help="Current task count of the jobtype's gang — "
                     "changes live on an elastic resize.").set(
                job.instances)
        if self.elastic is not None:
            self.metrics.gauge(
                "tony_membership_generation", app,
                help="Elastic membership generation (bumps on every "
                     "resize; the topology fence).").set(
                self.elastic.mgen)
        self._update_coord_metrics(app)

    def _update_coord_metrics(self, app: Dict[str, str]) -> None:
        """Control-plane self-observation families: the coordinator's own
        phase seconds, tick duration, journal throughput + fsync
        histogram, beats received, registered-task count."""
        snap = self.coordphases.snapshot()
        if not snap:
            return
        for name, secs in sorted((snap.get("cum") or {}).items()):
            self.metrics.gauge(
                "tony_coord_phase_seconds", {**app, "phase": str(name)},
                help="Cumulative seconds of the coordinator's own tick "
                     "wall attributed to each control-plane phase "
                     "(coordinator/coordphases.py; 'other' = "
                     "unattributed, 'idle' = the monitor sleep)."
            ).set(float(secs))
        self.metrics.gauge(
            "tony_coord_tick_seconds", app,
            help="Recent mean ACTIVE coordinator tick duration "
                 "(attributed non-idle work per monitor tick — the "
                 "number that grows with gang width).").set(
            float(snap.get("tick_active_s", 0.0)))
        self.metrics.gauge(
            "tony_coord_registered_tasks", app,
            help="Tasks currently registered with the coordinator."
        ).set(self.session.num_registered)
        for metric, key_, help_ in (
                ("tony_coord_beats_total", "beats_total",
                 "Heartbeats received by the coordinator."),
                ("tony_journal_records_total", "journal_records_total",
                 "Write-ahead journal records appended (each one "
                 "fsync'd)."),
                ("tony_journal_bytes_total", "journal_bytes_total",
                 "Write-ahead journal bytes appended.")):
            cur = float(snap.get(key_, 0) or 0)
            prev = self._coord_counter_prev.get(metric, 0.0)
            self.metrics.counter(metric, app, help=help_).inc(
                max(0.0, cur - prev))
            self._coord_counter_prev[metric] = cur
        fsync = snap.get("fsync")
        if isinstance(fsync, dict):
            self.metrics.set_histogram_snapshot(
                "tony_journal_fsync_seconds", app, fsync,
                help="Write-ahead journal append latency (fsync "
                     "included) — the histogram behind JOURNAL_BOUND "
                     "evidence.")

    def _render_prom(self) -> None:
        """The expensive half of an export: render the whole exposition
        and write it (atomic replace) + snapshot counters for recovery.
        Runs on the export worker (or synchronously at teardown)."""
        with self.coordphases.phase("prom_export"):
            text = self.metrics.render()
            try:
                durable.atomic_write(self._prom_path,
                                     text.encode("utf-8"))
            except OSError as e:
                log.debug("metrics.prom write failed: %s", e)
            self.metrics.save_counters(self._counters_path)

    def metrics_live(self) -> dict:
        """The `tony-tpu top` feed: current utilization + liveness per
        task, with a short steps/s history for sparklines (ring-buffer
        series, bounded by tony.metrics.ring-points)."""
        now = time.monotonic()
        with self._hb_lock:
            # One snapshot for the whole build: heartbeat ages AND the
            # latest phase beacons — beats keep folding on RPC threads
            # while this runs (the beacon-fold-vs-metrics.live race the
            # tonyrace bring-up flagged).
            hb = dict(self._last_hb)
            phase_snapshot = dict(self._phase_latest)
        tasks = []
        for t in self.session.all_tasks():
            labels = {"app": self.app_id, "task": t.task_id}
            row: Dict[str, object] = {"task": t.task_id,
                                      "status": t.status.value}
            snap = self.progress.snapshot(t.task_id) or {}
            if snap.get("state"):
                row["state"] = snap["state"]
            if "steps" in snap:
                row["steps"] = snap["steps"]
            for name, key in (("tony_task_steps_per_sec", "steps_per_sec"),
                              ("tony_task_mfu", "mfu"),
                              ("tony_task_hbm_bytes", "hbm_bytes"),
                              ("tony_task_rss_bytes", "rss_bytes")):
                v = self.metrics.gauge_value(name, labels)
                if v is not None:
                    row[key] = v
            history_v = self.metrics.gauge_history(
                "tony_task_steps_per_sec", labels)
            if history_v:
                row["steps_per_sec_history"] = history_v[-32:]
            ph = phase_snapshot.get(t.task_id)
            if ph:
                # Recent-window attribution preferred (the live view
                # should show what the step is doing NOW, not the job
                # average); falls back to cumulative.
                from tony_tpu.profiling import phase_fractions

                recent = ph.get("recent")
                if isinstance(recent, dict) and ph.get("recent_wall_s"):
                    fr = phase_fractions(recent, ph["recent_wall_s"])
                else:
                    fr = phase_fractions(ph.get("cum") or {},
                                         ph.get("wall_s", 0.0))
                if fr:
                    row["phases"] = {k: round(v, 4)
                                     for k, v in fr.items()}
            last = hb.get(t.task_id)
            if last is not None:
                row["heartbeat_age_s"] = round(now - last, 3)
            tasks.append(row)
        snap = {"app_id": self.app_id, "generation": self.generation,
                "session_id": self.session.session_id,
                "status": self.session.status.value,
                "gang_size": {name: job.instances
                              for name, job in self.session.jobs.items()},
                "tasks": tasks}
        if phase_snapshot:
            # Live bottleneck verdict over the wall-weighted aggregate —
            # the `top` header line every item-4 perf PR is aimed by.
            from tony_tpu import profiling

            doc = profiling.build_perf_report(self.app_id, phase_snapshot)
            if doc.get("verdict"):
                snap["perf"] = {"verdict": doc["verdict"]["category"],
                                "summary": doc["verdict"]["summary"],
                                "fractions": doc["fractions"]}
        if self.elastic is not None:
            snap["elastic"] = self.elastic.snapshot()
        coord = self._coord_live_row()
        if coord is not None:
            # Coordinator self row (`tony-tpu top` control-plane
            # section): control-plane health must be visible DURING an
            # incident, not only in post-hoc metrics.
            snap["coord"] = coord
        firing = self.alerts.firing()
        if firing or self._alerts_degraded:
            # Firing alerts ride the top feed (alert rows in `tony-tpu
            # top`): a page-worthy breach must be on the screen the
            # operator is already watching.
            snap["alerts"] = {"degraded": self._alerts_degraded,
                              "firing": firing}
        return snap

    # ------------------------------------------------------------------
    # Alerting (tony_tpu/alerts/)
    # ------------------------------------------------------------------
    def _alerts_tick(self) -> None:
        """Evaluate the job-scope alert pack against the live registry.
        Degrade contract (the fleet.ledger shape): any evaluator failure
        disables alerting for the rest of this coordinator life with one
        warning — the monitor tick never blocks or fails on its own
        observability."""
        if self._alerts_degraded:
            return
        try:
            faults.check("alerts.eval")
            for tr in self.alerts.evaluate(RegistrySource(self.metrics)):
                self._apply_alert_transition(tr)
        except Exception as e:  # noqa: BLE001 — observability, not duty
            self._alerts_degraded = True
            log.warning(
                "alert evaluation failed (%s) — degrading: alerting "
                "disabled for the rest of this coordinator life", e)

    def _apply_alert_transition(self, tr) -> None:
        """Surface one state-machine step: REC_ALERT write-ahead (dedup-
        fenced by the engine), then the transition counter, the firing
        gauge, and the ALERT_FIRING/ALERT_RESOLVED event (pending stays
        journal-and-counter only — one bad tick never pages, and it
        never spams the event stream either)."""
        if tr.journal:
            self.journal.alert(tr.rule, tr.state, tr.severity, tr.value,
                               tr.labels, tr.summary)
        self.metrics.counter(
            "tony_alert_transitions_total", {"state": tr.state},
            help="alert state-machine transitions journaled").inc()
        for sev, n in self.alerts.firing_count().items():
            self.metrics.gauge(
                "tony_alerts_firing", {"severity": sev},
                help="alerts currently firing, by severity").set(n)
        payload = {"rule": tr.rule, "severity": tr.severity,
                   "value": tr.value, "labels": tr.labels,
                   "summary": tr.summary, "scope": "job"}
        if tr.state == "firing":
            log.warning("ALERT firing [%s]: %s (value=%s %s)",
                        tr.severity, tr.rule, tr.value, tr.labels)
            self.events.emit(Event(EventType.ALERT_FIRING, payload))
        elif tr.state == "resolved":
            log.info("alert resolved: %s", tr.rule)
            self.events.emit(Event(EventType.ALERT_RESOLVED, payload))

    def alerts_snapshot(self) -> dict:
        """The `alerts` RPC: full per-rule state for the CLI/portal."""
        return {"app_id": self.app_id, "scope": "job",
                "degraded": self._alerts_degraded,
                "alerts": self.alerts.snapshot()}

    def metrics_push(self, task_id: str, metrics: dict) -> bool:
        """metrics.push intake (reference ``rpc/MetricsRpc.java``):
        replaced whole under the beat lock — readers (TASK_FINISHED
        payloads, the report builder) snapshot under the same lock."""
        with self._hb_lock:
            self.metrics_store[task_id] = metrics
        return True

    def metrics_get(self, task_id: str) -> Optional[dict]:
        with self._hb_lock:
            return self.metrics_store.get(task_id)

    def _task_metrics(self, task_id: str) -> dict:
        """The task's last pushed metrics blob (TASK_FINISHED payloads,
        report rows) — one locked read."""
        with self._hb_lock:
            return self.metrics_store.get(task_id, {})

    def _coord_live_row(self) -> Optional[dict]:
        """The control-plane self row for metrics.live/top: tick
        duration, beats/s, journal fsync p99 + records/s, registered
        tasks, recent phase fractions, and the control-plane verdict."""
        snap = self.coordphases.snapshot()
        if not snap:
            return None
        fr = self.coordphases.fractions()
        row: Dict[str, object] = {
            "tick_s": round(float(snap.get("tick_active_s", 0.0)), 6),
            "tick_wall_s": round(float(snap.get("recent_wall_s", 0.0)),
                                 6),
            "beats_per_s": round(float(snap.get("beats_per_sec", 0.0)),
                                 2),
            "journal_records_per_s": round(
                float(snap.get("journal_records_per_sec", 0.0)), 2),
            "journal_fsync_p99_s": round(
                float(snap.get("journal_fsync_p99_s", 0.0)), 6),
            "registered_tasks": self.session.num_registered,
        }
        if fr:
            row["phases"] = {k: round(v, 4) for k, v in fr.items()}
            from tony_tpu import profiling

            v = profiling.classify_coord(fr)
            row["verdict"] = v["category"]
            row["summary"] = v["summary"]
        return row

    # ------------------------------------------------------------------
    # On-demand device profiling (tony-tpu profile <app>)
    # ------------------------------------------------------------------
    def profile_start(self, steps: int = 0, task: str = "") -> dict:
        """Arm an on-demand capture: pick the target task (explicit, or
        the chief), allocate a monotonic request id, and let the PROFILE
        directive ride the target's heartbeat responses until its beacon
        reports the result. Refused when disabled, when the task is not
        running, or at the artifact ceiling — never fails the job."""
        if not self.conf.get_bool(K.PROFILE_ENABLED, True):
            return {"ok": False,
                    "message": "on-demand profiling is disabled "
                               "(tony.profile.enabled=false)"}
        steps = steps or self.conf.get_int(K.PROFILE_DEFAULT_STEPS, 5)
        target = None
        if task:
            t = self.session.get_task(task)
            if t is None or t.status.terminal:
                return {"ok": False,
                        "message": f"task {task!r} is not running"}
            target = t
        else:
            live = [t for t in self.session.all_tasks()
                    if not t.status.terminal]
            for t in live:
                if self.session.is_chief(t.job_name, t.index):
                    target = t
                    break
            target = target or (live[0] if live else None)
        if target is None:
            return {"ok": False, "message": "no running task to profile"}
        profile_root = os.path.join(self.job_dir, "profile")
        try:
            existing = sum(1 for d in os.listdir(profile_root)
                           if d.startswith("ondemand-"))
        except OSError:
            existing = 0
        max_artifacts = self.conf.get_int(K.PROFILE_MAX_ARTIFACTS, 8)
        if existing >= max_artifacts:
            return {"ok": False,
                    "message": f"{existing} on-demand artifact(s) "
                               f"already under {profile_root} (ceiling "
                               f"tony.profile.max-artifacts="
                               f"{max_artifacts}); delete old captures"}
        with self._profile_lock:
            self._profile_seq += 1
            req_id = self._profile_seq
            req = {"id": req_id, "task": target.task_id,
                   "steps": int(steps),
                   "dir": os.path.join(
                       profile_root,
                       f"ondemand-{req_id:03d}-"
                       f"{target.task_id.replace(':', '-')}"),
                   "status": "requested"}
            self._profile_reqs[target.task_id] = req
            out = dict(req)
        log.warning("profile: capture of %d step(s) requested on %s "
                    "(request %d) — arming at its next step boundary",
                    steps, target.task_id, req_id)
        return {"ok": True, **out}

    def profile_status(self) -> dict:
        with self._profile_lock:
            return {"requests": [dict(r)
                                 for r in self._profile_reqs.values()]}

    def _profile_directive(self, task_id: str) -> Optional[dict]:
        """The heartbeat-response payload for a pending capture (re-sent
        every beat — the executor dedups by id); None once terminal."""
        with self._profile_lock:
            req = self._profile_reqs.get(task_id)
            if req is None or req["status"] in ("captured", "failed"):
                return None
            return {"id": req["id"], "steps": req["steps"],
                    "dir": req["dir"]}

    def _observe_profile_beacon(self, task_id: str, prof: dict) -> None:
        """Match a beacon's capture status to our request; emit
        TASK_PROFILED exactly once on the terminal transition."""
        try:
            beacon_id = int(prof.get("id", 0))
        except (TypeError, ValueError):
            return
        status = str(prof.get("status", "") or "")
        emit_payload = None
        with self._profile_lock:
            req = self._profile_reqs.get(task_id)
            if req is None or beacon_id != req["id"]:
                return
            if status == "active" and req["status"] == "requested":
                req["status"] = "active"
            elif status in ("captured", "failed") \
                    and req["status"] not in ("captured", "failed"):
                req["status"] = status
                if prof.get("dir"):
                    req["dir"] = str(prof["dir"])
                if prof.get("error"):
                    req["error"] = str(prof["error"])[:300]
                emit_payload = dict(req)
        if emit_payload is not None:
            emit_payload["session_id"] = self.session.session_id
            self.events.emit(Event(EventType.TASK_PROFILED, emit_payload))
            if emit_payload["status"] == "captured":
                log.warning("profile: request %d captured %s step(s) on "
                            "%s — artifact at %s", emit_payload["id"],
                            emit_payload["steps"], task_id,
                            emit_payload["dir"])
            else:
                log.warning("profile: request %d FAILED on %s: %s "
                            "(training continues)", emit_payload["id"],
                            task_id, emit_payload.get("error", "?"))

    def _write_perf_report(self) -> None:
        """<job_dir>/perf.json at finish: phase totals + the bottleneck
        verdict over the job's steady-state step-time attribution. Only
        written when at least one task beaconed phases (a non-telemetry
        job has nothing to attribute). Best-effort by contract."""
        with self._hb_lock:
            snapshot = dict(self._phase_latest)
        if not snapshot:
            return
        try:
            from tony_tpu import profiling

            doc = profiling.build_perf_report(
                self.app_id, snapshot, status=self.final_status.value)
            profiling.save_perf(
                os.path.join(self.job_dir, constants.PERF_FILE), doc)
            v = doc.get("verdict") or {}
            log.warning("perf: %s — %s (perf.json written)",
                        v.get("category", "?"), v.get("summary", ""))
        except Exception:  # noqa: BLE001 — reporting must never fail a job
            log.exception("perf.json write failed")

    def ingest_trace_records(self, records) -> int:
        return self.tracer.write_records(records)

    def _end_task_span(self, task_id: str, **attrs) -> None:
        span = self._task_spans.pop(task_id, None)
        if span is not None:
            span.end(**attrs)

    def _close_epoch_spans(self, status: SessionStatus) -> None:
        """Close the epoch's open spans when its monitor loop returns —
        every span the coordinator opens must close (the golden trace
        test treats unclosed spans as a regression)."""
        if self._rendezvous_span is not None:
            self._rendezvous_span.end(aborted=True)
            self._rendezvous_span = None
        self._epoch_span.end(status=status.value)
        self._epoch_span = tracing.NULL_SPAN

    # ------------------------------------------------------------------
    # Launching
    # ------------------------------------------------------------------
    def _task_env(self, task: Task) -> Dict[str, str]:
        """Identity env contract (reference ApplicationMaster.java:1129-1141)."""
        job = self.session.jobs[task.job_name]
        host, port = self.rpc.address
        env = {
            constants.JOB_NAME: task.job_name,
            constants.TASK_INDEX: str(task.index),
            constants.TASK_NUM: str(job.instances),
            constants.IS_CHIEF: str(
                self.session.is_chief(task.job_name, task.index)).lower(),
            constants.SESSION_ID: str(self.session.session_id),
            constants.APP_ID: self.app_id,
            constants.TASK_ID: task.task_id,
            constants.COORDINATOR_HOST: host,
            constants.COORDINATOR_PORT: str(port),
            constants.METRICS_PORT: str(port),
            constants.COORDINATOR_GENERATION: str(self.generation),
            constants.TASK_COMMAND: job.command,
        }
        if self.addr_file:
            # Lets the executor RE-resolve a restarted coordinator (it
            # rewrites this file with its fresh ephemeral port).
            env[constants.COORDINATOR_ADDR_FILE] = self.addr_file
        if self.elastic is not None:
            # Topology fence: frames from this executor carry the
            # membership generation it was launched under; survivors
            # adopt newer generations from the RESIZE directive.
            env[constants.MEMBERSHIP_GEN] = str(self.elastic.mgen)
            env.setdefault(constants.TASK_KILL_GRACE_ENV,
                           str(self.elastic.drain_grace_s))
        if self.tracer.enabled:
            # Trace context: the executor's spans parent under this
            # task's lifecycle span, stitching one tree per job.
            env[constants.TRACE_ID_ENV] = self.tracer.trace_id
            span = self._task_spans.get(task.task_id)
            if span is not None and getattr(span, "span_id", ""):
                env[constants.TRACE_PARENT_ENV] = span.span_id
        if self.rpc_token:
            env["TONY_RPC_TOKEN"] = self.rpc_token
        ckpt_dir = str(self.conf.get(K.APPLICATION_CHECKPOINT_DIR, "") or "")
        if ckpt_dir:
            env[constants.CHECKPOINT_DIR] = ckpt_dir
        conf_url = str(self.conf.get(K.INTERNAL_CONF_URL, "") or "")
        if self.conf.get_bool(K.APPLICATION_PROFILER_ENABLED) and \
                self.session.is_chief(task.job_name, task.index):
            # Chief-only trace capture into the job history dir, where the
            # portal finds it (tony_tpu/profiler.py contract). With a
            # remote store the chief may be on another host where the job
            # dir doesn't exist: traces go to the task's own workdir and
            # ride the store home (executor uploads post-run, _stop pulls
            # them into the job dir).
            if conf_url:
                env[constants.PROFILE_DIR] = "profile"
                env[constants.PROFILE_UPLOAD] = self._profile_store_url(
                    conf_url)
            else:
                env[constants.PROFILE_DIR] = os.path.join(self.job_dir,
                                                          "profile")
        if conf_url:
            # Remote store configured: executors fetch the frozen config
            # from the store (they may be on another host); the credential
            # travels by env because it gates reading the config itself.
            env[constants.EXECUTOR_CONF] = conf_url
        elif self._final_conf_path:
            env[constants.EXECUTOR_CONF] = self._final_conf_path
        from tony_tpu.storage.store import STORAGE_TOKEN_ENV

        # Credential passthrough: inherited env from the client (the frozen
        # config is scrubbed of it — see client._stage_bundle).
        token = os.environ.get(STORAGE_TOKEN_ENV, "") \
            or str(self.conf.get(K.STORAGE_TOKEN, "") or "")
        if token:
            env[STORAGE_TOKEN_ENV] = token
        env.update(faults.env_passthrough())
        for kv in self.conf.get_list(K.EXECUTION_ENV):
            if "=" in kv:
                k, v = kv.split("=", 1)
                env[k] = v
        env.update(job.env)
        return env

    @staticmethod
    def _profile_store_url(conf_url: str) -> str:
        """Store prefix for chief traces, next to the frozen config
        (<prefix>/tony-final.json → <prefix>/profile)."""
        return conf_url.rsplit("/", 1)[0] + "/profile"

    def _launch_job(self, job_name: str) -> None:
        # Widen the rendezvous barrier to this gang BEFORE any instance can
        # register, so a fast first instance never sees a spec missing its
        # peers (reference adds numExpectedTasks at schedule time,
        # ``TonySession.addNumExpectedTask`` :197).
        self.session.mark_job_scheduled(job_name)
        self.journal.job_scheduled(job_name, self.session.session_id)
        for i in self.session.members(job_name):
            task = self.session.get_task(f"{job_name}:{i}")
            if task is None or task.status != TaskStatus.NEW:
                continue
            if not self._launch_task(task):
                return

    def _launch_task(self, task: Task) -> bool:
        """Launch ONE task (gang launch and elastic relaunch/grow share
        this path). Returns False when the backend spawn failed and the
        session was failed INFRA_TRANSIENT."""
        if task.status.terminal:
            # Terminal-state discipline (tonylint terminal-state):
            # relaunching a finished Task object would resurrect a
            # closed identity under its old exit verdict — resize and
            # retry paths always hand this a FRESH Task.
            log.error("refusing to launch terminal task %s (%s)",
                      task.task_id, task.status.value)
            return False
        job = self.session.jobs[task.job_name]
        # Write-ahead: journal the SCHEDULED transition before the
        # backend spawn. A crash in between recovers a task the
        # journal says was launched but that never registers — the
        # re-registration grace expires into a normal retry epoch,
        # never a duplicate launch over a live executor.
        self.journal.task(task.task_id, TaskStatus.SCHEDULED.value,
                          self.session.session_id)
        # Lifecycle span opens BEFORE the env is built so the
        # executor inherits it as its trace parent.
        if task.task_id not in self._task_spans:
            self._task_spans[task.task_id] = self.tracer.start_span(
                "task.lifecycle", parent=self._epoch_span,
                task=task.task_id, attrs={"job": task.job_name})
        spec = TaskLaunchSpec(
            task_id=task.task_id, job_name=task.job_name, index=task.index,
            command=job.command, env=self._task_env(task),
            vcores=job.vcores, memory=job.memory, chips=job.chips,
            node_pool=job.node_pool, docker_image=job.docker_image,
            exclude_hosts=tuple(
                self._failed_hosts.get(task.task_id, ())))
        try:
            task.handle = self.backend.launch_task(spec)
        except Exception as e:  # noqa: BLE001 — e.g. SliceProvisionError
            # An unlaunchable gang is an INFRA_TRANSIENT session
            # failure (subject to the normal retry budget), not a
            # coordinator crash — the analogue of an unserviceable
            # container request.
            log.error("launch of %s failed: %s", task.task_id, e)
            self._end_task_span(task.task_id, error=str(e))
            self.session.fail(f"launch of {task.task_id} failed: {e}",
                              FailureDomain.INFRA_TRANSIENT)
            return False
        # Each gang launch restarts the registration-timeout clock; the
        # timeout gates on launched-but-unregistered tasks (scoped like
        # the barrier), so a long-running earlier DAG stage can't trip it.
        self._schedule_start = time.monotonic()
        task.status = TaskStatus.SCHEDULED
        self.events.emit(Event(EventType.TASK_STARTED, {
            "task": task.task_id, "session_id": self.session.session_id}))
        return True

    # ------------------------------------------------------------------
    # RPC-surface behaviour
    # ------------------------------------------------------------------
    def _check_membership(self, task_id: str, mgen,
                          for_register: bool = False) -> None:
        """Topology fence (coordinator/elastic.py): reject frames from a
        pre-resize topology. A registration for a task the matrix no
        longer holds — or holds only as a terminal corpse being replaced
        — is a zombie member of a world that no longer exists."""
        el = self.elastic
        if el is None or task_id.partition(":")[0] != el.job:
            return
        t = self.session.get_task(task_id)
        known = t is not None and not (for_register and t.status.terminal)
        reason = el.fences_frame(known, mgen)
        if reason is not None:
            raise FencedError(f"task {task_id}: {reason}")

    def register_worker_spec(self, task_id: str, host: str, port: int,
                             session_id: int = -1,
                             mgen: int = -1) -> Optional[dict]:
        """Gang barrier: record the spec, return the full cluster spec only
        once ALL tasks registered (reference ApplicationMaster.java:841-889).
        Serves initial registration AND post-recovery re-registration —
        the latter is the same call with the executor's existing
        task_id/host/port, fenced by session epoch — AND a drained
        survivor's PARK during an elastic resize (same call again, now
        carrying the new membership generation)."""
        self._check_epoch(task_id, session_id)
        self._check_membership(task_id, mgen, for_register=True)
        ok = self.session.register_worker(task_id, host, port)
        if ok and self.elastic is not None \
                and self.elastic.ack_registration(task_id, mgen):
            log.info("resize: %s parked under membership generation %s "
                     "(%d still draining)", task_id, mgen,
                     len(self.elastic.op.awaiting)
                     if self.elastic.op else 0)
        if ok:
            if task_id not in self._task_spans and self.tracer.enabled:
                # Post-recovery re-adoption: the original lifecycle span
                # died unclosed with the previous coordinator; open a
                # fresh one in the SAME trace so the task's second life
                # is visible on the timeline.
                self._task_spans[task_id] = self.tracer.start_span(
                    "task.lifecycle", parent=self._epoch_span,
                    task=task_id, attrs={"re_registered": True})
            # Write-ahead: the registration must be on disk before the
            # executor can observe it succeeded (a crash after the reply
            # but before the append would resurrect an unregistered task
            # whose executor believes it is registered).
            self.journal.register(task_id, host, int(port),
                                  self.session.session_id)
            with self._hb_lock:
                self._last_hb[task_id] = time.monotonic()
                steps_hint = self._recovered_steps.pop(task_id, None)
            # Progress tracking starts at registration; a post-recovery
            # re-registration seeds the journalled step counter so the
            # task comes back ARMED with a fresh deadline.
            self.progress.track(
                task_id, task_id.partition(":")[0],
                steps_hint=steps_hint)
            self._maybe_test_worker_termination(task_id)
        el = self.elastic
        if el is not None and el.resizing and el.op is not None \
                and el.op.phase == DRAIN:
            # The barrier stays CLOSED while the drain runs: lost tasks
            # keep their registered flag from their first life, so the
            # raw spec would otherwise open with the OLD topology and a
            # parked survivor would relaunch at the stale world size.
            return None
        spec = self.session.get_cluster_spec()
        if spec is not None and el is not None:
            # Elastic metadata rides the spec under a reserved key the
            # executor pops before the runtimes see it: the current
            # membership generation (survivors adopt it) and the member
            # indices (dense-rank mapping for sparse post-shrink gangs).
            spec["__elastic__"] = {
                "mgen": el.mgen,
                "members": {el.job: self.session.members(el.job)}}
        return spec

    def _maybe_test_worker_termination(self, task_id: str) -> None:
        """TEST_WORKER_TERMINATION hook: once the chief registers, kill one
        task of the configured jobtype (reference :1224-1235)."""
        target_type = os.environ.get(constants.TEST_WORKER_TERMINATION, "")
        if not target_type or self._worker_termination_done:
            return
        job, _, idx = task_id.partition(":")
        if not self.session.is_chief(job, int(idx)):
            return
        for t in self.session.all_tasks():
            if t.job_name == target_type and t.handle is not None:
                log.warning("TEST hook: terminating %s", t.task_id)
                self.backend.kill_task(t.handle, grace_s=0.0)
                self._worker_termination_done = True
                return

    def register_tensorboard_url(self, task_id: str, url: str,
                                 session_id: int = -1) -> bool:
        # Epoch fence (tonylint fence-coverage): a chief surviving from a
        # pre-reset session must not overwrite the NEW epoch's TB URL
        # with its dead server's address. session_id < 0 = pre-fence
        # caller, compat-accepted like every other fenced surface.
        self._check_epoch(task_id, session_id)
        t = self.session.get_task(task_id)
        if t is None:
            return False
        t.tb_url = url
        self.tb_url = url
        return True

    def register_execution_result(self, task_id: str, exit_code: int,
                                  session_id: int = -1,
                                  diagnostics: Optional[dict] = None) -> int:
        """Executor self-report; unregisters from the liveness monitor so a
        completed task can't be deemed dead (reference design note
        ``ApplicationMaster.java:891-919``). ``diagnostics`` is the
        executor's postmortem extract for a failed user process (the
        traceback from its own log tail, the decoded exit signal) —
        captured at the source, where the log is ALWAYS local, instead
        of hoping the coordinator can reach the file."""
        self._check_epoch(task_id, session_id)
        with self._hb_lock:
            if isinstance(diagnostics, dict) and diagnostics:
                self._task_diag[task_id] = diagnostics
            self._last_hb.pop(task_id, None)
        self.progress.forget(task_id)
        self._process_completion(task_id, exit_code)
        return 0

    def heartbeat(self, task_id: str, session_id: int = -1,
                  progress: Optional[dict] = None, mgen: int = -1):
        """Liveness refresh + progress-beacon intake. The return value
        doubles as the coordinator→executor directive channel: normally
        True (wire-compatible with pre-progress executors), or a dict
        carrying ``{"dump": True}`` exactly once after a hang verdict —
        the executor then signals the user process group so its
        pre-registered faulthandler dumps all-thread stacks — and/or
        ``{"resize": {...}}`` while an elastic drain runs (re-sent every
        beat; the executor dedups on the membership generation)."""
        self._check_epoch(task_id, session_id)
        self._check_membership(task_id, mgen)
        with self._hb_lock:
            if task_id in self._last_hb:
                self._last_hb[task_id] = time.monotonic()
        # The beacon doubles as the live-metrics feed: utilization gauges
        # and the executor's client-latency histogram ride the same dict
        # the liveness tracker reads steps from. The fold runs inline on
        # the beat path — its cost is booked to the beacon_fold tick
        # phase (and subtracted from rpc_serve), so a width problem here
        # indicts as HEARTBEAT_BOUND instead of hiding.
        with self.coordphases.phase("beacon_fold"):
            self._observe_beacon(task_id, progress)
        if self.progress.observe(task_id, progress):
            self._maybe_journal_progress(task_id)
        resp: Dict[str, object] = {}
        if self.progress.should_dump(task_id):
            resp["dump"] = True
        if self.elastic is not None:
            directive = self.elastic.directive_for(task_id)
            if directive is not None:
                resp["resize"] = directive
        profile = self._profile_directive(task_id)
        if profile is not None:
            resp["profile"] = profile
        if resp:
            return {"ok": True, **resp}
        return True

    def _maybe_journal_progress(self, task_id: str) -> None:
        """Journal an advanced step counter, throttled per task — the
        recovery seed must not turn the fsync'd journal into a per-step
        hot path."""
        now = time.monotonic()
        with self._hb_lock:
            last = self._progress_journal_t.get(task_id, 0.0)
            if now - last < liveness.PROGRESS_JOURNAL_MIN_INTERVAL_S:
                return
            self._progress_journal_t[task_id] = now
        snap = self.progress.snapshot(task_id) or {}
        steps = snap.get("steps")
        if steps is not None:
            self.journal.progress(task_id, float(steps),
                                  self.session.session_id)

    def _retry_available(self, domain: Optional[FailureDomain]) -> bool:
        """Would the run loop retry a failure of this domain right now?
        (Pure read — the loop consumes via _consume_retry.)"""
        infra_left = self._infra_retries_used < self._retries_total
        if domain == FailureDomain.USER_ERROR:
            # Terminal on first occurrence: retrying a deterministic user
            # crash burns epochs for nothing — unless the operator opted
            # into reference-compat undiscriminating retry.
            return self._retry_user_errors and infra_left
        if domain == FailureDomain.PREEMPTION:
            # Free budget first; once exhausted, preemptions degrade to
            # drawing on the transient budget rather than failing a job
            # that still has retries to give.
            return (self._preempt_retries_used
                    < self._preempt_retries_total) or infra_left
        return infra_left

    def _consume_retry(self, domain: Optional[FailureDomain]) -> None:
        if domain == FailureDomain.PREEMPTION and \
                self._preempt_retries_used < self._preempt_retries_total:
            self._preempt_retries_used += 1
            return
        self._infra_retries_used += 1

    def application_report(self) -> dict:
        status = (self.final_status if self.final_status != SessionStatus.RUNNING
                  else self.session.status)
        retries_left = max(0, self._retries_total - self._infra_retries_used)
        preempt_left = max(0, self._preempt_retries_total
                           - self._preempt_retries_used)
        domain = self.session.failure_domain
        if (self.final_status == SessionStatus.RUNNING
                and status in (SessionStatus.FAILED, SessionStatus.KILLED)
                and self._retry_available(domain)
                and not self._stop_requested.is_set()):
            # Whole-job retry window: the current epoch failed but the
            # failed DOMAIN still has budget, so the next report may well
            # be RUNNING again. A client that treats any terminal status
            # as final (ours does, like ``TonyClient.java:838-892`` gates
            # on the YARN *application* status, never transient session
            # state) must not observe the transient FAILED here. A
            # USER_ERROR with retry-user-errors off is genuinely final
            # and reports FAILED immediately — no wasted retry epochs.
            status = SessionStatus.RUNNING
        if self._stop_requested.is_set() and status == SessionStatus.FAILED:
            # Kill teardown window: session.fail(stop_reason) lands before
            # run()'s finally block remaps the final status, and killing
            # the gang can take seconds — a poll here must already read
            # KILLED, not the transient FAILED (same YARN semantics as the
            # finally-block mapping).
            status = SessionStatus.KILLED
        tasks = []
        with self._hb_lock:
            hb = dict(self._last_hb)
        hb_now = time.monotonic()
        for t in self.session.all_tasks():
            info = t.to_info()
            # Live progress state for the status surfaces (CLI `status`,
            # portal): steps, stall age, rate, and the hang/straggler
            # verdicts — absent for terminal/untracked tasks.
            snap = self.progress.snapshot(t.task_id)
            if snap:
                info["progress"] = snap
            # Heartbeat age, from the same map the liveness monitor
            # expires on — the CLI status column (absent once a task is
            # terminal and unregistered from the monitor).
            last = hb.get(t.task_id)
            if last is not None:
                info["last_heartbeat_age_s"] = round(hb_now - last, 3)
            tasks.append(info)
        report = {
            "app_id": self.app_id,
            "status": status.value,
            "failure_reason": self.session.failure_reason or self._stop_reason,
            "failure_domain": domain.value if domain else "",
            "session_id": self.session.session_id,
            "attempt": self._attempt,
            "generation": self.generation,
            "recovered": self._recover_state is not None,
            "retries_left": retries_left,
            "preemption_retries_left": preempt_left,
            "tb_url": self.tb_url,
            "gang_size": {name: job.instances
                          for name, job in self.session.jobs.items()},
            "tasks": tasks,
        }
        if self.elastic is not None:
            report["elastic"] = self.elastic.snapshot()
        return report

    def request_stop(self, reason: str) -> None:
        self._stop_reason = reason
        self._stop_requested.set()

    # ------------------------------------------------------------------
    # Completion plumbing
    # ------------------------------------------------------------------
    def _record_failed_host(self, task_id: str,
                            domain: Optional[FailureDomain]) -> None:
        """Exclude-on-retry bookkeeping: remember which host an INFRA
        failure happened on, BEFORE the backend forgets the task. The
        next launch of this task id carries the list in
        TaskLaunchSpec.exclude_hosts. USER_ERROR records nothing —
        blacklisting hardware for a code bug just shrinks the pool."""
        if domain is None or domain == FailureDomain.USER_ERROR:
            return
        host = self.backend.host_of(task_id)
        if not host:
            return
        hosts = self._failed_hosts.setdefault(task_id, [])
        if host not in hosts:
            hosts.append(host)

    def _process_completion(self, task_id: str, exit_code: int) -> None:
        """Reference ``processFinishedContainer`` :1187-1220: apply failure
        policy, notify scheduler, emit TASK_FINISHED with last metrics."""
        delay = float(os.environ.get(constants.TEST_COMPLETION_DELAY, "0") or 0)
        if delay:
            time.sleep(delay)
        t = self.session.get_task(task_id)
        if t is None or t.status.terminal:
            return
        self.progress.forget(task_id)
        domain_hint = self.backend.completion_domain(task_id)
        if exit_code != 0 and self._absorb_task_loss(
                t, exit_code, domain_hint,
                reason=f"exited {exit_code} ({describe_exit(exit_code)})"):
            # Elastic absorption: the loss became a shrink (or folded
            # into the in-flight resize) — the session failure policy
            # never sees it.
            return
        self.session.on_task_completed(task_id, exit_code,
                                       domain_hint=domain_hint)
        if exit_code != 0:
            self._record_failed_host(task_id, t.failure_domain)
        self._end_task_span(task_id, exit_code=exit_code,
                            status=t.status.value)
        self.journal.task(
            task_id, t.status.value, self.session.session_id,
            exit_code=exit_code,
            domain=t.failure_domain.value if t.failure_domain else "")
        logs = self.backend.task_log_paths(task_id)
        payload = {
            "task": task_id, "exit_code": exit_code,
            "status": t.status.value,
            "exit_detail": describe_exit(exit_code),
            "failure_domain": (t.failure_domain.value
                               if t.failure_domain else ""),
            "metrics": self._task_metrics(task_id),
            "logs": list(logs) if logs else [],
            "session_id": self.session.session_id}
        with self._hb_lock:
            diag = self._task_diag.get(task_id) if exit_code != 0 else None
        if diag:
            # Executor-extracted postmortem: the user traceback rides the
            # event stream so diagnosis works even after task dirs purge.
            if diag.get("traceback"):
                payload["traceback"] = str(diag["traceback"])[:8192]
            if diag.get("exit_detail"):
                payload["exit_detail"] = str(diag["exit_detail"])
        self.events.emit(Event(EventType.TASK_FINISHED, payload))
        if self.scheduler is not None and t.tracked:
            done = [self.session.get_task(f"{t.job_name}:{i}")
                    for i in self.session.members(t.job_name)]
            if all(x is not None and x.status == TaskStatus.SUCCEEDED
                   for x in done):
                self.journal.job_completed(t.job_name,
                                           self.session.session_id)
                self.scheduler.register_job_completed(t.job_name)
            elif t.status in (TaskStatus.FAILED, TaskStatus.KILLED) and \
                    not self.scheduler.dependency_check_passed(t.job_name):
                # A failed jobtype with unlaunched dependents can never let
                # the DAG progress — fail now instead of waiting on tasks
                # that will never be launched (reference monitor() DAG check,
                # ``ApplicationMaster.java:581-650``).
                self.session.fail(
                    f"jobtype {t.job_name} failed with unlaunched dependent "
                    f"jobtypes; DAG cannot make progress (task {task_id} "
                    f"exit {exit_code})", t.failure_domain)

    # ------------------------------------------------------------------
    # Elastic resizing (coordinator/elastic.py)
    # ------------------------------------------------------------------
    def _absorb_task_loss(self, t: Task, exit_code: int,
                          domain_hint: Optional[str], reason: str,
                          hb_age_s: Optional[float] = None,
                          kill: bool = False) -> bool:
        """Try to absorb a dying elastic-gang member as a shrink instead
        of an epoch failure. Terminalizes the task (WITHOUT the session
        failure policy), emits its TASK_FINISHED with ``resize: true``
        (the diagnosis engine must not blame a deliberate resize), and
        starts — or folds into — the resize op. Returns False when the
        policy says this loss is a real failure (chief, USER_ERROR,
        below min-tasks, elasticity off): the caller then takes the
        ordinary failure path."""
        from tony_tpu.coordinator.session import classify_exit

        el = self.elastic
        if el is None:
            return False
        domain = classify_exit(exit_code, domain_hint) \
            or FailureDomain.INFRA_TRANSIENT
        # A migrating member that already ACKED its park self-exits (it
        # cannot follow the gang to the destination slice) — that exit is
        # as expected as a released task's, and must never fold the move
        # into a shrink.
        released = el.is_released(t.task_id) or \
            el.is_parked_for_migration(t.task_id)
        if not released and not el.may_absorb(t, domain.value,
                                              self.session):
            return False
        task_id = t.task_id
        t.status = (TaskStatus.KILLED
                    if exit_code == constants.EXIT_KILLED
                    else TaskStatus.FAILED)
        t.exit_code = exit_code
        t.failure_domain = domain
        self._record_failed_host(task_id, domain)
        with self._hb_lock:
            self._last_hb.pop(task_id, None)
        self.progress.forget(task_id)
        self._end_task_span(task_id, exit_code=exit_code,
                            resized_out=True)
        self.journal.task(task_id, t.status.value,
                          self.session.session_id, exit_code=exit_code,
                          domain=domain.value)
        if kill and t.handle is not None:
            # Heartbeat-expiry shape: the EXECUTOR vanished but its user
            # tree may live on — reap it off the monitor loop (kill_task
            # blocks through its grace window).
            threading.Thread(
                target=self.backend.kill_task, args=(t.handle,),
                kwargs={"grace_s": 0.0}, daemon=True,
                name=f"resize-reap-{task_id}").start()
        logs = self.backend.task_log_paths(task_id)
        payload = {
            "task": task_id, "exit_code": exit_code,
            "status": t.status.value,
            "exit_detail": describe_exit(exit_code),
            "failure_domain": domain.value,
            "reason": reason,
            "resize": True,
            "metrics": self._task_metrics(task_id),
            "logs": list(logs) if logs else [],
            "session_id": self.session.session_id}
        if hb_age_s is not None:
            payload["last_heartbeat_age_s"] = round(hb_age_s, 3)
        self.events.emit(Event(EventType.TASK_FINISHED, payload))
        if released:
            el.note_task_gone(task_id)
            return True
        if el.resizing and el.op is not None:
            # Second loss during the drain: supersede the op with the
            # smaller membership (mgen bumps again; parked survivors
            # adopt it through the directive channel).
            op = el.op
            members = [m for m in op.members if m != t.index]
            if op.migrate:
                # A host died mid-migration: the move is abandoned and
                # the loss folds into an ordinary elastic shrink — a
                # failed migration is never worse than a host loss. The
                # superseded record closes the journaled migrate start
                # write-ahead of the resize that replaces it.
                self.journal.migrate(el.job, op.mgen, op.members,
                                     "superseded", op.target,
                                     self.session.session_id,
                                     reason=f"lost {task_id} mid-"
                                            f"migration: {reason}")
                log.warning("migrate: member %s lost mid-drain — move to "
                            "%r abandoned, folding into a shrink to %d "
                            "member(s)", task_id, op.target, len(members))
            else:
                log.warning("resize: member %s lost mid-drain — "
                            "superseding to %d member(s)", task_id,
                            len(members))
        else:
            members = [x.index for x in self.session.all_tasks()
                       if x.job_name == el.job and not x.status.terminal]
        self._start_resize(members,
                           f"absorbed loss of {task_id}: {reason}")
        return True

    def _start_resize(self, members, reason: str) -> None:
        """Begin (or supersede) a resize op: journal the start record
        write-ahead, emit the timeline event, and let the drain
        directives ride the next heartbeats."""
        el = self.elastic
        live = [t for t in self.session.all_tasks()
                if t.job_name == el.job and not t.status.terminal]
        op = el.begin(sorted(members), live, reason)
        self.journal.resize(el.job, op.mgen, op.members, "start",
                            self.session.session_id, reason=reason)
        self.events.emit(Event(EventType.GANG_RESIZED, {
            "job": el.job, "phase": "started", "mgen": op.mgen,
            "members": list(op.members), "from": op.size_before,
            "to": len(op.members), "reason": reason,
            "session_id": self.session.session_id}))
        log.warning("resize: %s -> %d member(s) under membership "
                    "generation %d (%s); draining %d, releasing %d",
                    el.job, len(op.members), op.mgen, reason,
                    len(op.awaiting), len(op.release))

    def _start_migrate(self, members, target: str, reason: str,
                       mgen: Optional[int] = None,
                       resumed: bool = False) -> None:
        """Begin a live migration (coordinator/migrate.py): journal the
        REC_MIGRATE start write-ahead, emit the timeline event, and let
        the whole-gang drain directives ride the next heartbeats — every
        member parks (its user process makes one final durable save via
        the SIGTERM handler), then _apply_migrate moves the topology."""
        el = self.elastic
        live = [t for t in self.session.all_tasks()
                if t.job_name == el.job and not t.status.terminal]
        op = el.begin(sorted(members), live, reason, mgen=mgen,
                      target=target, migrate=True)
        self.journal.migrate(el.job, op.mgen, op.members, "start",
                             target, self.session.session_id,
                             reason=reason)
        job_spec = self.session.jobs.get(el.job)
        source = str(job_spec.node_pool or "") if job_spec else ""
        payload = {"job": el.job, "phase": "started", "mgen": op.mgen,
                   "members": list(op.members), "source": source,
                   "target": target, "reason": reason,
                   "session_id": self.session.session_id}
        if resumed:
            payload["resumed"] = True
        self.events.emit(Event(EventType.GANG_MIGRATED, payload))
        log.warning("migrate: %s (%d member(s)) %r -> %r under membership "
                    "generation %d (%s); draining the whole gang",
                    el.job, len(op.members), source, target, op.mgen,
                    reason)

    def _elastic_tick(self) -> None:
        """Advance the resize state machine (monitor-loop cadence):
        drain done → apply the re-mesh; barrier reopened → finish; the
        whole op is bounded by tony.elastic.barrier-timeout-s."""
        el = self.elastic
        if el is None or not el.resizing:
            return
        if el.timed_out():
            op = el.abandon()
            what = (f"live migration to {op.target!r}" if op.migrate
                    else f"elastic resize to {len(op.members)} member(s)")
            self.session.fail(
                f"{what} did not "
                f"complete within {el.barrier_timeout_s}s "
                f"(phase {op.phase}, still draining "
                f"{sorted(op.awaiting)})",
                FailureDomain.INFRA_TRANSIENT)
            return
        op = el.op
        if op.phase == DRAIN and el.drain_complete:
            if op.migrate:
                self._apply_migrate()
            else:
                self._apply_remesh()
        elif op.phase == BARRIER and self.session.all_registered():
            done = el.finish()
            duration_s = round(time.monotonic() - done.started, 3)
            if done.migrate:
                self.events.emit(Event(EventType.GANG_MIGRATED, {
                    "job": el.job, "phase": "completed",
                    "mgen": done.mgen, "members": list(done.members),
                    "target": done.target, "reason": done.reason,
                    "duration_s": duration_s,
                    "session_id": self.session.session_id}))
                log.warning("migrate: %s live on %r at %d member(s) "
                            "(mgen %d) in %.1fs — training continues in "
                            "the SAME epoch, zero steps lost", el.job,
                            done.target, len(done.members), done.mgen,
                            duration_s)
                return
            self.events.emit(Event(EventType.GANG_RESIZED, {
                "job": el.job, "phase": "completed", "mgen": done.mgen,
                "members": list(done.members), "from": done.size_before,
                "to": len(done.members), "reason": done.reason,
                "duration_s": duration_s,
                "session_id": self.session.session_id}))
            log.warning("resize: %s re-meshed at %d member(s) "
                        "(mgen %d) in %.1fs — training continues in the "
                        "SAME epoch", el.job, len(done.members),
                        done.mgen, duration_s)

    def _apply_remesh(self) -> None:
        """All survivors parked (or dead): rebuild the member set at the
        new cardinality, journal it write-ahead, launch replacements /
        grow-back tasks, and reopen the barrier."""
        el = self.elastic
        op = el.op
        try:
            faults.check("resize.remesh")
        except faults.InjectedFault as e:
            el.abandon()
            self.session.fail(f"elastic re-mesh failed: {e}",
                              FailureDomain.INFRA_TRANSIENT)
            return
        member_set = set(op.members)
        for t in self.session.all_tasks():
            if t.job_name != el.job or t.index in member_set:
                continue
            # Removed from the topology: close its trace/liveness state;
            # a released executor that ignored its directive is reaped
            # off-loop (its straggling frames are fenced as non-members).
            self._end_task_span(t.task_id, resized_out=True)
            with self._hb_lock:
                self._last_hb.pop(t.task_id, None)
            self.progress.forget(t.task_id)
            el.note_task_gone(t.task_id)
            if t.handle is not None and not t.status.terminal:
                threading.Thread(
                    target=self.backend.kill_task, args=(t.handle,),
                    kwargs={"grace_s": float(el.drain_grace_s)},
                    daemon=True, name=f"resize-release-{t.task_id}"
                ).start()
        fresh = self.session.resize_job(el.job, op.members)
        self.journal.resize(el.job, op.mgen, op.members, "applied",
                            self.session.session_id, reason=op.reason)
        for t in fresh:
            if not self._launch_task(t):
                el.abandon()
                return             # session already failed INFRA_TRANSIENT
        try:
            faults.check("resize.barrier")
        except faults.InjectedFault as e:
            el.abandon()
            self.session.fail(f"elastic resize barrier failed: {e}",
                              FailureDomain.INFRA_TRANSIENT)
            return
        self._schedule_start = time.monotonic()
        el.mark_remeshed()
        log.warning("resize: topology applied — %s members %s (mgen %d, "
                    "%d fresh launch(es)); waiting at the barrier",
                    el.job, op.members, op.mgen, len(fresh))

    def _apply_migrate(self) -> None:
        """The whole gang is parked (every member's final save durable):
        kill the source-slice executors, re-pin the job's node pool to
        the target, journal the applied record write-ahead, and relaunch
        the SAME member indices on the destination — warm-pool adoption
        or cold spawn, the backend's ordinary launch ladder. Any failure
        degrades to the INFRA_TRANSIENT retry machinery."""
        el = self.elastic
        op = el.op
        try:
            faults.check("migrate.snapshot")
        except faults.InjectedFault as e:
            el.abandon()
            self.session.fail(f"migration snapshot seal failed: {e}",
                              FailureDomain.INFRA_TRANSIENT)
            return
        # Source executors die BEFORE their indices exist again: a
        # straggling frame from the old slice then meets a closed drain
        # barrier or a non-member fence, never the destination gang.
        kills: List[threading.Thread] = []
        for t in self.session.all_tasks():
            if t.job_name != el.job or t.status.terminal:
                continue
            self._end_task_span(t.task_id, resized_out=True)
            with self._hb_lock:
                self._last_hb.pop(t.task_id, None)
            self.progress.forget(t.task_id)
            el.note_task_gone(t.task_id)
            self.session.mark_killed(t.task_id)
            if t.handle is not None:
                th = threading.Thread(
                    target=self.backend.kill_task, args=(t.handle,),
                    kwargs={"grace_s": float(el.drain_grace_s)},
                    daemon=True, name=f"migrate-release-{t.task_id}")
                th.start()
                kills.append(th)
        for th in kills:
            th.join(timeout=float(el.drain_grace_s) + 15.0)
        job_spec = self.session.jobs.get(el.job)
        source = str(job_spec.node_pool or "") if job_spec else ""
        if job_spec is not None:
            job_spec.node_pool = op.target
        fresh = self.session.resize_job(el.job, op.members)
        self.journal.migrate(el.job, op.mgen, op.members, "applied",
                             op.target, self.session.session_id,
                             reason=op.reason)
        try:
            faults.check("migrate.adopt")
        except faults.InjectedFault as e:
            el.abandon()
            self.session.fail(
                f"migration destination adoption failed: {e}",
                FailureDomain.INFRA_TRANSIENT)
            return
        for t in fresh:
            if not self._launch_task(t):
                el.abandon()
                return             # session already failed INFRA_TRANSIENT
        self._schedule_start = time.monotonic()
        el.mark_remeshed()
        log.warning("migrate: topology moved %r -> %r — %s members %s "
                    "(mgen %d, %d destination launch(es)); waiting at "
                    "the barrier", source, op.target, el.job, op.members,
                    op.mgen, len(fresh))

    def resize_application(self, size: int, job: str = "") -> dict:
        """Operator-initiated resize (`tony-tpu resize <app> <n>`):
        validated by policy, then the same drain→remesh→barrier path a
        host-loss absorption takes."""
        el = self.elastic
        if el is None:
            return {"ok": False,
                    "message": "elasticity is disabled for this job "
                               "(set tony.elastic.enabled=true)"}
        if job and job != el.job:
            return {"ok": False,
                    "message": f"jobtype {job!r} is not the elastic "
                               f"jobtype ({el.job})"}
        try:
            members = el.plan_explicit(int(size), self.session)
        except ResizeRefused as e:
            if el.at_size(int(size), self.session):
                # Idempotent no-op: the gang is already exactly there.
                # At-least-once delivery (a lost response, a fleet
                # daemon that crashed between the resize RPC and its
                # journal record) retries the same resize — the second
                # delivery must read as success or the caller livelocks
                # re-sending a resize that can never "succeed".
                return {"ok": True, "noop": True, "mgen": el.mgen,
                        "message": f"gang already has {size} member(s) "
                                   f"— no-op"}
            return {"ok": False, "message": str(e)}
        self._start_resize(members, f"operator resize to {size}")
        return {"ok": True, "mgen": el.mgen, "members": members,
                "message": f"resizing {el.job} to {len(members)} "
                           f"member(s) (membership generation {el.mgen})"}

    def migrate_application(self, target: str, job: str = "",
                            reason: str = "") -> dict:
        """Live migration (`tony-tpu migrate <app> <target>`): validated
        by policy (coordinator/migrate.py), then DRAIN the whole gang →
        final durable saves → relaunch on the target slice → barrier —
        the same machinery as a resize, pointed at a different slice."""
        el = self.elastic
        if el is None:
            return {"ok": False,
                    "message": "migration rides the elastic drain "
                               "machinery — set tony.elastic.enabled"
                               "=true"}
        try:
            plan = plan_migration(el, self.session, target, job=job,
                                  reason=reason)
        except MigrateRefused as e:
            return {"ok": False, "message": str(e)}
        self._start_migrate(plan.members, plan.target, plan.reason)
        return {"ok": True, "mgen": el.mgen,
                "members": list(plan.members), "source": plan.source,
                "target": plan.target,
                "message": f"migrating {el.job} ({len(plan.members)} "
                           f"member(s)) to {plan.target} (membership "
                           f"generation {el.mgen})"}

    def _check_heartbeats(self) -> None:
        """Liveness monitor (reference AbstractLivelinessMonitor usage
        :188-208; expiry → ``onTaskDeemedDead`` :1178-1185)."""
        now = time.monotonic()
        expired: List[tuple] = []
        with self._hb_lock:
            for task_id, last in list(self._last_hb.items()):
                if now - last > self._hb_expiry_s:
                    expired.append((task_id, now - last))
                    del self._last_hb[task_id]
        for task_id, hb_age_s in expired:
            t = self.session.get_task(task_id)
            if t is None or t.status.terminal:
                continue
            log.error("task %s missed heartbeats for %.1fs — deemed dead",
                      task_id, self._hb_expiry_s)
            if self._absorb_task_loss(
                    t, constants.EXIT_KILLED,
                    FailureDomain.INFRA_TRANSIENT.value,
                    reason=f"task {task_id} deemed dead (missed "
                           f"heartbeats for {self._hb_expiry_s:.1f}s)",
                    hb_age_s=hb_age_s, kill=True):
                # Host loss absorbed: the gang shrinks and continues —
                # no epoch failure, no retry burned.
                continue
            # Postmortem context BEFORE the tracker forgets the task: the
            # event must let an operator tell "executor vanished" (stale
            # heartbeat age, any progress state) from "executor alive,
            # user hung" (the TASK_HUNG path, which never comes through
            # here).
            progress_snap = self.progress.snapshot(task_id)
            self.progress.forget(task_id)
            self._end_task_span(task_id, deemed_dead=True,
                                heartbeat_age_s=round(hb_age_s, 3))
            if t.handle is not None:
                self.backend.kill_task(t.handle, grace_s=0.0)
            # Fail first so the recorded reason is the liveness expiry, not
            # the generic chief/worker-exit policy triggered by the kill.
            # A wedged/vanished executor is transient infra: the retry
            # epoch gets a fresh process on (possibly) fresh hardware.
            self.session.fail(f"task {task_id} deemed dead "
                              f"(missed heartbeats)",
                              FailureDomain.INFRA_TRANSIENT)
            self.session.on_task_completed(
                task_id, constants.EXIT_KILLED,
                domain_hint=FailureDomain.INFRA_TRANSIENT.value)
            self._record_failed_host(task_id,
                                     FailureDomain.INFRA_TRANSIENT)
            self.journal.task(
                task_id, t.status.value, self.session.session_id,
                exit_code=constants.EXIT_KILLED,
                domain=FailureDomain.INFRA_TRANSIENT.value)
            # The kill's eventual backend completion is a no-op (task
            # already terminal), so THIS is the only place the task's
            # TASK_FINISHED — with its liveness-expiry domain — can be
            # emitted.
            logs = self.backend.task_log_paths(task_id)
            self.events.emit(Event(EventType.TASK_FINISHED, {
                "task": task_id, "exit_code": constants.EXIT_KILLED,
                "status": t.status.value,
                "exit_detail": describe_exit(constants.EXIT_KILLED),
                "failure_domain": FailureDomain.INFRA_TRANSIENT.value,
                "reason": f"task {task_id} deemed dead (missed "
                          f"heartbeats for {self._hb_expiry_s:.1f}s)",
                "last_heartbeat_age_s": round(hb_age_s, 3),
                "progress": progress_snap or {},
                "metrics": self._task_metrics(task_id),
                "logs": list(logs) if logs else [],
                "session_id": self.session.session_id}))

    def _check_progress(self) -> None:
        """Progress-based liveness pass (coordinator/liveness.py): act on
        the tracker's verdicts. Heartbeat expiry proves a DEAD executor;
        this proves a LIVE executor whose user process stopped doing
        work — hang (frozen step counter → diagnose → kill → retry) and
        straggler (rate below the gang median → event, optional
        restart)."""
        for action in self.progress.poll():
            t = self.session.get_task(action.task_id)
            if t is None or t.status.terminal:
                continue
            payload = dict(action.info)
            payload.update({"task": action.task_id,
                            "session_id": self.session.session_id})
            if action.kind == liveness.WARN_UNINSTRUMENTED:
                log.warning(
                    "task %s reported no step counter within the %ss "
                    "warmup — progress liveness degrades to "
                    "heartbeat-only for it (instrument the training "
                    "loop with tony_tpu.telemetry.step())",
                    action.task_id, action.info.get("warmup_s"))
                self.events.emit(Event(
                    EventType.TASK_PROGRESS_UNINSTRUMENTED, payload))
            elif action.kind == liveness.HUNG:
                log.error(
                    "task %s HUNG: heartbeats alive but step counter "
                    "frozen at %s for %.1fs (deadline %ss) — requesting "
                    "a stack dump, kill follows in %ss",
                    action.task_id, action.info.get("steps"),
                    action.info.get("stalled_s", 0.0),
                    action.info.get("timeout_s"),
                    self.progress.dump_grace_s)
                self.events.emit(Event(EventType.TASK_HUNG, payload))
            elif action.kind == liveness.STRAGGLER:
                log.warning(
                    "task %s STRAGGLING: %.3f steps/s vs gang median "
                    "%.3f (threshold %.0f%%) sustained %ss",
                    action.task_id,
                    action.info.get("rate_steps_per_s", 0.0),
                    action.info.get("median_steps_per_s", 0.0),
                    100 * float(action.info.get("fraction", 0.0)),
                    action.info.get("window_s"))
                self.events.emit(Event(EventType.TASK_STRAGGLER, payload))
            elif action.kind == liveness.HANG_KILL:
                reason = (f"task {action.task_id} hung: heartbeats alive "
                          f"but no step progress for "
                          f"{action.info.get('stalled_s', 0.0):.0f}s "
                          f"(progress deadline "
                          f"{action.info.get('timeout_s')}s)")
                # Elastic hang absorption (PR 8 carry-over): a hung
                # elastic member is drained out via resize like a host
                # loss — same epoch, no INFRA_TRANSIENT retry burned —
                # instead of failing the epoch. The absorb policy itself
                # (chief, min-tasks, elasticity off) decides; refusals
                # fall through to the ordinary hang-kill path.
                if self._absorb_task_loss(
                        t, constants.EXIT_KILLED,
                        FailureDomain.INFRA_TRANSIENT.value,
                        reason=reason, kill=True):
                    continue
                self._kill_unhealthy_task(
                    t, reason, action.info, capture_dump=True)
            elif action.kind == liveness.STRAGGLER_KILL:
                self._kill_unhealthy_task(
                    t, f"task {action.task_id} proactively restarted as "
                       f"a straggler: "
                       f"{action.info.get('rate_steps_per_s', 0.0):.3f} "
                       f"steps/s vs gang median "
                       f"{action.info.get('median_steps_per_s', 0.0):.3f}",
                    action.info, capture_dump=False)

    def _kill_unhealthy_task(self, t: Task, reason: str, info: dict,
                             capture_dump: bool) -> None:
        """Hang/straggler kill: TERM→grace→KILL the task and fail the
        epoch INFRA_TRANSIENT into the ordinary retry machinery — a wedge
        or skew is infra-shaped (fresh process, possibly fresh hardware,
        usually clears it), never a deterministic user crash. Mirrors the
        heartbeat-expiry kill, plus the captured diagnostics."""
        task_id = t.task_id
        hb_age_s = None
        with self._hb_lock:
            last = self._last_hb.pop(task_id, None)
            if last is not None:
                hb_age_s = time.monotonic() - last
        progress_snap = self.progress.snapshot(task_id)
        self.progress.forget(task_id)
        self._end_task_span(task_id, killed=reason[:200])
        dump_excerpt = self._stack_dump_excerpt(task_id) \
            if capture_dump else ""
        log.error("%s — killing into an INFRA_TRANSIENT retry", reason)
        # Verdict BEFORE the kill: kill_task blocks through its grace
        # window, and the dying executor reports its (TERM-shaped, 143)
        # exit over RPC inside that window — processed first, it would
        # re-label this deliberate restart as a chief PREEMPTION failure.
        # With the task already terminal, the late report is a no-op.
        self.session.fail(reason, FailureDomain.INFRA_TRANSIENT)
        self.session.on_task_completed(
            task_id, constants.EXIT_KILLED,
            domain_hint=FailureDomain.INFRA_TRANSIENT.value)
        self.journal.task(
            task_id, t.status.value, self.session.session_id,
            exit_code=constants.EXIT_KILLED,
            domain=FailureDomain.INFRA_TRANSIENT.value)
        if t.handle is not None:
            # A wedged user process rarely honours TERM, but the grace
            # window costs little and lets a merely-slow process flush
            # its save-on-TERM handlers before the KILL lands.
            self.backend.kill_task(
                t.handle,
                grace_s=min(self.conf.get_int(K.COORDINATOR_STOP_GRACE_S,
                                              15), 5))
        logs = self.backend.task_log_paths(task_id)
        payload = {
            "task": task_id, "exit_code": constants.EXIT_KILLED,
            "status": t.status.value,
            "exit_detail": describe_exit(constants.EXIT_KILLED),
            "failure_domain": FailureDomain.INFRA_TRANSIENT.value,
            "reason": reason,
            "progress": progress_snap or dict(info),
            "metrics": self._task_metrics(task_id),
            "logs": list(logs) if logs else [],
            "session_id": self.session.session_id}
        if hb_age_s is not None:
            payload["last_heartbeat_age_s"] = round(hb_age_s, 3)
        if dump_excerpt:
            payload["stack_dump_excerpt"] = dump_excerpt
        self.events.emit(Event(EventType.TASK_FINISHED, payload))

    def _stack_dump_excerpt(self, task_id: str,
                            max_bytes: int = 4096) -> str:
        """Best-effort: pull the faulthandler all-thread dump the executor
        triggered out of the task's stderr log, so the event stream holds
        the stacks even after task dirs are purged. Empty when the log is
        unreachable (remote host) or the dump never landed (user signal
        override, dump signal lost)."""
        from tony_tpu.utils import logs as logutil

        paths = self.backend.task_log_paths(task_id)
        for path in reversed(paths or ()):  # stderr is the usual home
            tail = logutil.tail_text(path, 64 * 1024)
            if tail is None:
                continue
            excerpt = logutil.extract_stack_dump(tail, max_bytes)
            if excerpt:
                return excerpt
        return ""

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def run(self) -> SessionStatus:
        """prepare → [start → monitor → reset?]* → stop
        (reference ``ApplicationMaster.run`` :312 + retry loop :337-371)."""
        self.rpc.start()
        self.events.start()
        recovered = self._recover_state is not None
        # Root coordinator span: parented under the client's submit span
        # (env trace context) on a fresh job; a recovery run is a new root
        # in the SAME trace — the outage reads as a gap between them.
        self._run_span = self.tracer.start_span(
            "coordinator.recover" if recovered else "coordinator.run",
            parent=os.environ.get(constants.TRACE_PARENT_ENV, "") or None,
            attrs={"app": self.app_id, "generation": self.generation})
        if not recovered:
            self.events.emit(Event(EventType.APPLICATION_INITED, {
                "app_id": self.app_id, "user": self.user,
                "conf": {k: v for k, v in self.conf.as_dict().items()
                         if not k.startswith("_")}}))
        self._final_conf_path = self.conf.freeze(
            os.path.join(self.job_dir, constants.FINAL_CONFIG_FILE))

        if os.environ.get(constants.TEST_COORDINATOR_CRASH) == "true":
            # Reference TEST_AM_CRASH aborts the AM after startup (:338-343).
            self.events.stop(history.final_name(
                self.app_id, self._started_ms, int(time.time() * 1000),
                self.user, "FAILED"))
            self.rpc.stop()
            raise CoordinatorCrash("TEST_COORDINATOR_CRASH requested")

        # On recovery the loop resumes AT the journaled epoch: the first
        # iteration re-adopts the surviving gang instead of launching one,
        # and any later retry epochs continue the same numbering.
        attempt = self._attempt
        first = True
        retry_domain: Optional[FailureDomain] = None
        try:
            local_cmd = str(self.conf.get(K.COORDINATOR_COMMAND, "") or "")
            single_node = not self.session.all_tasks()
            if local_cmd and not recovered and (
                    single_node or self.conf.get_bool(
                        K.APPLICATION_ENABLE_PREPROCESS)):
                # Preprocess / single-node path: run the command in the
                # coordinator (reference ``doPreprocessingJob`` :714-766 —
                # short-circuit the job if it fails). Not re-run on
                # recovery: a completed prepare stage's effects are on
                # disk, and re-running it mid-job is never safe to assume.
                code = self._do_local_job(local_cmd, register_tb=single_node)
                if code != 0:
                    self.session.fail(
                        f"coordinator-local job failed (exit {code})")
                    return self.final_status
                if single_node:
                    self.session.status = SessionStatus.SUCCEEDED
                    return self.final_status
            try:
                while True:
                    if first and recovered:
                        self._resume_session()
                    else:
                        self._start_session(attempt, retry_domain)
                    first = False
                    status = self._monitor()
                    self._close_epoch_spans(status)
                    if self.journal.dead is not None:
                        # An RPC-handler append (register/progress) hit
                        # the dead disk first: same terminal INFRA shape
                        # as the raise below, even if the monitor's own
                        # ticks kept succeeding in memory. fail_terminal
                        # on purpose — a finished epoch whose verdict
                        # can no longer be journaled must NOT read as
                        # SUCCEEDED (the history would claim a success
                        # the write-ahead journal never saw).
                        self.session.fail_terminal(
                            f"journal write failed: {self.journal.dead}",
                            FailureDomain.INFRA_TRANSIENT)
                        break
                    if status == SessionStatus.SUCCEEDED \
                            or self._stop_requested.is_set():
                        break
                    retry_domain = (self.session.failure_domain
                                    or FailureDomain.INFRA_TRANSIENT)
                    self.journal.verdict(
                        self.session.session_id, retry_domain.value,
                        self.session.failure_reason or "")
                    if not self._retry_available(retry_domain):
                        if retry_domain == FailureDomain.USER_ERROR \
                                and not self._retry_user_errors:
                            log.error(
                                "session %d failed with USER_ERROR (%s) "
                                "— terminal on first occurrence (set %s "
                                "to retry user errors anyway)", attempt,
                                self.session.failure_reason,
                                K.APPLICATION_RETRY_USER_ERRORS)
                        break
                    log.warning(
                        "session %d failed [%s] (%s); retrying "
                        "(transient budget %d/%d used, preemption %d/%d)",
                        attempt, retry_domain.value,
                        self.session.failure_reason,
                        self._infra_retries_used, self._retries_total,
                        self._preempt_retries_used,
                        self._preempt_retries_total)
                    self._reset_session()
                    attempt += 1
            except DurableWriteError as e:
                # The write-ahead journal died (ENOSPC/EIO) — whether
                # mid-monitor, on the retry path's verdict record, or in
                # a session reset. TERMINAL, domain INFRA: retrying
                # would schedule state transitions recovery can never
                # see, and the verdict/retry machinery itself journals.
                # Kill the gang with the full grace and stop — the
                # committed journal prefix stays replayable for
                # --recover.
                log.critical(
                    "journal write failed (%s) — failing the job "
                    "terminally [INFRA_TRANSIENT]", e)
                self.session.fail_terminal(
                    f"journal write failed: {e}",
                    FailureDomain.INFRA_TRANSIENT)
                self._kill_all_tasks(
                    self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15))
        finally:
            self.final_status = self.session.update_status()
            if self._stop_requested.is_set() and self.final_status in (
                    SessionStatus.RUNNING, SessionStatus.FAILED):
                # A requested stop reads as KILLED even when the teardown
                # itself made tasks exit nonzero first (killing the gang
                # races the chief-failure policy) — YARN semantics: a
                # user-killed app is KILLED, not FAILED.
                self.final_status = SessionStatus.KILLED
            try:
                self._stop()
            except DurableWriteError as e:
                # Teardown writes the journal too (terminal states,
                # close). A disk that dies HERE must not crash the
                # coordinator out of its own exit path: the committed
                # prefix is already replayable, the history record below
                # still lands (separate file), so scream and finish.
                log.critical("journal write failed during teardown "
                             "(%s); committed prefix intact", e)
        return self.final_status

    def _do_local_job(self, cmd: str, register_tb: bool) -> int:
        """Run a command in the coordinator process (single-node/preprocess
        mode, reference ``ApplicationMaster.doPreprocessingJob`` :714-766):
        TB port registered for single-node, HOME pinned to the job dir for
        notebook-style servers, exit code short-circuits the job."""
        from tony_tpu.executor.ports import ReservedPort
        from tony_tpu.utils import proc as procutil

        env = dict(os.environ)
        env.update({
            constants.APP_ID: self.app_id,
            constants.JOB_NAME: "coordinator",
            constants.TASK_INDEX: "0",
            "HOME": self.job_dir,
            "PREPROCESSING_JOB": "true",
        })
        if register_tb:
            tb = ReservedPort(reuse=False)
            import socket as _socket
            self.tb_url = f"http://{_socket.gethostname()}:{tb.port}"
            env[constants.TB_PORT] = str(tb.port)
            tb.release()
        for kv in self.conf.get_list(K.EXECUTION_ENV):
            if "=" in kv:
                k, v = kv.split("=", 1)
                env[k] = v
        self.events.emit(Event(EventType.TASK_STARTED, {
            "task": "coordinator:0", "session_id": 0}))
        # The command blocks this thread, but force_kill arrives on the RPC
        # thread as _stop_requested — a watcher delivers the TERM→grace→KILL
        # ladder to the child's process group so a killed notebook/preprocess
        # job cannot orphan its server (reference stops preprocessing with
        # the AM teardown, ApplicationMaster.java:714-766 + :694-711).
        child: List[object] = []
        done = threading.Event()

        def _stop_watcher() -> None:
            while not done.wait(0.2):
                if self._stop_requested.is_set():
                    if not child:
                        # Stop arrived before on_start registered the
                        # child — keep polling; returning here would leave
                        # the about-to-spawn process unkillable.
                        continue
                    procutil.kill_process_groups(
                        [child[0].pid],
                        grace_s=self.conf.get_int(
                            K.COORDINATOR_STOP_GRACE_S, 15))
                    return

        watcher = threading.Thread(target=_stop_watcher,
                                   name="local-job-stop-watcher", daemon=True)
        watcher.start()
        try:
            code = procutil.execute_shell(
                cmd, timeout_s=self.conf.get_int(
                    K.TASK_EXECUTOR_EXECUTION_TIMEOUT_S, 0), env=env,
                on_start=lambda p: child.append(p))
        finally:
            done.set()
        self.events.emit(Event(EventType.TASK_FINISHED, {
            "task": "coordinator:0", "exit_code": code,
            "status": "SUCCEEDED" if code == 0 else "FAILED",
            "metrics": {}, "logs": [], "session_id": 0}))
        return code

    def _start_session(self, attempt: int,
                       retried_domain: Optional[FailureDomain] = None
                       ) -> None:
        if attempt > 0:
            # Rebuild the task matrix under a new epoch (reference
            # ``reset`` :559-575 — sessionId++ and re-request everything).
            self.session = Session(self.conf, session_id=attempt)
            with self._hb_lock:
                self._last_hb.clear()
                # The old gang's per-task residue dies with the epoch:
                # journal throttles, postmortem extracts (a stale
                # traceback must not attach to the new gang's exits) and
                # phase attribution (fresh processes restart their
                # telemetry counters at 0).
                self._progress_journal_t.clear()
                self._task_diag.clear()
                self._phase_latest.clear()
            # Progress state belongs to the old gang; the new epoch's
            # tasks re-arm from scratch (fresh warmup, fresh deadlines).
            self.progress.reset()
            self._worker_termination_done = False
            if self.elastic is not None:
                # The retry epoch relaunches at the CONFIGURED size; the
                # old gang's membership (and any in-flight resize) died
                # with it. mgen stays monotonic — zombies stay fenced.
                self.elastic.reset_for_epoch()
        # Bump the attempt only after the fresh session is installed: a
        # concurrent application_report must never see (old FAILED session,
        # new attempt) — that combination un-masks the transient FAILED.
        self._attempt = attempt
        if attempt > 0 and retried_domain is not None:
            # Consume the budget only AFTER the fresh RUNNING session is
            # installed: a report between consumption and install would
            # see (old FAILED session, exhausted budget) and un-mask the
            # transient FAILED on the last permitted retry.
            self._consume_retry(retried_domain)
        # The epoch record is the journal's per-epoch state barrier:
        # replay folds registrations/transitions only from the LAST epoch
        # record forward, with the budget counters as consumed so far.
        self.journal.epoch(attempt, self._infra_retries_used,
                           self._preempt_retries_used)
        self._reregistration_grace = False
        self._epoch_span = self.tracer.start_span(
            "session.epoch", parent=self._run_span,
            attrs={"epoch": attempt})
        self.scheduler = GangScheduler(self.conf, self._launch_job)
        self._schedule_start = time.monotonic()
        self._rendezvous_span = self.tracer.start_span(
            "gang.rendezvous", parent=self._epoch_span,
            attrs={"expected": self.session.num_expected})
        self.scheduler.schedule_ready()

    def _resume_session(self) -> None:
        """Recovery twin of _start_session: the journaled epoch's session
        was rebuilt in __init__; re-adopt the surviving gang instead of
        launching one. Executors re-register through the ordinary
        register_worker_spec path (their processes never stopped), under
        the re-registration grace window instead of the first-rendezvous
        timeout; jobtypes whose launch never hit the journal go through
        schedule_ready as usual."""
        st = self._recover_state
        scheduled = set(self.session.scheduled_job_names())
        live = [t for t in self.session.all_tasks()
                if not t.status.terminal and t.job_name in scheduled]
        log.warning(
            "recovery: generation %d resumes session epoch %d — %d task(s) "
            "awaiting re-registration (%ds grace), budgets used: "
            "transient %d/%d, preemption %d/%d",
            self.generation, self.session.session_id, len(live),
            self.conf.get_int(K.COORDINATOR_REREGISTRATION_GRACE_S, 60),
            self._infra_retries_used, self._retries_total,
            self._preempt_retries_used, self._preempt_retries_total)
        self.events.emit(Event(EventType.COORDINATOR_RECOVERED, {
            "app_id": self.app_id, "generation": self.generation,
            "session_id": self.session.session_id,
            "journal_records": st.records if st else 0,
            "awaiting_reregistration": [t.task_id for t in live]}))
        self._reregistration_grace = True
        self._epoch_span = self.tracer.start_span(
            "session.epoch", parent=self._run_span,
            attrs={"epoch": self.session.session_id, "resumed": True})
        self.scheduler = GangScheduler(self.conf, self._launch_job)
        self.scheduler.restore(st.scheduled_jobs, st.completed_jobs)
        self._schedule_start = time.monotonic()
        self._rendezvous_span = self.tracer.start_span(
            "gang.rendezvous", parent=self._epoch_span,
            attrs={"expected": self.session.num_expected,
                   "re_registration": True})
        self.scheduler.schedule_ready()
        if self.elastic is not None:
            # The pre-crash gang had completed its rendezvous (or the
            # journal would hold no registrations worth re-adopting).
            self.elastic.established = True
            has_migrate = (st is not None
                           and st.inflight_migrate_job == self.elastic.job
                           and st.inflight_migrate_members)
            has_resize = (st is not None
                          and st.inflight_job == self.elastic.job
                          and st.inflight_members)
            if has_migrate and has_resize:
                # Both in flight on the journal means one superseded the
                # other without its closing record landing — the newer
                # membership generation owns the gang.
                if st.inflight_migrate_mgen >= st.inflight_mgen:
                    has_resize = False
                else:
                    has_migrate = False
            if has_migrate:
                # Mid-migration crash: RE-ENTER the drain toward the
                # journaled target at the journaled mgen — parked
                # survivors re-register with that mgen and the move
                # completes instead of the job restarting.
                reason = st.inflight_migrate_reason \
                    or "resumed mid-migration"
                self._start_migrate(st.inflight_migrate_members,
                                    st.inflight_migrate_target, reason,
                                    mgen=st.inflight_migrate_mgen,
                                    resumed=True)
                op = self.elastic.op
                log.warning(
                    "recovery: resuming in-flight migration to %r "
                    "(%d member(s), mgen %d) — %d survivor(s) still to "
                    "park", st.inflight_migrate_target,
                    len(op.members) if op else 0,
                    st.inflight_migrate_mgen,
                    len(op.awaiting) if op else 0)
            elif has_resize:
                # Mid-resize crash: RE-ENTER the drain at the journaled
                # membership generation instead of abandoning the resize
                # — parked survivors re-register with that mgen and the
                # op completes under the recovery grace window.
                live = [t for t in self.session.all_tasks()
                        if t.job_name == self.elastic.job
                        and not t.status.terminal]
                reason = st.inflight_reason or "resumed mid-resize"
                op = self.elastic.begin(st.inflight_members, live,
                                        reason, mgen=st.inflight_mgen)
                self.journal.resize(self.elastic.job, op.mgen,
                                    op.members, "start",
                                    self.session.session_id,
                                    reason=reason)
                self.events.emit(Event(EventType.GANG_RESIZED, {
                    "job": self.elastic.job, "phase": "started",
                    "mgen": op.mgen, "members": list(op.members),
                    "from": op.size_before, "to": len(op.members),
                    "reason": reason, "resumed": True,
                    "session_id": self.session.session_id}))
                log.warning(
                    "recovery: resuming in-flight resize to %d member(s) "
                    "(mgen %d) — %d survivor(s) still to park",
                    len(op.members), op.mgen, len(op.awaiting))

    def _monitor(self) -> SessionStatus:
        """Reference ``monitor()`` :581-650 — 5 s loop; 500 ms here."""
        interval = self.conf.get_int(K.COORDINATOR_MONITOR_INTERVAL_MS,
                                     500) / 1000.0
        timeout_s = self.conf.get_int(K.APPLICATION_TIMEOUT_S, 0)
        reg_timeout_s = self.conf.get_int(K.TASK_REGISTRATION_TIMEOUT_S, 900)
        regrace_s = self.conf.get_int(K.COORDINATOR_REREGISTRATION_GRACE_S,
                                      60)
        # Anchor the self-observation clock: the first tick_done only
        # records "now" so the first folded interval is a real tick.
        self.coordphases.tick_done()
        while True:
            if faults.fire("coordinator.crash"):
                # The SIGKILL shape: no teardown, no history finalize, no
                # gang kill — exactly what --recover must survive. The
                # call counter is monitor iterations, so `at:K` places
                # the crash deterministically mid-job.
                log.critical("FAULT coordinator.crash: hard-exiting with "
                             "no teardown (os._exit)")
                os._exit(137)
            slow_tick = faults.fire_amount("coord.slow-tick")
            if slow_tick:
                # Injected control-plane stall: the tick stretches by the
                # configured amount BEFORE any per-tick work, so the
                # slowdown lands in the tick-duration accounting the
                # self-observation surfaces must show.
                time.sleep(slow_tick)
            if self._reregistration_grace and self.session.all_registered():
                log.info("recovery: all surviving tasks re-registered; "
                         "resuming normal monitoring")
                self._reregistration_grace = False
            with self.coordphases.phase("rendezvous_barrier"):
                if self._rendezvous_span is not None \
                        and self.session.all_registered():
                    # The gang barrier opened: every later step (first
                    # step, epochs) hangs off a closed rendezvous on the
                    # timeline.
                    self._rendezvous_span.end(
                        registered=self.session.num_registered)
                    self._rendezvous_span = None
                    if self.elastic is not None:
                        # Resizes only make sense against an established
                        # gang; losses before this point are rendezvous
                        # failures, not absorbable churn.
                        self.elastic.established = True
            # Live-metrics export (throttled internally): keeps the
            # portal's /metrics exposition fresh while the job runs.
            self._maybe_write_prom()
            if self._stop_requested.is_set():
                self.session.fail(self._stop_reason or "stop requested")
                # TERM with the FULL configured grace (reference
                # stop-with-grace, ApplicationMaster.java:694-711): a
                # force-killed job's save-on-SIGTERM handlers
                # (checkpoint/manager.install_preemption_handler) get the
                # whole window to make the final save durable.
                self._kill_all_tasks(
                    self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15))
                return self.session.status
            if timeout_s and (time.monotonic() - self._schedule_start
                              > timeout_s):
                # The job exceeded its OWN configured wall-clock budget —
                # a rerun would exceed it again. USER_ERROR: terminal.
                self.session.fail(f"application timed out after {timeout_s}s",
                                  FailureDomain.USER_ERROR)
                return self.session.status
            reg_window = regrace_s if self._reregistration_grace \
                else reg_timeout_s
            if not self.session.all_registered() and reg_window and \
                    self.session.num_expected > 0 \
                    and (time.monotonic() - self._schedule_start
                         > reg_window):
                # Gang rendezvous timed out (reference registration timeout
                # kills stuck allocations, ApplicationMaster.java:791-888).
                # In recovery this is the re-registration grace expiring:
                # the gang did not survive the coordinator outage after
                # all — fall through to the ordinary retry machinery.
                what = ("re-registration grace (recovery)"
                        if self._reregistration_grace
                        else "registration timeout")
                self.session.fail(
                    f"{what}: {self.session.num_registered}/"
                    f"{self.session.num_expected} tasks registered within "
                    f"{reg_window}s", FailureDomain.INFRA_TRANSIENT)
                return self.session.status
            for task_id, exit_code in self.backend.poll_completions():
                self._process_completion(task_id, exit_code)
            with self.coordphases.phase("hb_scan"):
                self._check_heartbeats()
            self._check_progress()
            self._alerts_tick()
            self._elastic_tick()
            if self.session.status != SessionStatus.RUNNING:
                return self.session.status
            if self.session.training_finished():
                return self.session.update_status()
            with self.coordphases.phase("idle"):
                time.sleep(interval)
            # Close this tick's attribution interval (sum-to-wall fold,
            # like step_done for the data plane).
            self.coordphases.tick_done()

    def _kill_all_tasks(self, grace_s: float,
                        mark: str = "killed") -> None:
        """TERM→grace→KILL every non-terminal task, CONCURRENTLY: each
        kill_task blocks up to grace_s, and a serial loop would make
        teardown latency N·grace — longer than the client is willing to
        wait for the coordinator. One loop, one grace policy per call
        site (the previous three hand-rolled copies had three different
        caps, which is how the preemption-save window silently shrank to
        2 s)."""
        tasks = [t for t in self.session.all_tasks()
                 if t.handle is not None and not t.status.terminal]
        threads = [threading.Thread(
            target=self.backend.kill_task, args=(t.handle,),
            kwargs={"grace_s": grace_s}, daemon=True,
            name=f"kill-{t.task_id}") for t in tasks]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=grace_s + 30)
        for t in tasks:
            if mark == "none":
                continue          # epoch reset: the session is replaced
            if mark == "teardown" and not t.tracked:
                t.status = TaskStatus.SUCCEEDED  # ps-style normal teardown
            else:
                self.session.mark_killed(t.task_id)

    def _reset_session(self) -> None:
        # Short grace: the whole point of an epoch reset is a fast retry,
        # and the failed epoch's periodic checkpoints are the resume
        # source (save-on-TERM still gets 1 s for tiny states).
        grace = min(self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15), 1)
        # The old gang's lifecycle spans end here: the epoch reset is the
        # terminal event for tasks killed with mark="none" (they never
        # reach _process_completion under the replaced session).
        for task_id in list(self._task_spans):
            self._end_task_span(task_id, epoch_reset=True)
        self._kill_all_tasks(grace, mark="none")
        # Wait for the old gang to be FULLY down, draining exits as they
        # land. Breaking on the first empty poll is not enough: a killed
        # task that hasn't exited yet polls as nothing-to-report, and
        # relaunching while it lives trips the slice backend's
        # one-gang-per-lease invariant ("lost hosts while its gang is
        # still running") — a race observed under CI load.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.backend.poll_completions()
            if not self.backend.gang_active():
                break
            time.sleep(0.1)
        else:
            log.warning("old gang still has live tasks after reset grace; "
                        "relaunch may be refused by the backend")
        self.backend.poll_completions()   # clear final stale completions

    def _maybe_diagnose(self) -> None:
        """Automatic failure diagnosis (tony_tpu/diagnosis/): on any
        non-SUCCEEDED finish, flush the event stream to disk, run the
        collector + rule engine over the job dir, write incident.json,
        and emit JOB_DIAGNOSED so downstream tooling sees the verdict
        without re-running the engine. Best-effort by contract: the
        flight recorder must never be the reason a teardown fails."""
        if self.final_status == SessionStatus.SUCCEEDED:
            return
        if not self.conf.get_bool(K.DIAGNOSIS_ENABLED, True):
            return
        try:
            from tony_tpu import diagnosis

            # The collector reads the in-progress jhist file from disk;
            # the async writer must materialize everything emitted so
            # far (including APPLICATION_FINISHED) first.
            self.events.flush()
            incident = diagnosis.diagnose_job_dir(
                self.job_dir, app_id=self.app_id,
                tail_bytes=self.conf.get_int(
                    K.DIAGNOSIS_LOG_TAIL_BYTES, 65536))
            # The just-emitted APPLICATION_FINISHED carries the final
            # status; stamp it in case the stream lagged anyway.
            incident["status"] = self.final_status.value
            incident["provisional"] = False
            path = os.path.join(self.job_dir, constants.INCIDENT_FILE)
            diagnosis.save_incident(path, incident)
            v = incident.get("verdict") or {}
            log.warning(
                "incident diagnosis: %s (blamed task %s, rule %s) — "
                "report at %s", v.get("category", "UNKNOWN"),
                v.get("blamed_task") or "-", v.get("rule", "?"), path)
            self.events.emit(Event(EventType.JOB_DIAGNOSED, {
                "app_id": self.app_id,
                "category": v.get("category", "UNKNOWN"),
                "blamed_task": v.get("blamed_task", ""),
                "rule": v.get("rule", ""),
                "confidence": v.get("confidence", 0.0),
                "summary": v.get("summary", ""),
                "incident_path": path}))
        except Exception:  # noqa: BLE001 — diagnosis is best-effort
            log.exception("incident diagnosis failed")

    def _stop(self) -> None:
        """Reference ``stop()`` :670-711 — stop running tasks with grace,
        wait for the client finish signal, finalize history."""
        # Full grace: the survivors here are untracked services (ps,
        # heads, notebooks) on a job that already finished — they get the
        # same TERM window as everyone else (a TERM-honouring service
        # exits immediately; only TERM-ignoring ones cost the window).
        self._kill_all_tasks(
            self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15),
            mark="teardown")
        if self.conf.get_bool(K.APPLICATION_NUM_CLIENTS_TO_WAIT, True):
            self.client_signalled_finish.wait(
                timeout=self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15))
        conf_url = str(self.conf.get(K.INTERNAL_CONF_URL, "") or "")
        if conf_url and self.conf.get_bool(K.APPLICATION_PROFILER_ENABLED):
            # Pull store-staged chief traces into the job dir so the
            # portal's /profiles view works for remote-host jobs too.
            try:
                from tony_tpu.storage import get_store

                url = self._profile_store_url(conf_url)
                store = get_store(url)
                if store.isdir(url):
                    store.get_tree(url, os.path.join(self.job_dir,
                                                     "profile"))
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.warning("profile trace localization failed: %s", e)
        if self.final_status == SessionStatus.SUCCEEDED \
                and not self._alerts_degraded:
            # A SUCCEEDED job's journal must not end with an alert
            # firing (the alert-journal invariant): force-resolve every
            # open rule while the journal and event stream are still
            # writable. Failed jobs deliberately KEEP their firing
            # alerts — they are the diagnosis engine's evidence.
            try:
                for tr in self.alerts.resolve_all():
                    self._apply_alert_transition(tr)
            except Exception:  # noqa: BLE001 — teardown best-effort
                log.exception("alert teardown resolve failed")
        # Step-time attribution report BEFORE diagnosis: the incident
        # bundle attaches perf.json as its perf advisory section.
        self._write_perf_report()
        self.events.emit(Event(EventType.APPLICATION_FINISHED, {
            "app_id": self.app_id, "status": self.final_status.value,
            "failure_reason": self.session.failure_reason or "",
            "failure_domain": (self.session.failure_domain.value
                               if self.session.failure_domain else ""),
        }))
        self._maybe_diagnose()
        # Close the trace: untracked services killed at teardown still
        # hold open lifecycle spans; the finish marker + root span close
        # the tree (zero unclosed spans on any orderly shutdown), and the
        # final exposition snapshot freezes terminal task states.
        for task_id in list(self._task_spans):
            self._end_task_span(task_id, teardown=True)
        self.tracer.instant("application.finished", parent=self._run_span,
                            attrs={"status": self.final_status.value})
        self._run_span.end(status=self.final_status.value)
        self._maybe_write_prom(force=True)
        self.events.stop(history.final_name(
            self.app_id, self._started_ms, int(time.time() * 1000), self.user,
            self.final_status.value))
        self.journal.close()
        self.backend.stop()
        self.rpc.stop()
        self.tracer.close()
