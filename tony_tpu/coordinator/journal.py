"""Write-ahead session journal: the coordinator's crash-survivable memory.

The reference inherited application-master restart from YARN
(``keepContainersAcrossApplicationAttempts``: the AM dies, comes back,
and the containers — the gang — keep running). Our coordinator had no
equivalent: Session/Task state lived only in memory
(``coordinator/session.py``), so a coordinator crash lost the job even
though the executors, the rendezvous, and the verified checkpoints all
survived. This module closes that gap: every control-plane state
transition — registration, task state change, epoch reset, failure
verdict, generation bump — is appended as one JSON line and fsync'd
BEFORE the transition is acted on (write-ahead discipline), into a file
next to the job's history stream. ``replay`` folds the journal back into
the state a restarted coordinator needs to resume the SAME epoch and
enter a re-registration grace window instead of launching a fresh gang.

Format: JSON lines (same choice as the event stream — self-describing,
greppable, no schema compiler); one record per line, ``"t"`` is the
record type. Torn final record (the crash window between ``write`` and
``fsync``, utils/durable.py): replay stops at the first undecodable or
unterminated line and uses the prefix — NEVER an exception. Losing the
last record is safe by construction: write-ahead means the lost record's
transition was not yet acted on, so the world matches the prefix.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, Dict, Iterable, Iterator, Optional, Set, Tuple

from tony_tpu.utils.durable import AppendLog, DurableWriteError

log = logging.getLogger(__name__)

#: record types (the "t" field)
REC_GENERATION = "gen"            # coordinator (re)start: generation bump
REC_APP = "app"                   # app identity: app_id/started_ms/user
REC_EPOCH = "epoch"               # session (re)start at a retry epoch
REC_JOB_SCHEDULED = "job_scheduled"
REC_JOB_COMPLETED = "job_completed"
REC_REGISTER = "register"         # executor registration (host/port)
REC_TASK = "task"                 # task state transition
REC_VERDICT = "verdict"           # failure-domain verdict for an epoch
REC_PROGRESS = "progress"         # throttled task step-counter checkpoint
REC_RESIZE = "resize"             # elastic membership change (start/applied)
REC_MIGRATE = "migrate"           # live slice migration (start/applied/
                                  # superseded) — coordinator/migrate.py
REC_ALERT = "alert"               # alert state transition (pending/
                                  # firing/resolved) — tony_tpu/alerts/


class JournalError(RuntimeError):
    pass


@dataclasses.dataclass
class TaskRecord:
    """Folded per-task state for the CURRENT epoch."""

    status: str = "NEW"
    host: str = ""
    port: int = 0
    registered: bool = False
    exit_code: Optional[int] = None
    domain: str = ""
    # Last journalled step counter (-1 = none): seeds the recovered
    # coordinator's progress tracker so hang deadlines RESUME (fresh
    # clock, armed state) instead of instantly expiring across the
    # outage (coordinator/liveness.py track(steps_hint=...)).
    steps: float = -1.0


@dataclasses.dataclass
class ReplayState:
    """What a recovering coordinator reconstructs from the journal."""

    generation: int = 0
    app_id: str = ""
    started_ms: int = 0
    user: str = ""
    session_id: int = 0
    infra_retries_used: int = 0
    preempt_retries_used: int = 0
    scheduled_jobs: Set[str] = dataclasses.field(default_factory=set)
    completed_jobs: Set[str] = dataclasses.field(default_factory=set)
    tasks: Dict[str, TaskRecord] = dataclasses.field(default_factory=dict)
    records: int = 0              # complete records replayed
    torn_tail: bool = False       # a torn/undecodable suffix was dropped
    # --- elastic membership (coordinator/elastic.py) -------------------
    # Highest membership generation journaled (monotonic across lives).
    elastic_mgen: int = 0
    # Member indices of the LAST applied resize per job — the matrix the
    # recovered coordinator must rebuild (None = never resized).
    applied_members: Dict[str, list] = dataclasses.field(
        default_factory=dict)
    # An in-flight resize (start with no matching applied): the recovered
    # coordinator re-enters the drain instead of abandoning it, so a
    # mid-resize crash completes the resize rather than restarting the
    # job. (job, mgen, members, reason) — empty job = none.
    inflight_job: str = ""
    inflight_mgen: int = 0
    inflight_members: list = dataclasses.field(default_factory=list)
    inflight_reason: str = ""
    # --- live migration (coordinator/migrate.py) -----------------------
    # Target slice of the LAST applied migration per job: the recovered
    # coordinator re-pins job.node_pool so relaunches land on the slice
    # the job actually moved to.
    migrated_target: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    # An in-flight migration (start with no applied/superseded): the
    # recovered coordinator re-enters the drain toward the target
    # instead of abandoning the move. Empty job = none.
    inflight_migrate_job: str = ""
    inflight_migrate_mgen: int = 0
    inflight_migrate_members: list = dataclasses.field(
        default_factory=list)
    inflight_migrate_target: str = ""
    inflight_migrate_reason: str = ""
    # --- alerting (tony_tpu/alerts/) -----------------------------------
    # Last journaled state per alert rule (last-wins fold). NOT cleared
    # on REC_EPOCH: an alert watches the job across retry epochs — a
    # heartbeat alert that fired in epoch 2 is still firing while epoch
    # 3's gang launches. Seeds AlertEngine.seed() on --recover so a
    # firing alert survives a coordinator SIGKILL.
    alerts: Dict[str, str] = dataclasses.field(default_factory=dict)


class SessionJournal:
    """Append side. ``enabled=False`` turns every append into a no-op so
    the journal can be conf-gated without littering call sites.

    ``observer`` is the control-plane self-observation seam
    (coordinator/coordphases.py): called ``(n_bytes, seconds)`` after
    every fsync'd append, it feeds the ``journal_fsync`` tick phase, the
    fsync-latency histogram, and the records/bytes rate counters — the
    numbers behind the JOURNAL_BOUND verdict. Best-effort by contract:
    an observer failure must never fail a write-ahead append."""

    def __init__(self, path: str, enabled: bool = True,
                 observer: Optional[Callable[[int, float], None]]
                 = None) -> None:
        self.path = path
        self.enabled = enabled
        self.observer = observer
        #: first durable-write failure, sticky (ENOSPC/EIO). The FIRST
        #: failing append raises so the caller hears it; later appends
        #: no-op — the journal is declared dead ONCE, loudly, and the
        #: teardown/verdict paths must not cascade tracebacks against a
        #: disk that cannot take the write anyway. The committed prefix
        #: on disk stays replayable (readers tolerate a torn tail).
        self.dead: Optional[DurableWriteError] = None
        self._log: Optional[AppendLog] = AppendLog(path) if enabled else None

    def append(self, record: Dict) -> None:
        if self._log is None:
            return
        if self.dead is not None:
            return
        record.setdefault("ts", int(time.time() * 1000))
        data = (json.dumps(record, sort_keys=True) + "\n").encode("utf-8")
        t0 = time.monotonic()
        try:
            self._log.append(data)
        except DurableWriteError as e:
            self.dead = e
            log.critical(
                "session journal %s is DEAD (%s): failing loudly — a "
                "coordinator that cannot journal cannot be recovered "
                "truthfully; the committed prefix remains replayable",
                self.path, e)
            raise
        if self.observer is not None:
            try:
                self.observer(len(data), time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — observation is best-effort
                log.exception("journal observer failed")

    # -- typed convenience appenders (one per record shape) ---------------
    def generation(self, generation: int) -> None:
        self.append({"t": REC_GENERATION, "generation": generation})

    def app(self, app_id: str, started_ms: int, user: str) -> None:
        self.append({"t": REC_APP, "app_id": app_id,
                     "started_ms": started_ms, "user": user})

    def epoch(self, session_id: int, infra_used: int,
              preempt_used: int) -> None:
        self.append({"t": REC_EPOCH, "session": session_id,
                     "infra_used": infra_used, "preempt_used": preempt_used})

    def job_scheduled(self, job: str, session_id: int) -> None:
        self.append({"t": REC_JOB_SCHEDULED, "job": job,
                     "session": session_id})

    def job_completed(self, job: str, session_id: int) -> None:
        self.append({"t": REC_JOB_COMPLETED, "job": job,
                     "session": session_id})

    def register(self, task_id: str, host: str, port: int,
                 session_id: int) -> None:
        self.append({"t": REC_REGISTER, "task": task_id, "host": host,
                     "port": port, "session": session_id})

    def task(self, task_id: str, status: str, session_id: int,
             exit_code: Optional[int] = None, domain: str = "") -> None:
        rec = {"t": REC_TASK, "task": task_id, "status": status,
               "session": session_id}
        if exit_code is not None:
            rec["exit"] = exit_code
        if domain:
            rec["domain"] = domain
        self.append(rec)

    def verdict(self, session_id: int, domain: str, reason: str) -> None:
        self.append({"t": REC_VERDICT, "session": session_id,
                     "domain": domain, "reason": reason})

    def progress(self, task_id: str, steps: float, session_id: int) -> None:
        """Throttled by the caller (liveness.PROGRESS_JOURNAL_MIN_INTERVAL_S)
        — the journal is fsync'd and must stay control-plane-rate."""
        self.append({"t": REC_PROGRESS, "task": task_id, "steps": steps,
                     "session": session_id})

    def resize(self, job: str, mgen: int, members: Iterable[int],
               phase: str,
               session_id: int, reason: str = "") -> None:
        """Elastic membership transition. Write-ahead discipline:
        ``phase="start"`` lands BEFORE any drain directive is issued and
        ``phase="applied"`` BEFORE the new topology's launches, so a
        crash anywhere inside a resize replays into either "re-enter the
        drain" or "the new matrix, under the re-registration grace"."""
        self.append({"t": REC_RESIZE, "job": job, "mgen": int(mgen),
                     "members": sorted(int(m) for m in members),
                     "phase": phase, "session": session_id,
                     "reason": reason})

    def migrate(self, job: str, mgen: int, members: Iterable[int],
                phase: str, target: str, session_id: int,
                reason: str = "") -> None:
        """Live-migration transition (coordinator/migrate.py). Same
        write-ahead discipline as ``resize``: ``phase="start"`` lands
        BEFORE the drain directive, ``phase="applied"`` BEFORE the
        destination launches, and ``phase="superseded"`` when a host
        loss mid-migration folds the op into an ordinary elastic
        shrink — every start is closed by applied/superseded/epoch."""
        self.append({"t": REC_MIGRATE, "job": job, "mgen": int(mgen),
                     "members": sorted(int(m) for m in members),
                     "phase": phase, "target": target,
                     "session": session_id, "reason": reason})

    def alert(self, rule: str, state: str, severity: str,
              value: Optional[float], labels: Dict[str, str],
              summary: str) -> None:
        """Alert state-machine transition (tony_tpu/alerts/). Write-ahead
        like everything else: the record lands BEFORE the ALERT_FIRING/
        ALERT_RESOLVED event or gauge update, so a recovered coordinator
        re-arms the exact firing set. The engine's dedup fence guarantees
        consecutive records for a rule never repeat a state."""
        rec = {"t": REC_ALERT, "rule": rule, "state": state,
               "severity": severity, "summary": summary}
        if value is not None:
            rec["value"] = float(value)
        if labels:
            rec["labels"] = dict(labels)
        self.append(rec)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None


def _iter_complete_lines(path: str) -> Tuple[Iterator[bytes], bool]:
    """Yield complete (newline-terminated) lines; a trailing unterminated
    line is the torn-write window and is dropped, flagged via the second
    yield element."""
    with open(path, "rb") as f:
        buf = f.read()
    end = buf.rfind(b"\n")
    torn = end != len(buf) - 1 and len(buf) > 0
    if end < 0:
        return iter(()), torn or bool(buf)
    return iter(buf[:end].split(b"\n")), torn


def replay(path: str) -> ReplayState:
    """Fold the journal into a ReplayState.

    Torn/corrupt tail: replay consumes records in order and STOPS at the
    first line that fails to decode — the remainder is the crash window
    and the write-ahead discipline guarantees the world matches the
    prefix. A missing journal is a JournalError (recovery was requested
    for a job that never journaled — operator error, say so plainly).
    """
    if not os.path.exists(path):
        raise JournalError(
            f"no session journal at {path} — this job was not run with "
            f"the journal enabled (tony.coordinator.journal-enabled), or "
            f"the wrong history/job directory was given")
    state = ReplayState()
    lines, torn = _iter_complete_lines(path)
    state.torn_tail = bool(torn)
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except (ValueError, UnicodeDecodeError) as e:
            # Mid-file damage cannot be attributed to the torn-write
            # window, but the recovery contract is the same: replay the
            # prefix rather than refuse to recover at all.
            log.warning("journal %s: undecodable record after %d good "
                        "ones (%s) — replaying the prefix", path,
                        state.records, e)
            state.torn_tail = True
            break
        state.records += 1
        t = rec.get("t")
        if t == REC_GENERATION:
            state.generation = max(state.generation,
                                   int(rec.get("generation", 0) or 0))
        elif t == REC_APP:
            state.app_id = str(rec.get("app_id", "") or "")
            state.started_ms = int(rec.get("started_ms", 0) or 0)
            state.user = str(rec.get("user", "") or "")
        elif t == REC_EPOCH:
            # A new epoch supersedes all per-epoch state before it.
            state.session_id = int(rec.get("session", 0) or 0)
            state.infra_retries_used = int(rec.get("infra_used", 0) or 0)
            state.preempt_retries_used = int(rec.get("preempt_used", 0) or 0)
            state.scheduled_jobs.clear()
            state.completed_jobs.clear()
            state.tasks.clear()
            # Membership belongs to the epoch's gang (a retry epoch
            # relaunches at the configured size); the generation itself
            # stays monotonic so old-topology zombies stay fenced.
            state.applied_members.clear()
            state.inflight_job = ""
            state.inflight_members = []
            state.inflight_reason = ""
            state.inflight_mgen = 0
            # A retry epoch relaunches wherever its conf points: the
            # applied-migration pin and any in-flight move die with the
            # gang they were moving (an epoch reset CLOSES a dangling
            # migrate start — the invariant checker counts on it).
            state.migrated_target.clear()
            state.inflight_migrate_job = ""
            state.inflight_migrate_members = []
            state.inflight_migrate_target = ""
            state.inflight_migrate_reason = ""
            state.inflight_migrate_mgen = 0
        elif t == REC_JOB_SCHEDULED:
            if int(rec.get("session", 0) or 0) == state.session_id:
                state.scheduled_jobs.add(str(rec.get("job", "")))
        elif t == REC_JOB_COMPLETED:
            if int(rec.get("session", 0) or 0) == state.session_id:
                state.completed_jobs.add(str(rec.get("job", "")))
        elif t == REC_REGISTER:
            if int(rec.get("session", 0) or 0) != state.session_id:
                continue
            tr = state.tasks.setdefault(str(rec.get("task", "")),
                                        TaskRecord())
            tr.host = str(rec.get("host", "") or "")
            tr.port = int(rec.get("port", 0) or 0)
            tr.registered = True
            if tr.status in ("NEW", "SCHEDULED"):
                tr.status = "RUNNING"
        elif t == REC_TASK:
            if int(rec.get("session", 0) or 0) != state.session_id:
                continue
            tr = state.tasks.setdefault(str(rec.get("task", "")),
                                        TaskRecord())
            tr.status = str(rec.get("status", tr.status) or tr.status)
            if "exit" in rec:
                tr.exit_code = int(rec["exit"])
            if rec.get("domain"):
                tr.domain = str(rec["domain"])
        elif t == REC_PROGRESS:
            if int(rec.get("session", 0) or 0) != state.session_id:
                continue
            tr = state.tasks.setdefault(str(rec.get("task", "")),
                                        TaskRecord())
            try:
                tr.steps = float(rec.get("steps", -1.0))
            except (TypeError, ValueError):
                pass
        elif t == REC_RESIZE:
            if int(rec.get("session", 0) or 0) != state.session_id:
                continue
            job = str(rec.get("job", "") or "")
            mgen = int(rec.get("mgen", 0) or 0)
            members = [int(m) for m in rec.get("members", []) or []]
            state.elastic_mgen = max(state.elastic_mgen, mgen)
            if rec.get("phase") == "applied":
                state.applied_members[job] = members
                # The applied topology supersedes the removed tasks'
                # folded state AND any in-flight start it completes.
                state.tasks = {
                    tid: tr for tid, tr in state.tasks.items()
                    if tid.partition(":")[0] != job
                    or int(tid.rpartition(":")[2]) in members}
                if state.inflight_job == job \
                        and state.inflight_mgen <= mgen:
                    state.inflight_job = ""
                    state.inflight_members = []
                    state.inflight_reason = ""
                    state.inflight_mgen = 0
            else:                  # "start": a resize is in flight
                state.inflight_job = job
                state.inflight_mgen = mgen
                state.inflight_members = members
                state.inflight_reason = str(rec.get("reason", "") or "")
        elif t == REC_MIGRATE:
            if int(rec.get("session", 0) or 0) != state.session_id:
                continue
            job = str(rec.get("job", "") or "")
            mgen = int(rec.get("mgen", 0) or 0)
            members = [int(m) for m in rec.get("members", []) or []]
            target = str(rec.get("target", "") or "")
            state.elastic_mgen = max(state.elastic_mgen, mgen)
            phase = rec.get("phase")
            if phase == "applied":
                # The move completed: relaunches must land on the
                # target slice, and the same-member topology is the
                # applied matrix. EVERY task was replaced by a fresh
                # destination launch — drop the source gang's folded
                # records (host/port/registered belong to dead
                # executors); the destination's REC_TASK/REC_REGISTER
                # records that follow rebuild them.
                state.migrated_target[job] = target
                state.applied_members[job] = members
                state.tasks = {
                    tid: tr for tid, tr in state.tasks.items()
                    if tid.partition(":")[0] != job}
                if state.inflight_migrate_job == job \
                        and state.inflight_migrate_mgen <= mgen:
                    state.inflight_migrate_job = ""
                    state.inflight_migrate_members = []
                    state.inflight_migrate_target = ""
                    state.inflight_migrate_reason = ""
                    state.inflight_migrate_mgen = 0
            elif phase == "superseded":
                # A host loss mid-migration folded the op into an
                # ordinary elastic shrink: the move is abandoned, the
                # resize records that follow own the membership story.
                if state.inflight_migrate_job == job \
                        and state.inflight_migrate_mgen <= mgen:
                    state.inflight_migrate_job = ""
                    state.inflight_migrate_members = []
                    state.inflight_migrate_target = ""
                    state.inflight_migrate_reason = ""
                    state.inflight_migrate_mgen = 0
            else:                  # "start": a migration is in flight
                state.inflight_migrate_job = job
                state.inflight_migrate_mgen = mgen
                state.inflight_migrate_members = members
                state.inflight_migrate_target = target
                state.inflight_migrate_reason = str(
                    rec.get("reason", "") or "")
        elif t == REC_ALERT:
            # Last-wins per rule; deliberately NOT epoch-scoped (see the
            # ReplayState field comment).
            rule = str(rec.get("rule", "") or "")
            if rule:
                state.alerts[rule] = str(rec.get("state", "") or "")
        elif t == REC_VERDICT:
            pass                   # forensic record; no folded state
        else:
            # Unknown record types from a NEWER build replaying an older
            # coordinator's journal: skip, do not fail recovery.
            log.warning("journal %s: unknown record type %r skipped",
                        path, t)
    return state
