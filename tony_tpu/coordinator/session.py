"""In-coordinator job state: the task matrix, cluster spec and failure policy.

Reference model: ``tensorflow/TonySession.java`` (561 LoC) —
- jobName → TonyTask[] matrix (:54) with a per-task state machine (:410-551);
- cluster spec {job: [host:port, ...]} built from registered workers
  (``getClusterSpec`` :226-246);
- chief semantics: the ``chief`` jobtype, else worker:0 (``isChief`` :364);
- failure policy on task completion (:251-271): chief failure fails the job;
  ``stop-on-failure-jobtypes`` short-circuit; optional fail-on-any-worker;
- final-status reduction over tracked tasks (``updateSessionStatus`` :276-330);
- ``sessionId`` retry epoch incremented on whole-job retry (:51).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, List, Optional

from tony_tpu import constants
from tony_tpu.conf.config import JobType, TonyTpuConfig
from tony_tpu.conf import keys as K
from tony_tpu.devtools.race import guarded


class TaskStatus(str, enum.Enum):
    NEW = "NEW"                # defined, not yet handed to the backend
    SCHEDULED = "SCHEDULED"    # launch requested from the backend
    RUNNING = "RUNNING"        # process up (registered or heartbeating)
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"

    @property
    def terminal(self) -> bool:
        return self in (TaskStatus.SUCCEEDED, TaskStatus.FAILED,
                        TaskStatus.KILLED)


class SessionStatus(str, enum.Enum):
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"


class FailureDomain(str, enum.Enum):
    """Which kind of thing broke — the axis the retry policy pivots on.

    The reference burned one undiscriminating retry budget on everything
    (``ApplicationMaster.java:356-371``); at TPU scale the three causes
    have opposite economics: a user bug reproduces deterministically (any
    retry is wasted epochs), transient infra deserves the bounded budget,
    and preemption is EXPECTED churn on spot/reclaimable capacity — it
    must not be able to exhaust the budget kept for real failures.
    """

    USER_ERROR = "USER_ERROR"            # non-retryable by default
    INFRA_TRANSIENT = "INFRA_TRANSIENT"  # retryable, consumes retry-count
    PREEMPTION = "PREEMPTION"            # retryable on its own free budget


#: reduction precedence when one epoch has multiple failed tasks: the
#: least-retryable domain decides the epoch's fate.
_DOMAIN_SEVERITY = {FailureDomain.PREEMPTION: 0,
                    FailureDomain.INFRA_TRANSIENT: 1,
                    FailureDomain.USER_ERROR: 2}


def worst_domain(a: Optional[FailureDomain],
                 b: Optional[FailureDomain]) -> Optional[FailureDomain]:
    if a is None:
        return b
    if b is None:
        return a
    return a if _DOMAIN_SEVERITY[a] >= _DOMAIN_SEVERITY[b] else b


def classify_exit(exit_code: int,
                  hint: Optional[str] = None) -> Optional[FailureDomain]:
    """Map a task completion to its failure domain.

    ``hint`` is the backend's attribution when it knows the MACHINE died
    (``Backend.completion_domain``) — exit codes alone cannot tell a lost
    host (137) from an OOM kill (137). Without a hint:
    exit 0 → None (no failure); 143 (128+SIGTERM) → PREEMPTION (the
    advance-notice save path); 137 (SIGKILL) → INFRA_TRANSIENT (liveness
    kill / OOM / sudden death — retryable, on the accounted budget);
    anything else → USER_ERROR (the user process chose that exit).
    """
    if hint:
        return FailureDomain(hint)
    if exit_code == 0:
        return None
    if exit_code == constants.EXIT_PREEMPTED:
        return FailureDomain.PREEMPTION
    if exit_code == constants.EXIT_KILLED:
        return FailureDomain.INFRA_TRANSIENT
    return FailureDomain.USER_ERROR


@dataclasses.dataclass
class Task:
    """One gang member (reference ``TonySession.TonyTask`` :410-551)."""

    job_name: str
    index: int
    session_id: int = 0
    status: TaskStatus = TaskStatus.NEW
    host: str = ""
    port: int = 0
    exit_code: Optional[int] = None
    tracked: bool = True
    registered: bool = False
    tb_url: str = ""
    handle: object = None  # backend-specific process/lease handle
    failure_domain: Optional[FailureDomain] = None

    @property
    def task_id(self) -> str:
        return f"{self.job_name}:{self.index}"

    @property
    def spec(self) -> str:
        return f"{self.host}:{self.port}"

    def to_info(self) -> Dict[str, object]:
        """Wire form of TaskInfo (reference ``rpc/TaskInfo.java``)."""
        return {
            "name": self.job_name, "index": self.index,
            "status": self.status.value, "url": self.tb_url,
            "host": self.host, "port": self.port,
            "exit_code": self.exit_code, "session_id": self.session_id,
            "failure_domain": (self.failure_domain.value
                               if self.failure_domain else ""),
        }


@guarded
class Session:
    """Task matrix + rendezvous barrier + failure policy.

    Thread-safety: RPC handler threads mutate the matrix (register,
    completion, resize) while the monitor tick reads/reduces it — every
    touch of the ``GUARDED_BY`` fields holds ``_lock`` (an RLock, so
    locked methods compose). The scalar fields are atomic rebinds whose
    writes all happen under the same lock; they are audited in the
    registry but not lock-enforced on read (a reader sees the old or the
    new value, both valid snapshots).
    """

    #: tonyrace registry (devtools/race.py + the guarded-by lint rules)
    GUARDED_BY = {
        "tasks": "_lock",
        "scheduled_jobs": "_lock",
        "status": None,
        "failure_reason": None,
        "failure_domain": None,
        "_scheduling_narrowed": None,
    }

    def __init__(self, conf: TonyTpuConfig, session_id: int = 0):
        self.conf = conf
        self.session_id = session_id
        self.jobs: Dict[str, JobType] = conf.job_types()
        untracked = set(conf.untracked_jobtypes())
        self.stop_on_failure = set(
            conf.get_list(K.APPLICATION_STOP_ON_FAILURE_JOBTYPES))
        self.fail_on_worker_failure = conf.get_bool(
            K.APPLICATION_FAIL_ON_WORKER_FAILURE)
        self._lock = threading.RLock()
        self._untracked = untracked
        self.tasks: Dict[str, Task] = {}
        for job in self.jobs.values():
            for i in range(job.instances):
                t = Task(job.name, i, session_id=session_id,
                         tracked=job.name not in untracked)
                self.tasks[t.task_id] = t
        self.status = SessionStatus.RUNNING
        self.failure_reason: Optional[str] = None
        self.failure_domain: Optional[FailureDomain] = None
        # Jobtypes whose gang has been handed to the backend. The rendezvous
        # barrier and cluster spec cover exactly these (reference
        # ``TonySession.getNumExpectedTasks`` :193 — "scheduled at current
        # time"); a staged DAG must not make early-stage executors wait on
        # jobtypes that haven't launched. Starts as ALL jobs so that direct
        # Session use (unit tests, non-DAG paths) keeps whole-job barrier
        # semantics; the coordinator narrows it before the first launch.
        self.scheduled_jobs = set(self.jobs)
        self._scheduling_narrowed = False

    # -- queries ----------------------------------------------------------
    def get_task(self, task_id: str) -> Optional[Task]:
        with self._lock:
            return self.tasks.get(task_id)

    def all_tasks(self) -> List[Task]:
        with self._lock:
            return list(self.tasks.values())

    def tracked_tasks(self) -> List[Task]:
        with self._lock:
            return [t for t in self.tasks.values() if t.tracked]

    def scheduled_job_names(self) -> List[str]:
        with self._lock:
            return sorted(self.scheduled_jobs)

    def members(self, job_name: str) -> List[int]:
        """Sorted member indices of a jobtype's gang. Dense
        ``range(instances)`` until an elastic resize makes it sparse
        (coordinator/elastic.py): a shrink keeps SURVIVOR indices — task
        identity is stable across resizes; only the dense rank (a task's
        position in this list) changes."""
        with self._lock:
            return sorted(t.index for t in self.tasks.values()
                          if t.job_name == job_name)

    def resize_job(self, job_name: str, members) -> List[Task]:
        """Apply an elastic membership change: the jobtype's gang becomes
        exactly ``members`` (indices). Live tasks already in the set are
        kept (their executors are parked at the barrier and re-register);
        indices without a live task get a FRESH Task (returned for the
        caller to launch — lost hosts being replaced, or grow-back);
        indices outside the set are dropped from the matrix (their
        executors were released and any stragglers are fenced as
        non-members). ``jobs[job].instances`` tracks the new cardinality
        so TASK_NUM and the quota surfaces stay truthful."""
        with self._lock:
            job = self.jobs[job_name]
            wanted = sorted(set(int(m) for m in members))
            for t in [t for t in self.tasks.values()
                      if t.job_name == job_name]:
                if t.index not in wanted:
                    del self.tasks[t.task_id]
            fresh: List[Task] = []
            for i in wanted:
                tid = f"{job_name}:{i}"
                t = self.tasks.get(tid)
                if t is None or t.status.terminal:
                    nt = Task(job_name, i, session_id=self.session_id,
                              tracked=job_name not in self._untracked)
                    self.tasks[tid] = nt
                    fresh.append(nt)
            job.instances = len(wanted)
            return fresh

    def is_chief(self, job_name: str, index: int) -> bool:
        """Reference ``TonySession.isChief`` :364 — the ``chief`` jobtype if it
        exists, else worker:0."""
        if constants.CHIEF_JOB_NAME in self.jobs:
            return job_name == constants.CHIEF_JOB_NAME
        return job_name == constants.WORKER_JOB_NAME and index == 0

    def mark_job_scheduled(self, job_name: str) -> None:
        """Called by the coordinator before launching a gang. The first call
        narrows the barrier scope from "all jobs" to "launched jobs" (staged
        DAGs add later stages as they launch)."""
        with self._lock:
            if not self._scheduling_narrowed:
                self.scheduled_jobs = set()
                self._scheduling_narrowed = True
            self.scheduled_jobs.add(job_name)

    def _expected_tasks_locked(self) -> List[Task]:
        return [t for t in self.tasks.values()
                if t.job_name in self.scheduled_jobs]

    @property
    def num_expected(self) -> int:
        with self._lock:
            return len(self._expected_tasks_locked())

    @property
    def num_registered(self) -> int:
        with self._lock:
            return sum(1 for t in self.tasks.values() if t.registered)

    def all_registered(self) -> bool:
        with self._lock:
            expected = self._expected_tasks_locked()
            return bool(expected) and all(t.registered for t in expected)

    def get_cluster_spec(self) -> Optional[Dict[str, List[str]]]:
        """{job: ["host:port", ...]} once all *scheduled* tasks registered,
        else None — this None is the gang barrier the executors poll on
        (reference ``ApplicationMaster.java:856-888`` returns null until every
        one of numExpectedTasks has registered; spec built by
        ``TonySession.getClusterSpec`` :226-246). Only jobs whose gang has
        launched appear; later DAG stages join the spec when they launch."""
        with self._lock:
            if not self.all_registered():
                return None
            spec: Dict[str, List[str]] = {}
            for job_name in self.jobs:
                if job_name not in self.scheduled_jobs:
                    continue
                # Dense-rank order over the (possibly sparse, post-resize)
                # member indices: list position IS the dense rank the
                # runtimes build JAX_PROCESS_ID / TF_CONFIG from.
                addrs = [self.tasks[f"{job_name}:{i}"].spec
                         for i in self.members(job_name)]
                if addrs:
                    spec[job_name] = addrs
            return spec

    # -- mutations --------------------------------------------------------
    def register_worker(self, task_id: str, host: str, port: int) -> bool:
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None or t.status.terminal:
                return False
            t.host, t.port = host, int(port)
            t.registered = True
            if t.status in (TaskStatus.NEW, TaskStatus.SCHEDULED):
                t.status = TaskStatus.RUNNING
            return True

    def on_task_completed(self, task_id: str, exit_code: int,
                          domain_hint: Optional[str] = None) -> None:
        """Apply completion + failure policy (reference
        ``TonySession.onTaskCompleted`` :251-271). ``domain_hint`` is the
        backend's failure attribution (``Backend.completion_domain``)."""
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None or t.status.terminal:
                return
            t.exit_code = exit_code
            if exit_code == 0:
                t.status = TaskStatus.SUCCEEDED
                return
            t.status = (TaskStatus.KILLED
                        if exit_code == constants.EXIT_KILLED
                        else TaskStatus.FAILED)
            domain = classify_exit(exit_code, domain_hint)
            t.failure_domain = domain
            tag = f"exit {exit_code}, {domain.value if domain else '?'}"
            if not t.tracked:
                # Untracked (ps-style) crash is still a job failure when it
                # dies on its own (reference ApplicationMaster.java:1212-1215).
                self._fail_locked(f"untracked task {task_id} crashed "
                           f"({tag})", domain)
                return
            if self.is_chief(t.job_name, t.index):
                self._fail_locked(f"chief task {task_id} failed ({tag})", domain)
            elif t.job_name in self.stop_on_failure:
                self._fail_locked(f"stop-on-failure jobtype {t.job_name}: task "
                           f"{task_id} failed ({tag})", domain)
            elif self.fail_on_worker_failure:
                self._fail_locked(f"task {task_id} failed ({tag}) and "
                           f"fail-on-worker-failure is enabled", domain)

    def restore_task(self, task_id: str, status: TaskStatus,
                     host: str = "", port: int = 0,
                     exit_code: Optional[int] = None,
                     domain: Optional[FailureDomain] = None,
                     registered: bool = False) -> None:
        """Install journal-replayed state for one task (coordinator crash
        recovery, coordinator/journal.py). Terminal states are restored
        verbatim; live states come back as RUNNING with
        ``registered=False`` — the task's last-known host/port are kept
        for the report, but the executor must RE-register inside the
        recovery grace window before it counts toward the barrier again
        (its process survived the coordinator; its liveness did not
        survive the restart)."""
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None:
                return
            t.host, t.port = host, int(port)
            if status.terminal:
                t.status = status
                t.exit_code = exit_code
                t.failure_domain = domain
                # A task that finished before the crash keeps its
                # registered-ness: the barrier must not wait on it.
                t.registered = registered
            elif status in (TaskStatus.SCHEDULED, TaskStatus.RUNNING):
                t.status = TaskStatus.RUNNING
                t.registered = False

    def mark_killed(self, task_id: str, reason: str = "") -> None:
        with self._lock:
            t = self.tasks.get(task_id)
            if t and not t.status.terminal:
                t.status = TaskStatus.KILLED
                t.exit_code = constants.EXIT_KILLED

    def _fail_locked(self, reason: str,
                     domain: Optional[FailureDomain] = None) -> None:
        if self.status == SessionStatus.RUNNING:
            self.status = SessionStatus.FAILED
            self.failure_reason = reason
        # Even when a reason already landed, keep the WORST domain seen:
        # a preempted host plus a user crash in the same epoch must not
        # retry for free.
        self.failure_domain = worst_domain(self.failure_domain, domain)

    def fail(self, reason: str,
             domain: Optional[FailureDomain] = None) -> None:
        with self._lock:
            self._fail_locked(reason, domain)

    def fail_terminal(self, reason: str,
                      domain: Optional[FailureDomain] = None) -> None:
        """Force FAILED even over a completed epoch — the journal-dead
        degrade: an outcome the coordinator can no longer durably
        record must not read as SUCCEEDED (the history would claim a
        success the write-ahead journal never saw)."""
        with self._lock:
            if self.status != SessionStatus.FAILED:
                self.status = SessionStatus.FAILED
                self.failure_reason = reason
            self.failure_domain = worst_domain(self.failure_domain,
                                               domain)

    # -- reduction --------------------------------------------------------
    def update_status(self) -> SessionStatus:
        """Reduce tracked-task states to a session status (reference
        ``TonySession.updateSessionStatus`` :276-330)."""
        with self._lock:
            if self.status != SessionStatus.RUNNING:
                return self.status
            tracked = self.tracked_tasks()
            if tracked and all(t.status.terminal for t in tracked):
                failed = [t for t in tracked
                          if t.status in (TaskStatus.FAILED, TaskStatus.KILLED)]
                if failed:
                    domain = None
                    for t in failed:
                        domain = worst_domain(domain, t.failure_domain)
                    self._fail_locked(
                        f"{len(failed)} tracked task(s) failed: "
                        + ", ".join(t.task_id for t in failed[:5]),
                        domain)
                else:
                    self.status = SessionStatus.SUCCEEDED
            return self.status

    def training_finished(self) -> bool:
        tracked = self.tracked_tasks()
        return bool(tracked) and all(t.status.terminal for t in tracked)
