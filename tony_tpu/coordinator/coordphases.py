"""Control-plane self-observation: the coordinator's own phase accounting.

PR 9 gave the *data plane* per-step phase attribution (telemetry.phase →
ring → verdict); this module turns the same machinery on the coordinator
itself, because the control plane is built of O(n)-per-tick loops — the
heartbeat scan, fsync-per-journal-record, per-beat beacon fold, prom
rendering, one global rendezvous barrier — and the PR-12 restructuring
(batched heartbeats, group-commit journal, hierarchical beacon fan-in)
must be aimed by numbers, not guesses (ROADMAP item 5; TonY's own
heartbeat/RPC design, SURVEY §1 L2–L4, marks where the reference would
have fallen over first).

Phases (disjoint by construction — see nesting below):

- ``hb_scan``            the monitor loop's heartbeat-expiry scan
- ``journal_fsync``      write-ahead journal appends (fsync included)
- ``beacon_fold``        per-beat metrics-beacon fold into the registry
- ``prom_export``        Prometheus gauge refresh + render + atomic write
- ``rpc_serve``          RPC dispatch time NOT already booked to a phase
  above (the ``_on_rpc_request`` latency hook feeds it; journal appends
  and beacon folds that happen INSIDE a dispatch are subtracted so the
  per-tick phases stay disjoint and sum-to-wall holds)
- ``rendezvous_barrier`` monitor-side barrier bookkeeping (the
  all-registered scan while the gang rendezvous is open)
- ``idle``               the monitor loop's sleep (explicit, so the duty
  cycle is readable directly from the fractions)
- ``other``              everything unattributed in the tick interval

Fold discipline — EXACTLY the step-phase ring (telemetry._fold_phases):
each monitor tick closes one attribution interval (previous tick end →
this tick end); phases recorded on RPC handler threads land in the tick
that paid for them; over-attribution (concurrent handler work exceeding
the interval) widens the wall rather than inventing a negative ``other``
— so per-tick phases ALWAYS sum to the tick wall.

Nesting/disjointness: ``phase()`` keeps a per-thread frame stack; a
nested phase's seconds are subtracted from its parent, and the total
phase-attributed seconds of a dispatch are subtracted from that
dispatch's ``rpc_serve`` booking (``note_dispatch`` reads and resets the
per-thread outermost-attribution counter right after the dispatch, in
the same handler thread).

Thread-safety: accumulation from any thread behind one lock whose
critical sections are pure dict math (tonylint lock-blocking); all
clocks monotonic (tonylint clock).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Dict, Optional

from tony_tpu.metrics import Histogram

#: canonical control-plane phase names (the coordinator verdict
#: classifier — tony_tpu/profiling/verdict.py classify_coord — reads
#: these; free-form names are accepted like the step-phase ring).
COORD_PHASES = ("hb_scan", "journal_fsync", "beacon_fold", "prom_export",
                "rpc_serve", "rendezvous_barrier", "idle")
#: synthetic bucket: tick wall no phase claimed.
OTHER_PHASE = "other"

#: fsync-latency buckets: journal appends are sub-ms on a healthy local
#: disk and tens of ms when the device stalls — the histogram must
#: resolve both regimes (the p99 behind JOURNAL_BOUND evidence).
FSYNC_BUCKETS_S = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


def histogram_quantile(snap: Dict[str, object], q: float) -> float:
    """Approximate quantile from a Histogram.snapshot() by linear
    interpolation inside the owning bucket (Prometheus
    histogram_quantile semantics; overflow clamps to the top bound)."""
    buckets = [float(b) for b in snap.get("buckets", [])]
    counts = [int(c) for c in snap.get("counts", [])]
    total = int(snap.get("count", 0) or 0)
    if total <= 0 or not buckets:
        return 0.0
    rank = q * total
    cum = 0
    lo = 0.0
    for bound, c in zip(buckets, counts):
        if cum + c >= rank and c > 0:
            return lo + (bound - lo) * (rank - cum) / c
        cum += c
        lo = bound
    return buckets[-1]


class _Frames(threading.local):
    def __init__(self):
        self.stack = []        # nested-phase seconds per open frame
        self.outer = 0.0       # outermost-phase seconds since last reset


class CoordPhases:
    """Bounded-ring per-tick phase accountant for one coordinator."""

    def __init__(self, ring_ticks: int = 256):
        self._lock = threading.Lock()
        self._frames = _Frames()
        self._acc: Dict[str, float] = {}      # since the last tick fold
        self._cum: Dict[str, float] = {}
        self._wall_cum = 0.0
        self._ticks = 0
        self._ring: Deque[dict] = collections.deque(
            maxlen=max(8, int(ring_ticks)))
        self._last_tick_end: Optional[float] = None
        # Control-plane rate counters (monotonic; rates derived over the
        # ring window from per-tick samples).
        self._beats = 0
        self._journal_records = 0
        self._journal_bytes = 0
        self._samples: Deque[tuple] = collections.deque(maxlen=64)
        self._fsync_hist = Histogram(FSYNC_BUCKETS_S)

    # -- recording (any thread) ------------------------------------------
    @contextlib.contextmanager
    def phase(self, name: str):
        """Attribute the enclosed wall time to control-plane phase
        ``name``. Re-entrant: a nested phase's time is subtracted from
        its parent so concurrent bookings stay disjoint."""
        frames = self._frames
        frames.stack.append(0.0)
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            nested = frames.stack.pop()
            if frames.stack:
                frames.stack[-1] += dt
            else:
                frames.outer += dt
            self_dt = max(0.0, dt - nested)
            with self._lock:
                self._acc[name] = self._acc.get(name, 0.0) + self_dt

    def note_dispatch(self, method: str, seconds: float) -> None:
        """RPC-dispatch booking (the ``_on_rpc_request`` hook): the
        dispatch's wall MINUS whatever its handler already attributed to
        named phases (journal appends, beacon folds) lands in
        ``rpc_serve``. Runs in the handler thread right after dispatch,
        so the per-thread outer-attribution counter belongs to exactly
        this dispatch."""
        frames = self._frames
        attributed, frames.outer = frames.outer, 0.0
        self_dt = max(0.0, float(seconds) - attributed)
        with self._lock:
            self._acc["rpc_serve"] = \
                self._acc.get("rpc_serve", 0.0) + self_dt
            if method == "task_executor_heartbeat":
                self._beats += 1

    def note_journal_append(self, n_bytes: int, seconds: float) -> None:
        """Journal observer (coordinator/journal.py): one fsync'd append.
        Books the latency into the ``journal_fsync`` phase AND the fsync
        histogram + records/bytes counters."""
        frames = self._frames
        if frames.stack:
            frames.stack[-1] += seconds
        else:
            frames.outer += seconds
        self._fsync_hist.observe(seconds)
        with self._lock:
            self._acc["journal_fsync"] = \
                self._acc.get("journal_fsync", 0.0) + float(seconds)
            self._journal_records += 1
            self._journal_bytes += int(n_bytes)

    # -- tick fold (monitor thread) --------------------------------------
    def tick_done(self) -> None:
        """Close one attribution interval: previous tick end → now.
        The first call only anchors the clock (nothing to attribute a
        wall to yet)."""
        now = time.monotonic()
        with self._lock:
            prev = self._last_tick_end
            self._last_tick_end = now
            if prev is None:
                return
            acc = dict(self._acc)
            self._acc.clear()
            wall = max(now - prev, 0.0)
            attributed = sum(acc.values())
            if attributed > wall:
                # Handler-thread work is concurrent with the monitor
                # loop and can over-attribute an interval; widen the
                # wall rather than invent a negative other bucket
                # (telemetry._fold_phases discipline).
                wall = attributed
            acc[OTHER_PHASE] = wall - attributed
            for k, v in acc.items():
                self._cum[k] = self._cum.get(k, 0.0) + v
            self._wall_cum += wall
            self._ticks += 1
            self._ring.append({"wall_s": wall, "phases": acc})
            self._samples.append((now, self._beats,
                                  self._journal_records,
                                  self._journal_bytes))

    # -- reads -----------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """Self-observation snapshot: cumulative + recent-ring phase
        seconds (sum EXACTLY equals the wall — ``other`` holds the
        unattributed rest), tick duration, and the control-plane rates.
        {} before the first folded tick."""
        with self._lock:
            if not self._ticks:
                return {}
            out: Dict[str, object] = {
                "ticks": float(self._ticks),
                "wall_s": self._wall_cum,
                "cum": dict(self._cum),
                "beats_total": self._beats,
                "journal_records_total": self._journal_records,
                "journal_bytes_total": self._journal_bytes,
            }
            n = len(self._ring)
            if n:
                recent: Dict[str, float] = {}
                rwall = 0.0
                # The tick interval includes the monitor sleep; the
                # ACTIVE tick duration (what grows with gang width) is
                # the attributed non-idle, non-other work per tick.
                active = 0.0
                for rec in self._ring:
                    rwall += rec["wall_s"]
                    for k, v in rec["phases"].items():
                        recent[k] = recent.get(k, 0.0) + v
                        if k not in (OTHER_PHASE, "idle"):
                            active += v
                out["recent"] = {k: v / n for k, v in recent.items()}
                out["recent_wall_s"] = rwall / n
                out["recent_ticks"] = float(n)
                out["tick_active_s"] = active / n
            if len(self._samples) >= 2:
                t0, b0, r0, y0 = self._samples[0]
                t1, b1, r1, y1 = self._samples[-1]
                window = max(t1 - t0, 1e-9)
                out["beats_per_sec"] = (b1 - b0) / window
                out["journal_records_per_sec"] = (r1 - r0) / window
                out["journal_bytes_per_sec"] = (y1 - y0) / window
        snap = self._fsync_hist.snapshot()
        out["fsync"] = snap
        out["journal_fsync_p99_s"] = histogram_quantile(snap, 0.99)
        return out

    def fractions(self) -> Dict[str, float]:
        """Recent-ring phase fractions of the tick wall (the classifier
        input — tony_tpu/profiling/verdict.py classify_coord)."""
        with self._lock:
            n = len(self._ring)
            if not n:
                return {}
            recent: Dict[str, float] = {}
            rwall = 0.0
            for rec in self._ring:
                rwall += rec["wall_s"]
                for k, v in rec["phases"].items():
                    recent[k] = recent.get(k, 0.0) + v
        if rwall <= 0:
            return {}
        return {k: v / rwall for k, v in recent.items()}
