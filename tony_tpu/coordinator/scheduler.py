"""Gang scheduler: stage/DAG-ordered jobtype launch.

Reference model: ``TaskScheduler.java`` (179 LoC) — builds a dependency graph
from ``tony.X.depends-on`` plus the prepare→training stage edge (:75-86),
validates acyclicity (``isDAG`` :142-178), requests containers for ready jobs
(``scheduleJob`` :93), and unlocks dependents as tasks of a jobtype complete
(``registerDependencyCompleted`` :118-140).

The TPU difference: instead of asking YARN for containers and matching
allocations back by priority (``TonySession.getAndInitMatchingTaskByPriority``
:208), the scheduler hands whole ready jobtypes to a backend which launches
them as gangs — a TPU slice lease is all-or-nothing, so partial-allocation
matching has no equivalent here (SURVEY.md §7 hard part (a)).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Set

from tony_tpu.conf.config import JobType, TonyTpuConfig
from tony_tpu.conf import keys as K


class SchedulerError(RuntimeError):
    pass


class GangScheduler:
    def __init__(self, conf: TonyTpuConfig,
                 launch_job: Callable[[str], None]):
        """launch_job(jobtype) must launch all instances of the jobtype."""
        self.conf = conf
        self.jobs: Dict[str, JobType] = conf.job_types()
        self._launch_job = launch_job
        self._lock = threading.Lock()
        self._deps: Dict[str, Set[str]] = {}
        self._scheduled: Set[str] = set()
        self._completed: Set[str] = set()
        self._build_graph()
        if not self._is_dag():
            raise SchedulerError(
                "jobtype dependency graph has a cycle "
                "(reference TaskScheduler.isDAG :142-178)")

    def _build_graph(self) -> None:
        """depends-on edges + prepare-stage → training-stage edges
        (reference TaskScheduler.java:75-86, Utils.java:372-406)."""
        prepare = [j for j in self.conf.get_list(K.APPLICATION_PREPARE_STAGE)
                   if j in self.jobs]
        training = [j for j in self.conf.get_list(K.APPLICATION_TRAINING_STAGE)
                    if j in self.jobs]
        for name, job in self.jobs.items():
            deps = {d for d in job.depends_on if d in self.jobs}
            if name in training:
                deps.update(prepare)
            self._deps[name] = deps

    def _is_dag(self) -> bool:
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in self._deps}

        def visit(n: str) -> bool:
            color[n] = GRAY
            for d in self._deps[n]:
                if color[d] == GRAY:
                    return False
                if color[d] == WHITE and not visit(d):
                    return False
            color[n] = BLACK
            return True

        for n in self._deps:
            if color[n] == WHITE and not visit(n):
                return False
        return True

    # -- scheduling -------------------------------------------------------
    def ready_jobs(self) -> List[str]:
        with self._lock:
            return sorted(
                n for n in self.jobs
                if n not in self._scheduled
                and self._deps[n] <= self._completed)

    def schedule_ready(self) -> List[str]:
        """Launch every jobtype whose dependencies are satisfied (reference
        ``scheduleTasks`` :55 / ``scheduleJob`` :93)."""
        launched = []
        for name in self.ready_jobs():
            with self._lock:
                if name in self._scheduled:
                    continue
                self._scheduled.add(name)
            self._launch_job(name)
            launched.append(name)
        return launched

    def restore(self, scheduled, completed) -> None:
        """Install journal-replayed DAG progress (coordinator crash
        recovery): jobtypes already handed to the backend must not be
        launched again over their surviving executors, and completed
        dependencies must keep their dependents unlocked. A later
        ``schedule_ready`` then launches exactly the jobtypes the crash
        interrupted before their launch record hit the journal."""
        with self._lock:
            self._scheduled |= {j for j in scheduled if j in self.jobs}
            self._completed |= {j for j in completed if j in self.jobs}

    def register_job_completed(self, job_name: str) -> List[str]:
        """All tasks of `job_name` finished successfully → unlock dependents
        (reference ``registerDependencyCompleted`` :118-140)."""
        with self._lock:
            self._completed.add(job_name)
        return self.schedule_ready()

    @property
    def all_scheduled(self) -> bool:
        with self._lock:
            return self._scheduled == set(self.jobs)

    def dependency_check_passed(self, failed_job: str) -> bool:
        """False if `failed_job` blocks a jobtype that has not been launched
        yet — the DAG can't make progress (reference ``dependencyCheckPassed``
        :43; the AM monitor fails the job on this,
        ``ApplicationMaster.java:581-650``). Already-scheduled dependents got
        their launch before the failure and are judged on their own merits."""
        with self._lock:
            return all(failed_job not in deps or name in self._scheduled
                       for name, deps in self._deps.items())
