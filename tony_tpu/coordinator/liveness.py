"""Progress-based liveness: hang detection and straggler policing.

The heartbeat monitor (coordinator.py ``_check_heartbeats``) proves the
*executor* is alive — nothing more. A user process wedged in a deadlocked
collective, a stuck data loader, or a NaN spin keeps heartbeating through
its executor forever while the whole gang stalls (in-graph gang execution
means one hung replica stalls every replica — TF-Replicator, PAPERS.md).
The progress signal already exists: the user process's telemetry reporter
publishes ``steps_completed`` (tony_tpu/telemetry.py) and the executor
piggybacks it on every heartbeat as a progress beacon. This module is the
coordinator-side consumer: per-task progress state plus two policies on
top of it.

**Hang detection** (``tony.task.progress-timeout-s``, 0 = off): a task is
armed the first time a beacon carries a step counter; from then on, a
task whose counter stops advancing for longer than the deadline is
declared HUNG. The verdict is staged — declare (TASK_HUNG event + a
dump directive rides the next heartbeat response so the executor signals
the user process group and its pre-registered ``faulthandler`` handler
dumps all-thread stacks into the task log), a dump grace, then the kill
(TERM→grace→KILL, INFRA_TRANSIENT through the ordinary retry-epoch
machinery). Warmup-aware by construction: an UNARMED task (still
compiling, restoring, or simply not instrumented) is never subject to
the deadline — uninstrumented tasks degrade to heartbeat-only liveness
with a one-time warning event after ``tony.task.progress-warmup-s``,
never a false kill.

**Straggler policing** (``tony.task.straggler-fraction``): per-task step
rates over a sliding window, compared against the gang (jobtype) median.
A task sustained below ``fraction × median`` for
``tony.task.straggler-window-s`` emits TASK_STRAGGLER with its rate vs.
the median; with ``tony.task.straggler-restart`` (off by default) it is
proactively killed into an INFRA_TRANSIENT retry. A 1-task gang can
never straggle (its own rate IS the median).

Recovery integration: the coordinator journals step counters (throttled
— see ``PROGRESS_JOURNAL_MIN_INTERVAL_S``) and a ``--recover`` replay
seeds ``track(steps_hint=...)``, which re-arms the task with a FRESH
deadline — the outage must not expire deadlines the moment the
coordinator comes back, but a hang that spans the crash is still caught
one full timeout later.
"""

from __future__ import annotations

import collections
import dataclasses
import statistics
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from tony_tpu.conf import keys as K

#: Floor between two journalled progress records for one task: the journal
#: is fsync'd and control-plane-rate; step counters must not turn it into
#: a per-step hot path.
PROGRESS_JOURNAL_MIN_INTERVAL_S = 10.0

#: poll() action kinds, in the order a task moves through them.
WARN_UNINSTRUMENTED = "uninstrumented"
HUNG = "hung"
HANG_KILL = "hang_kill"
STRAGGLER = "straggler"
STRAGGLER_KILL = "straggler_kill"


@dataclasses.dataclass
class _TaskProgress:
    job_name: str
    tracked_at: float
    steps: float = -1.0
    last_advance: float = 0.0
    armed: bool = False               # a beacon carried a step counter
    warned: bool = False              # uninstrumented warning emitted
    hung_at: float = 0.0              # 0 = not currently declared hung
    dump_pending: bool = False        # directive queued for the heartbeat
    dump_sent: bool = False
    killed: bool = False              # kill action already handed out
    samples: Deque[Tuple[float, float]] = dataclasses.field(
        default_factory=collections.deque)
    below_since: float = 0.0          # straggler condition start, 0 = above
    straggler_flagged: bool = False   # event emitted for this episode


@dataclasses.dataclass
class Action:
    """One policy verdict for the coordinator's monitor loop to act on."""

    kind: str
    task_id: str
    info: Dict[str, object]


class ProgressTracker:
    """Per-task progress state machine; thread-safe (beacons arrive on RPC
    handler threads, policy runs on the coordinator monitor loop)."""

    def __init__(self, conf, now_fn: Callable[[], float] = time.monotonic):
        self._now = now_fn
        self.timeout_s = float(conf.get_int(K.TASK_PROGRESS_TIMEOUT_S, 0))
        self.warmup_s = float(conf.get_int(K.TASK_PROGRESS_WARMUP_S, 300))
        self.dump_grace_s = float(conf.get_int(K.TASK_HANG_DUMP_GRACE_S, 5))
        self.straggler_fraction = float(
            conf.get(K.TASK_STRAGGLER_FRACTION, 0.0) or 0.0)
        self.straggler_window_s = float(
            conf.get_int(K.TASK_STRAGGLER_WINDOW_S, 60))
        self.straggler_restart = conf.get_bool(K.TASK_STRAGGLER_RESTART)
        self._tasks: Dict[str, _TaskProgress] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        """Any progress policy configured at all? (When False the tracker
        still records beacons for the status surfaces, but never warns,
        declares, or kills.)"""
        return bool(self.timeout_s or self.straggler_fraction)

    # -- bookkeeping ------------------------------------------------------
    def track(self, task_id: str, job_name: str,
              steps_hint: Optional[float] = None) -> None:
        """Start (or restart) tracking a task — called at registration and
        at post-recovery re-registration. ``steps_hint`` is the journal-
        replayed counter: the task comes back ARMED but with a fresh
        deadline, so a coordinator outage never expires a deadline on
        re-adoption — while a hang that began before the crash still
        trips one full timeout later."""
        now = self._now()
        with self._lock:
            tp = _TaskProgress(job_name=job_name, tracked_at=now)
            if steps_hint is not None and steps_hint >= 0:
                tp.armed = True
                tp.steps = float(steps_hint)
                tp.last_advance = now
            self._tasks[task_id] = tp

    def forget(self, task_id: str) -> None:
        """Task reached a terminal state: drop it from every policy (a
        finished fast task must not drag the gang median around)."""
        with self._lock:
            self._tasks.pop(task_id, None)

    def reset(self) -> None:
        """New retry epoch: all progress state belongs to the old gang."""
        with self._lock:
            self._tasks.clear()

    # -- beacon intake ----------------------------------------------------
    def observe(self, task_id: str,
                progress: Optional[dict]) -> bool:
        """Fold one heartbeat's progress beacon in. Returns True iff the
        step counter ADVANCED (the journal-throttle signal). ``progress``
        is ``{"steps": float, "age_s": float}`` or None from tasks with no
        instrumentation (those stay unarmed: heartbeat-only liveness)."""
        now = self._now()
        with self._lock:
            tp = self._tasks.get(task_id)
            if tp is None or tp.killed:
                return False
            if not isinstance(progress, dict) or "steps" not in progress:
                return False
            try:
                steps = float(progress["steps"])
                age_s = max(0.0, float(progress.get("age_s", 0.0) or 0.0))
            except (TypeError, ValueError):
                return False
            advanced = False
            if not tp.armed:
                # First sighting arms the deadline NOW — compile/restore
                # time before this point was never on the clock.
                tp.armed = True
                tp.steps = steps
                tp.last_advance = now
                advanced = True
            elif steps != tp.steps:
                # Any change counts as an advance ('!=' not '>': a retry
                # or executor restart resets the counter downward and that
                # is a live, progressing task). The executor's own stall
                # age backdates the advance to when IT saw the counter
                # move — clock-skew-free, it is a duration — but never
                # earlier than what we already knew (a recovery grace must
                # not be erased by a huge reported age).
                if steps < tp.steps:
                    # Counter reset (user process restarted inside the
                    # task, epoch-stale metrics file overwritten): the
                    # old samples would give the rate window a negative
                    # slope — clamped to 0, a guaranteed false straggler.
                    # Start the window over.
                    tp.samples.clear()
                    tp.below_since = 0.0
                tp.steps = steps
                tp.last_advance = max(tp.last_advance, now - age_s)
                advanced = True
                if tp.hung_at and not tp.killed:
                    # Progress resumed inside the dump grace: cancel the
                    # verdict (the dump, if delivered, is free forensics).
                    tp.hung_at = 0.0
                    tp.dump_pending = False
                    tp.dump_sent = False
            tp.samples.append((now, steps))
            cutoff = now - max(2.0 * self.straggler_window_s, 10.0)
            while tp.samples and tp.samples[0][0] < cutoff:
                tp.samples.popleft()
            return advanced

    def should_dump(self, task_id: str) -> bool:
        """One-shot dump directive for the heartbeat response: True exactly
        once per hang episode, on the first heartbeat after declaration."""
        with self._lock:
            tp = self._tasks.get(task_id)
            if tp is None or not tp.dump_pending or tp.dump_sent:
                return False
            tp.dump_sent = True
            return True

    # -- policy -----------------------------------------------------------
    def poll(self) -> List[Action]:
        """Run both policies; called from the coordinator monitor loop.
        Each returned Action is emitted at most once per episode (hang
        kills and straggler kills exactly once per task life)."""
        now = self._now()
        out: List[Action] = []
        with self._lock:
            if not self.enabled:
                return out
            rates = self._rates_locked(now)
            medians = self._gang_medians_locked(rates)
            for task_id, tp in self._tasks.items():
                if tp.killed:
                    continue
                if not tp.armed:
                    if not tp.warned and \
                            now - tp.tracked_at > self.warmup_s:
                        tp.warned = True
                        out.append(Action(WARN_UNINSTRUMENTED, task_id, {
                            "warmup_s": self.warmup_s}))
                    continue
                stalled_s = now - tp.last_advance
                if self.timeout_s and not tp.hung_at \
                        and stalled_s > self.timeout_s:
                    tp.hung_at = now
                    tp.dump_pending = True
                    out.append(Action(HUNG, task_id, {
                        "steps": tp.steps, "stalled_s": stalled_s,
                        "timeout_s": self.timeout_s}))
                if tp.hung_at:
                    if now - tp.hung_at >= self.dump_grace_s:
                        tp.killed = True
                        out.append(Action(HANG_KILL, task_id, {
                            "steps": tp.steps,
                            "stalled_s": now - tp.last_advance,
                            "timeout_s": self.timeout_s,
                            "dump_delivered": tp.dump_sent}))
                    continue      # a hung task is past straggler policing
                self._police_straggler_locked(
                    out, task_id, tp, now, rates, medians)
        return out

    def _police_straggler_locked(self, out: List[Action], task_id: str,
                                 tp: _TaskProgress, now: float,
                                 rates: Dict[str, float],
                                 medians: Dict[str, float]) -> None:
        if not self.straggler_fraction:
            return
        rate = rates.get(task_id)
        median = medians.get(tp.job_name)
        # A 1-task gang's median IS its own rate — never below a
        # fraction < 1 of itself; with both at 0 the strict '<' holds
        # the line (0 < 0 is False). Median needs at least the task's
        # own rate to exist.
        if rate is None or median is None or \
                rate >= self.straggler_fraction * median:
            tp.below_since = 0.0
            tp.straggler_flagged = False
            return
        if not tp.below_since:
            tp.below_since = now
        if now - tp.below_since < self.straggler_window_s:
            return
        info = {"rate_steps_per_s": rate, "median_steps_per_s": median,
                "fraction": self.straggler_fraction,
                "window_s": self.straggler_window_s, "steps": tp.steps}
        if not tp.straggler_flagged:
            tp.straggler_flagged = True
            out.append(Action(STRAGGLER, task_id, dict(info)))
        if self.straggler_restart:
            tp.killed = True
            out.append(Action(STRAGGLER_KILL, task_id, dict(info)))

    def _rates_locked(self, now: float) -> Dict[str, float]:
        """Step rate per armed task over the sliding window; absent when
        the sample span is too short to mean anything yet."""
        rates: Dict[str, float] = {}
        for task_id, tp in self._tasks.items():
            if not tp.armed or tp.killed or len(tp.samples) < 2:
                continue
            t0, s0 = tp.samples[0]
            t1, s1 = tp.samples[-1]
            if t1 - t0 < self.straggler_window_s / 2.0:
                continue
            rates[task_id] = max(0.0, (s1 - s0) / (t1 - t0))
        return rates

    def _gang_medians_locked(
            self, rates: Dict[str, float]) -> Dict[str, float]:
        by_job: Dict[str, List[float]] = {}
        for task_id, rate in rates.items():
            tp = self._tasks.get(task_id)
            if tp is not None and not tp.hung_at:
                by_job.setdefault(tp.job_name, []).append(rate)
        return {job: statistics.median(rs) for job, rs in by_job.items()
                if rs}

    # -- status surfaces --------------------------------------------------
    def snapshot(self, task_id: str) -> Optional[Dict[str, object]]:
        """Progress state for the application report / CLI / portal; None
        for untracked tasks."""
        now = self._now()
        with self._lock:
            tp = self._tasks.get(task_id)
            if tp is None:
                return None
            if not tp.armed:
                if not self.enabled:
                    # No policy configured: an unarmed task has nothing
                    # worth a status column ("warmup" would imply a
                    # deadline that does not exist).
                    return None
                state = "heartbeat-only" if tp.warned else "warmup"
                return {"state": state}
            out: Dict[str, object] = {
                "state": "hung" if tp.hung_at else (
                    "straggler" if tp.straggler_flagged else "ok"),
                "steps": tp.steps,
                "stalled_s": round(now - tp.last_advance, 3),
            }
            rate = self._rates_locked(now).get(task_id)
            if rate is not None:
                out["rate_steps_per_s"] = round(rate, 4)
            return out
