"""Mixture-of-Experts transformer with expert parallelism over the ``ep``
mesh axis.

No reference analogue — TonY has no expert/model parallelism anywhere
(SURVEY.md §2.3, verified absent); this is TPU-first new work.

Design (GShard/Switch-style dense dispatch — the TPU-idiomatic formulation):
- Expert FFN weights are stacked ``[n_experts, ...]`` with logical axis
  ``expert → ep``; the router is a small replicated Dense.
- Dispatch/combine are **einsums against one-hot dispatch tensors**, not
  gather/scatter — dense MXU work instead of dynamic indexing the TPU
  can't tile (pallas_guide.md: avoid data-dependent shapes under jit;
  capacity-factor padding keeps every shape static).
- The expert exchange is an explicit ``lax.all_to_all`` pair inside a
  *partial-manual* ``shard_map`` over the ``ep`` axis only (dp/fsdp/tp
  stay auto): each ep shard routes its token group locally (GShard
  "groups" = ep shards, per-group capacity), ships expert-major slices to
  the expert owners over ICI, FFNs its resident experts, and ships results
  back. Token tensors never pass through an all-gather.
- Top-k routing (k configurable) with per-group per-expert capacity
  ``c = ceil(k·T_group/E · capacity_factor)``; tokens over capacity are
  dropped (their residual path passes through — standard Switch behaviour).
- Aux load-balancing loss (Switch eq. 4: E · Σ_e fraction_e · prob_e) is
  returned alongside the logits so the train loss can add it.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tony_tpu import compat
from tony_tpu.models.transformer import (Attention, RMSNorm,
                                         TransformerConfig)


@dataclasses.dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @classmethod
    def tiny_moe(cls, **kw) -> "MoEConfig":
        defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, mlp_dim=128, max_seq_len=128,
                        dtype=jnp.float32, remat=False, n_experts=4,
                        top_k=2)
        defaults.update(kw)
        return cls(**defaults)


def _routed_ffn_group(cfg: MoEConfig, xt: jax.Array, probs: jax.Array,
                      w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array,
                      n_ep: int) -> jax.Array:
    """One routing group's expert FFN. ``xt``/``probs`` are the group's
    [T_g, D]/[T_g, E] slices; ``w_*`` are the E/n_ep resident experts'
    weights. Runs per-shard under shard_map when n_ep > 1."""
    t, d = xt.shape
    e, k = cfg.n_experts, cfg.top_k
    capacity = max(k, int(math.ceil(k * t / e * cfg.capacity_factor)))

    gate_vals, gate_idx = jax.lax.top_k(probs, k)          # [T_g, k]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # Position-in-expert with slot priority: slot 0 of every token beats
    # slot 1, earlier tokens beat later ones (deterministic, static).
    dispatch = jnp.zeros((t, e, capacity), cfg.dtype)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    offset = jnp.zeros((e,), jnp.int32)
    for slot in range(k):
        onehot = jax.nn.one_hot(gate_idx[:, slot], e, dtype=jnp.int32)
        loc = jnp.cumsum(onehot, axis=0) - 1 + offset[None, :]
        offset = offset + jnp.sum(onehot, axis=0)
        keep = (onehot > 0) & (loc < capacity)             # [T_g, E]
        loc_oh = jax.nn.one_hot(loc, capacity, dtype=jnp.float32)
        sel = keep[..., None] * loc_oh                     # [T_g, E, C]
        dispatch = dispatch + sel.astype(cfg.dtype)
        combine = combine + gate_vals[:, slot, None, None] * sel

    expert_in = jnp.einsum("tec,td->ecd", dispatch,
                           xt.astype(cfg.dtype))           # [E, c, D]
    if n_ep > 1:
        # Ship each expert's slots to its owner: [E, c, D] → split experts
        # into n_ep groups, concat received slot-chunks → [E/n_ep, n_ep·c, D].
        expert_in = jax.lax.all_to_all(expert_in, EP_AXIS, split_axis=0,
                                       concat_axis=1, tiled=True)
    h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate)) \
        * jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)
    if n_ep > 1:
        # Ship results back slot-major: [E/n_ep, n_ep·c, D] → [E, c, D].
        expert_out = jax.lax.all_to_all(expert_out, EP_AXIS, split_axis=1,
                                        concat_axis=0, tiled=True)
    return jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), expert_out)


EP_AXIS = "ep"


class MoEMLP(nn.Module):
    """Top-k routed expert FFN (gated-silu experts, like the dense MLP)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        b, s, d = x.shape
        t = b * s
        e = cfg.n_experts

        xt = x.reshape(t, d)
        # Router in f32: stability matters more than speed for a [d, E] dot.
        router = nn.Dense(
            e, use_bias=False, dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name="router",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "expert_logits")))
        probs = jax.nn.softmax(router(xt.astype(jnp.float32)), axis=-1)

        def w(name, shape, axes):
            return self.param(name, nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), axes), shape,
                cfg.param_dtype).astype(cfg.dtype)

        w_gate = w("gate", (e, d, cfg.mlp_dim), ("expert", "embed", "mlp"))
        w_up = w("up", (e, d, cfg.mlp_dim), ("expert", "embed", "mlp"))
        w_down = w("down", (e, cfg.mlp_dim, d), ("expert", "mlp", "embed"))

        n_ep = compat.mesh_axis_size(EP_AXIS)
        if n_ep > 1:
            from jax.sharding import PartitionSpec as P

            if t % n_ep or e % n_ep:
                raise ValueError(
                    f"tokens ({t}) and experts ({e}) must divide the ep "
                    f"axis ({n_ep})")
            out = compat.partial_shard_map(
                functools.partial(_routed_ffn_group, cfg, n_ep=n_ep),
                EP_AXIS,
                in_specs=(P(EP_AXIS), P(EP_AXIS), P(EP_AXIS), P(EP_AXIS),
                          P(EP_AXIS)),
                out_specs=P(EP_AXIS),
            )(xt, probs, w_gate, w_up, w_down)
        else:
            out = _routed_ffn_group(cfg, xt, probs, w_gate, w_up, w_down,
                                    n_ep=1)
        out = out.reshape(b, s, d)

        # Switch aux loss: E · Σ_e (token fraction to e) · (mean router prob).
        gate_idx = jnp.argmax(probs, axis=-1)
        token_frac = jnp.mean(
            jax.nn.one_hot(gate_idx, e, dtype=jnp.float32), axis=0)
        prob_frac = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(token_frac * prob_frac)
        return out, aux


class MoEBlock(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attn_norm")(x),
            positions)
        mlp_out, aux = MoEMLP(cfg, name="moe")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="mlp_norm")(h))
        out = h + mlp_out
        return nn.with_logical_constraint(out, ("batch", "seq", "embed")), aux


class MoETransformer(nn.Module):
    """Causal LM with routed-expert FFNs: tokens → (logits, aux_loss)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, positions=None):
        cfg = self.cfg
        if positions is None:
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            positions = jnp.broadcast_to(pos[None, :], tokens.shape)
        emb = self.param(
            "embedding", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = emb[tokens].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        block = MoEBlock
        if cfg.remat:
            # prevent_cse=True: layers are a Python loop, and with False
            # XLA CSEs the recomputation away and silently un-remats the
            # model (same defect found and measured in
            # models/transformer.py; False is only sound inside
            # scan/while bodies — see parallel/pipeline.py for the
            # legitimate case). remat_policy is honoured like the dense
            # transformer's.
            import jax as _jax

            policy = (getattr(_jax.checkpoint_policies, cfg.remat_policy)
                      if cfg.remat_policy else None)
            block = nn.remat(MoEBlock, prevent_cse=True, policy=policy)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            x, aux = block(cfg, name=f"layer_{i}")(x, positions)
            aux_total = aux_total + aux
        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="final_norm")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name="lm_head",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")))(
                    x.astype(jnp.float32))
        return logits, aux_total / cfg.n_layers


def moe_lm_loss(model_out, tokens, aux_weight: float) -> jax.Array:
    from tony_tpu.models.transformer import causal_lm_loss

    logits, aux = model_out
    return causal_lm_loss(logits, tokens) + aux_weight * aux


def dryrun_ep_step(devices, ep: int) -> float:
    """One FULL MoE train step (fwd + bwd + optimizer update) on an ep≥2
    mesh, asserting the compiled program dispatches experts via all_to_all.
    Used by ``__graft_entry__.dryrun_multichip``; returns the loss."""
    import optax

    from tony_tpu.parallel import MeshSpec, build_mesh, init_sharded_state
    from tony_tpu.parallel.sharding import DEFAULT_RULES

    n = len(devices)
    mesh = build_mesh(MeshSpec(dp=n // ep, ep=ep), devices=devices)
    cfg = MoEConfig.tiny_moe()
    model = MoETransformer(cfg)
    tokens = jax.random.randint(jax.random.key(0), (2 * (n // ep), 32), 0,
                                cfg.vocab_size)
    state, _sh = init_sharded_state(model, tokens, optax.adam(1e-3), mesh)

    def loss_fn(p):
        with nn.logical_axis_rules(list(DEFAULT_RULES)):
            return moe_lm_loss(model.apply({"params": p}, tokens), tokens,
                               cfg.aux_loss_weight)

    def step(state):
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), loss

    # set_mesh binds the abstract mesh MoEMLP reads to pick the ep path;
    # without it n_ep resolves to 1 and the dry run would only validate the
    # replicated fallback (advisor finding, round 2).
    with compat.set_mesh(mesh):
        compiled = jax.jit(step).lower(state).compile()
        hlo = compiled.as_text()
        assert "all-to-all" in hlo, \
            "ep dryrun compiled WITHOUT all_to_all expert dispatch"
        state, loss = compiled(state)
    loss = float(loss)
    assert jnp.isfinite(loss), f"ep MoE train step diverged: {loss}"
    return loss
