"""Model zoo: the workloads the reference ran as opaque user scripts.

The reference shipped example models as user Python (MNIST TF/PyTorch, MXNet
linear regression — ``tony-examples/*``, SURVEY.md §2.2) and never looked
inside them. Here the flagship models are part of the framework, built
TPU-first: flax modules annotated with logical axes so the parallel library
can shard them onto any mesh, bf16 compute, flash/ring attention from
`tony_tpu.ops`.
"""

from tony_tpu.models.transformer import (  # noqa: F401
    Transformer, TransformerConfig, causal_lm_loss, chunked_causal_lm_loss,
)
from tony_tpu.models.mlp import MnistMLP  # noqa: F401
from tony_tpu.models.resnet import ResNet, ResNetConfig  # noqa: F401
