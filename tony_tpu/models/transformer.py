"""Decoder-only transformer (llama-family architecture), TPU-first.

The flagship model for the Llama-3-8B-on-TPU target (BASELINE.json
"new JAXRuntime: Llama-3-8B multi-host SPMD"). Design choices map straight
onto TPU hardware:

- every weight carries logical axes (``embed``/``mlp``/``heads``/``vocab``)
  so `tony_tpu.parallel` can lay it out on any dp/fsdp/tp/sp mesh;
- bf16 activations (MXU-native), f32 params and softmax statistics;
- attention is pluggable: Pallas flash kernel (default), ring attention for
  sequence-parallel long context, Ulysses, or the XLA reference;
- static shapes and `remat`-friendly block structure (scan over layers is
  deliberately NOT used so pipeline stages can slice layers later).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tony_tpu.ops.attention import flash_attention, reference_attention
from tony_tpu.ops.quant import QDense
from tony_tpu.ops.ring import ring_attention
from tony_tpu.ops.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16          # activations
    param_dtype: jnp.dtype = jnp.float32
    attn_impl: str = "flash"                 # flash | ring | ulysses | xla
    remat: bool = True
    # Name of a jax.checkpoint_policies policy for remat, e.g.
    # "dots_with_no_batch_dims_saveable" (save matmul outputs, recompute
    # only cheap elementwise/norm ops — ~the full-remat memory win at a
    # fraction of the recompute FLOPs). None → full remat of each block.
    # NB (r5, tunneled-v5e rig): dot-saving policies crash the remote
    # tpu_compile_helper (HTTP 500) on this environment; the layer-
    # granular knob below is the selective lever that works everywhere.
    remat_policy: Optional[str] = None
    # Layer-granular selective remat (layers are a Python loop, so the
    # choice is per-layer): with remat on and N >= 2, every Nth block
    # runs UN-remat'd — its activations stay live (1/N of the no-remat
    # footprint) and its recompute disappears (1/N of the remat FLOPs
    # tax). 0/1 = remat every block (the default, max memory savings).
    remat_skip_every: int = 0
    # Flash kernel tile sizes (see ops/attention.py block sweep notes).
    attn_block_q: int = 1024
    attn_block_k: int = 1024
    tie_embeddings: bool = False
    # LM-head matmul dtype; None → activation dtype (bf16 on TPU: the
    # [dim, vocab] projection is ~20% of model FLOPs and f32 runs at half
    # the MXU rate — loss softmax stays f32 downstream either way).
    lm_head_dtype: Optional[jnp.dtype] = None
    # Opt-in quantized matmul path for the attention/MLP projections
    # (tony.train.matmul-dtype): "int8" | "fp8_e4m3" | None. Forward-only
    # symmetric per-channel quantization (ops/quant.py) on wq/wk/wv/wo and
    # gate/up/down; the embedding and LM head stay in bf16/f32 (they set
    # the loss scale). None keeps the exact nn.Dense path — bitwise
    # identical to the pre-quantization model. An unsupported backend
    # degrades to bf16 with a one-time beacon warning.
    matmul_dtype: Optional[str] = None

    @classmethod
    def llama3_8b(cls, **kw) -> "TransformerConfig":
        """Llama-3-8B geometry (public: 32L, 4096d, 32h/8kv, 14336 mlp,
        128k vocab)."""
        return cls(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, mlp_dim=14336, rope_theta=500000.0, **kw)

    @classmethod
    def tiny(cls, **kw) -> "TransformerConfig":
        """CI-sized config for the fake mesh (SURVEY.md §4 test strategy)."""
        defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, mlp_dim=128, max_seq_len=128,
                        dtype=jnp.float32, remat=False)
        defaults.update(kw)
        return cls(**defaults)


def _dense(cfg: TransformerConfig, feats: int, axes, name: str) -> nn.Module:
    init = nn.with_logical_partitioning(nn.initializers.lecun_normal(), axes)
    if cfg.matmul_dtype:
        # Same param name ("kernel"), path and init as nn.Dense, so the
        # knob flips freely across checkpoints of the same model.
        return QDense(features=feats, dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype, name=name,
                      kernel_init=init, matmul_dtype=cfg.matmul_dtype)
    return nn.Dense(
        feats, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        name=name, kernel_init=init)


def _sp_offset() -> jax.Array:
    """Shard index on the sp axis, or 0 when not under shard_map (init /
    single-shard apply trace the model outside any mesh axis context). A
    shard_map with a differently-named sequence axis raises instead of
    silently restarting positions at 0 (see ops.ring.bound_axis_size)."""
    from tony_tpu.ops.ring import bound_axis_size

    if bound_axis_size("sp") is None:
        return jnp.zeros((), jnp.int32)
    return jax.lax.axis_index("sp")


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding on [B, S, H, D]; f32 trig, cast back."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, :, None, None].astype(jnp.float32) \
        * freqs[None, None, None, :]                    # [B, S, 1, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones,
                                                  ("norm",)),
            (x.shape[-1],), self.param_dtype)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        y = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        return (y * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        b, s, _ = x.shape
        # Plain Dense with a fused (heads·head_dim) output: the fused dim is
        # heads-major, so sharding it over tp == sharding heads over tp.
        # (DenseGeneral flattens multi-dim kernels before calling
        # kernel_init, which breaks 3-axis logical metadata.)
        q = _dense(cfg, cfg.n_heads * head_dim, ("embed", "heads"), "wq")(
            x).reshape(b, s, cfg.n_heads, head_dim)
        k = _dense(cfg, cfg.n_kv_heads * head_dim, ("embed", "kv_heads"),
                   "wk")(x).reshape(b, s, cfg.n_kv_heads, head_dim)
        v = _dense(cfg, cfg.n_kv_heads * head_dim, ("embed", "kv_heads"),
                   "wv")(x).reshape(b, s, cfg.n_kv_heads, head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "kv"))
        k = nn.with_logical_constraint(k, ("batch", "seq", "kv_heads", "kv"))
        v = nn.with_logical_constraint(v, ("batch", "seq", "kv_heads", "kv"))

        if cfg.attn_impl == "flash":
            o = flash_attention(q, k, v, causal=True,
                                block_q=cfg.attn_block_q,
                                block_k=cfg.attn_block_k)
        elif cfg.attn_impl == "xla":
            g = cfg.n_heads // cfg.n_kv_heads
            o = reference_attention(q, jnp.repeat(k, g, axis=2),
                                    jnp.repeat(v, g, axis=2), causal=True)
        elif cfg.attn_impl == "ring":
            # GQA-native: K/V ride the ring at kv-head width (no repeat).
            o = ring_attention(q, k, v, axis_name="sp", causal=True,
                               block_q=cfg.attn_block_q,
                               block_k=cfg.attn_block_k)
        elif cfg.attn_impl == "ulysses":
            o = ulysses_attention(q, k, v, axis_name="sp", causal=True,
                                  block_q=cfg.attn_block_q,
                                  block_k=cfg.attn_block_k)
        else:
            raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")
        o = nn.with_logical_constraint(o, ("batch", "seq", "heads", "kv"))
        o = o.reshape(b, s, cfg.n_heads * head_dim)
        return _dense(cfg, cfg.dim, ("heads", "embed"), "wo")(o)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = _dense(cfg, cfg.mlp_dim, ("embed", "mlp"), "gate")(x)
        up = _dense(cfg, cfg.mlp_dim, ("embed", "mlp"), "up")(x)
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        return _dense(cfg, cfg.dim, ("mlp", "embed"), "down")(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attn_norm")(x),
            positions)
        out = h + MLP(cfg, name="mlp")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="mlp_norm")(h))
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))


class Transformer(nn.Module):
    """Causal LM: tokens [B, S] int32 → logits [B, S, vocab]."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden=False):
        """``return_hidden=True`` skips the LM head and returns the
        final-norm hidden states [B, S, D] — pair with
        ``chunked_causal_lm_loss`` for long context, where the full
        [B, S, vocab] logits tensor (4 GB f32 at 32k×32000) is the
        memory wall, not the attention."""
        cfg = self.cfg
        global_seq = tokens.shape[1]
        if cfg.attn_impl in ("ring", "ulysses"):
            # Under sequence-parallel shard_map this trace sees only the
            # local chunk; the RoPE-extrapolation guard must apply to the
            # GLOBAL sequence = local · sp-shards.
            from tony_tpu.ops.ring import bound_axis_size

            n_sp = bound_axis_size("sp")
            if n_sp is not None:
                global_seq = global_seq * n_sp
        if global_seq > cfg.max_seq_len:
            raise ValueError(
                f"global sequence length {global_seq} exceeds max_seq_len "
                f"{cfg.max_seq_len} (RoPE would extrapolate)")
        if positions is None:
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            if cfg.attn_impl in ("ring", "ulysses"):
                # Sequence-parallel: the model runs inside shard_map over
                # "sp" and sees only its local chunk — RoPE needs global
                # positions, offset by the shard index (0 under init or a
                # single-shard apply, where no sp axis is bound).
                pos = pos + _sp_offset() * tokens.shape[1]
            positions = jnp.broadcast_to(pos[None, :], tokens.shape)
        # The table gets its own logical names: sharding its vocab dim over
        # BOTH model axes (and leaving the embed dim whole) lets SPMD
        # partition the lookup as masked-gather + all-reduce; an
        # embed-sharded table instead makes the gather output embed-sharded
        # and the reshard to batch-sharded activations is an "involuntary
        # full rematerialization" in the partitioner (XLA b/433785288).
        emb = self.param(
            "embedding", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab_table", "embed_table")),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = emb[tokens].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        block = Block
        if cfg.remat:
            policy = (getattr(jax.checkpoint_policies, cfg.remat_policy)
                      if cfg.remat_policy else None)
            # prevent_cse MUST stay True here: layers are a Python loop
            # (deliberately — see module docstring), not a lax.scan, and
            # prevent_cse=False is only sound inside scan/while bodies
            # where XLA cannot CSE across the loop boundary. With False,
            # XLA merged each block's recomputation with its forward and
            # silently un-remat'ed the model — measured on v5e: the 317M
            # flagship at batch 8 / seq 8192 compiled to an identical
            # 21.33 GB HBM footprint with remat on and off; with True the
            # same config fits in 9.8 GB.
            block = nn.remat(Block, prevent_cse=True, policy=policy)
        for i in range(cfg.n_layers):
            blk = block
            if (cfg.remat and cfg.remat_skip_every >= 2
                    and i % cfg.remat_skip_every == 0):
                blk = Block     # selective: this layer's activations live
            x = blk(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="final_norm")(x)
        if return_hidden:
            return x
        head_dtype = cfg.lm_head_dtype or cfg.dtype
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x.astype(head_dtype),
                                emb.astype(head_dtype),
                                preferred_element_type=jnp.float32)
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=head_dtype,
                param_dtype=cfg.param_dtype, name="lm_head",
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")))(
                        x.astype(head_dtype))
        return logits.astype(jnp.float32)


def causal_lm_loss(logits: jax.Array, tokens: jax.Array,
                   mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross entropy; logits [B,S,V] predict tokens shifted.

    Computed as logsumexp − picked-logit rather than via log_softmax: the
    reductions fuse into passes over the logits, where log_softmax would
    materialize a second [B,S,V] f32 tensor (1 GB at the bench shape) just
    to gather one column from it."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - picked
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)


def chunked_causal_lm_loss(hidden: jax.Array, head_kernel: jax.Array,
                           tokens: jax.Array, chunk_size: int = 4096,
                           mask: Optional[jax.Array] = None,
                           head_dtype: Optional[jnp.dtype] = None,
                           seq_axis_name: str = "sp") -> jax.Array:
    """Next-token cross entropy without ever materializing [B, S, vocab].

    The long-context memory wall is not attention (flash streams it) but
    the logits: at 32k×32000 vocab the f32 logits plus their cotangent are
    ~8 GB — more than the whole remat'd model. This computes the loss a
    sequence chunk at a time: ``hidden`` [B, S, D] (from
    ``Transformer(..., return_hidden=True)``) is scanned in [B, C, D]
    chunks, each projected through ``head_kernel`` [D, V], reduced to
    (Σnll, count), and rematerialized in backward (``jax.checkpoint``), so
    peak residency is O(B·C·V) — chunk_size trades HBM for recompute.

    Exactly equals ``causal_lm_loss(model(tokens), tokens)`` for the
    untied head (same logsumexp−picked formulation; the matmul runs in
    ``head_dtype`` — pass ``cfg.lm_head_dtype`` if you set it; default =
    the activation dtype, matching ``nn.Dense(dtype=...)``). For
    ``tie_embeddings=True`` pass ``emb.T`` as the kernel; note the tied
    full path additionally accumulates in f32
    (``preferred_element_type``), so equality there is to bf16-matmul
    tolerance, not bitwise.

    Not sequence-parallel: under a sequence shard_map the per-shard
    sequence shift would misalign targets at shard boundaries, so this
    raises — compute hidden states inside the shard_map, gather, and take
    the loss outside (or keep the loss on the full-logits path). The guard
    probes ``seq_axis_name`` (default ``"sp"``) — meshes with a custom
    sequence axis name must pass it through, or the probe (which also
    checks the other standard mesh axes — ``bound_axis_size`` raises on a
    misnamed axis) cannot see the sharding.
    """
    from tony_tpu.ops.ring import bound_axis_size

    if bound_axis_size(seq_axis_name) is not None:
        raise ValueError(
            f"chunked_causal_lm_loss inside a {seq_axis_name!r} shard_map "
            "would shift targets per-shard (wrong at every shard boundary) "
            "and skip the cross-shard mean; compute it outside the "
            "shard_map")
    if hidden.shape[1] != tokens.shape[1]:
        # A sequence mismatch is the signature of per-shard hidden states
        # meeting full tokens (or vice versa) — the exact wrong-loss bug
        # the shard_map guard exists to stop, caught even when the axis
        # name didn't match the probe.
        raise ValueError(
            f"hidden seq {hidden.shape[1]} != tokens seq {tokens.shape[1]} "
            "— per-shard hidden states with full-sequence tokens? Gather "
            "hidden states before the loss (or pass seq_axis_name)")
    x = hidden[:, :-1]
    t = tokens[:, 1:]
    b, s, d = x.shape
    if s == 0:
        return jnp.float32(0.0)     # degenerate S=1: no next-token pairs
    valid = jnp.ones((b, s), jnp.float32) if mask is None \
        else mask[:, 1:].astype(jnp.float32)
    chunk_size = min(chunk_size, s)
    pad = (-s) % chunk_size
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        t = jnp.pad(t, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    nc = x.shape[1] // chunk_size
    xs = x.reshape(b, nc, chunk_size, d).transpose(1, 0, 2, 3)
    ts = t.reshape(b, nc, chunk_size).transpose(1, 0, 2)
    ms = valid.reshape(b, nc, chunk_size).transpose(1, 0, 2)

    hd = head_dtype or hidden.dtype

    @jax.checkpoint
    def chunk_stats(xc, tc, mc):
        logits = (xc.astype(hd)
                  @ head_kernel.astype(hd)).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, tc[..., None], axis=-1)[..., 0]
        return jnp.sum((lse - picked) * mc), jnp.sum(mc)

    def body(carry, args):
        tot, cnt = carry
        dn, dc = chunk_stats(*args)
        return (tot + dn, cnt + dc), None

    (tot, cnt), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (xs, ts, ms))
    return tot / jnp.maximum(cnt, 1.0)
