"""MNIST MLP — the parity workload for the reference's flagship examples.

Reference: ``tony-examples/mnist-tensorflow/mnist_distributed.py`` and
``mnist-pytorch/mnist_distributed.py`` train small MNIST nets through
PS/worker or DDP rendezvous. Here the same workload is a sharded pjit
program: batch over (dp, fsdp), hidden layer optionally over tp.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MnistMLP(nn.Module):
    """784 → hidden → 10 classifier."""
    hidden: int = 512

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "mlp")))(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden, kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("mlp", "embed")))(x)
        x = nn.relu(x)
        return nn.Dense(10, kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "vocab")))(x)


def classification_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)
