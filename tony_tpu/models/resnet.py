"""ResNet (v1.5 bottleneck) — the allreduce-DP parity workload.

Reference parity target: "HorovodRuntime ResNet-50 ImageNet (NCCL allreduce
→ ICI allreduce)" (BASELINE.json configs). TPU-first choices: NHWC layout
(XLA's native conv layout on TPU), bf16 compute, GroupNorm instead of
BatchNorm — no cross-replica batch-stat sync, so pure-DP scaling needs only
the gradient psum and the step stays a single fused XLA program (BatchNorm
would add mutable state + a cross-device mean/var exchange every layer).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tony_tpu.ops.convfuse import fused_groupnorm_relu


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    norm_groups: int = 32
    # HBM-aware conv trunk (BENCH_r05: every conv fusion HBM-bound at
    # 0.13 MFU): each conv→norm→relu chain runs the fused two-pass
    # GroupNorm epilogue (ops/convfuse.py — folded affine, Pallas apply
    # on TPU, remat'd backward) instead of nn.GroupNorm + separate relu.
    # False keeps the original module chain (the parity twin the fused
    # path is tested against).
    fused: bool = True

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(stage_sizes=(3, 4, 6, 3), **kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(1, 1), width=8, num_classes=10,
                        dtype=jnp.float32, norm_groups=4)
        defaults.update(kw)
        return cls(**defaults)


class _Conv(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int]
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        return nn.Conv(
            self.features, self.kernel, self.strides, padding="SAME",
            use_bias=False, dtype=self.cfg.dtype,
            param_dtype=self.cfg.param_dtype,
            # In-channel dim stays unsharded: the stem conv has only 3 input
            # channels, which no mesh axis divides.
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.he_normal(), (None, None, None, "mlp")))(x)


class _Norm(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        groups = min(self.cfg.norm_groups, x.shape[-1])
        return nn.GroupNorm(num_groups=groups, dtype=self.cfg.dtype,
                            param_dtype=self.cfg.param_dtype)(x)


class _NormAct(nn.Module):
    """Fused GroupNorm(+ReLU): same params (scale/bias, same shapes and
    leaf order as the _Norm twin) applied through the two-HBM-pass
    fused epilogue. ``relu=False`` for the pre-residual norms."""
    cfg: ResNetConfig
    relu: bool = True

    @nn.compact
    def __call__(self, x):
        groups = min(self.cfg.norm_groups, x.shape[-1])
        scale = self.param("scale", nn.initializers.ones,
                           (x.shape[-1],), self.cfg.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (x.shape[-1],), self.cfg.param_dtype)
        return fused_groupnorm_relu(x, scale, bias, groups=groups,
                                    relu=self.relu)


class _Bottleneck(nn.Module):
    features: int
    strides: Tuple[int, int]
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        residual = x
        if cfg.fused:
            y = _Conv(self.features, (1, 1), (1, 1), cfg)(x)
            y = _NormAct(cfg)(y)
            y = _Conv(self.features, (3, 3), self.strides, cfg)(y)
            y = _NormAct(cfg)(y)
            y = _Conv(self.features * 4, (1, 1), (1, 1), cfg)(y)
            y = _NormAct(cfg, relu=False)(y)
            if residual.shape != y.shape:
                residual = _Conv(self.features * 4, (1, 1), self.strides,
                                 cfg)(x)
                residual = _NormAct(cfg, relu=False)(residual)
            return nn.relu(y + residual)
        y = _Conv(self.features, (1, 1), (1, 1), cfg)(x)
        y = nn.relu(_Norm(cfg)(y))
        y = _Conv(self.features, (3, 3), self.strides, cfg)(y)
        y = nn.relu(_Norm(cfg)(y))
        y = _Conv(self.features * 4, (1, 1), (1, 1), cfg)(y)
        y = _Norm(cfg)(y)
        if residual.shape != y.shape:
            residual = _Conv(self.features * 4, (1, 1), self.strides,
                             cfg)(x)
            residual = _Norm(cfg)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Images [B, H, W, 3] → logits [B, num_classes]."""
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = _Conv(cfg.width, (7, 7), (2, 2), cfg)(x)
        if cfg.fused:
            x = _NormAct(cfg)(x)
        else:
            x = nn.relu(_Norm(cfg)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = _Bottleneck(cfg.width * 2 ** stage, strides, cfg)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")))(
                    x.astype(jnp.float32))
