"""Fleet daemon: the persistent cluster scheduler process.

``tony-tpu fleet start`` (or ``python -m tony_tpu.fleet serve``) runs one
of these per cluster. It owns a pool of TPU slices (LocalSim hosts in
drills), accepts submissions over the ordinary token-authed RPC plane
(``fleet.submit`` / ``fleet.status`` / ``fleet.cancel`` / ``fleet.stop``,
generation-fenced like every other surface), lets the stdlib policy
engine (``fleet/policy.py``) decide who runs where, and carries out the
decisions:

- a **grant** spawns the granted job through the ordinary single-job
  stack — one ``tony-tpu submit`` client subprocess per job, with the
  fleet's injections on its conf: granted gang size, elastic knobs for
  preemptible jobs, the shared warm executor pool (``tony.pool.dir``)
  and the per-model compile-cache mount
  (``tony.jax.compilation-cache-dir = <root>/<model>``) so every
  tenant's resubmit rides the warm paths;
- a **preemption** shrinks the victim through its coordinator's elastic
  resize RPC (``coordinator/elastic.py`` drain→remesh — the absorb path:
  no kill, no epoch burned) and hands the reclaimed hosts to the
  higher-priority demander;
- a **grow-back** restores shrunk victims once the queue drains.

Every decision is write-ahead journaled (``fleet/journal.py``) so a
SIGKILLed daemon restarted with ``--recover`` resumes the same queue
state, re-adopts still-running jobs by their recorded pid (the client
subprocesses are session leaders and survive the daemon), and re-spawns
granted-but-never-started jobs — zero duplicated or lost grants.
Scheduler state surfaces as FLEET_* events, the ``tony_fleet_*`` metric
families (``<fleet_dir>/fleet.prom``), an atomically replaced
``fleet.status.json`` (the portal's /fleet source), and ``tony-tpu
fleet top``.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from tony_tpu import constants, faults
from tony_tpu.conf import keys as K
from tony_tpu.events.events import Event, EventHandler, EventType
from tony_tpu.fleet import journal as fjournal
from tony_tpu.fleet.policy import (CAPACITY_DENIED, GRANT, QUOTA_DENIED,
                                   SHRINK, JobRequest, PolicyEngine,
                                   parse_quotas)
from tony_tpu.metrics import MetricsRegistry
from tony_tpu.utils.durable import atomic_write

log = logging.getLogger(__name__)

#: daemon-side job states (journal STATE_* plus the pre-grant ones)
QUEUED = "QUEUED"
GRANTED = "GRANTED"
RUNNING = "RUNNING"


class FleetError(RuntimeError):
    pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


class _FleetJob:
    def __init__(self, req: JobRequest, conf: Dict[str, str],
                 workdir: str) -> None:
        self.req = req
        self.conf = conf
        self.workdir = workdir
        self.state = QUEUED
        self.hosts = 0
        self.placement: Dict[int, int] = {}
        self.app_id = ""
        self.pid = 0
        self.exit_code: Optional[int] = None
        self.handle: Optional[Any] = None
        self.submitted_mono = time.monotonic()
        self.wait_s: Optional[float] = None    # queue wait, set at grant
        self.denial = ""                       # last quota/capacity note
        self.cancelled = False


class _AdoptedHandle:
    """Popen-shaped handle over a RECOVERED job's client process: not
    our child (the previous daemon life spawned it), so liveness is a
    signal-0 probe and the outcome comes from the job's finalized
    history file — the same adopt-a-foreign-process shape as the pool
    backend's _LeasedProc."""

    def __init__(self, pid: int, history_root: str, job: "_FleetJob"):
        self.pid = pid
        self.history_root = history_root
        self.job = job
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if _pid_alive(self.pid):
            return None
        status = self._history_status()
        self.returncode = 0 if status == "SUCCEEDED" else 1
        return self.returncode

    def _history_status(self) -> str:
        from tony_tpu.events import history

        app_id = self.job.app_id or _discover_app(self.job.workdir) or ""
        if not app_id:
            return ""
        job_dir = history.list_job_dirs(self.history_root).get(app_id)
        if not job_dir:
            return ""
        path = history.find_history_file(job_dir)
        if not path:
            return ""
        meta = history.parse_metadata(os.path.basename(path))
        return meta.status if meta is not None else ""


def _discover_app(job_workdir: str) -> Optional[str]:
    """The app id of the single job submitted from ``job_workdir`` (the
    client creates ``jobs/<app_id>/`` there); newest wins if a re-grant
    ever left a sibling."""
    jobs_dir = os.path.join(job_workdir, "jobs")
    try:
        entries = sorted(os.listdir(jobs_dir))
    except OSError:
        return None
    return entries[-1] if entries else None


class SubprocessJobRunner:
    """Carries fleet decisions out against the real single-job stack:
    spawn = one ``tony-tpu submit`` client subprocess (session leader —
    it survives a daemon SIGKILL), resize/kill = RPCs against the job's
    coordinator address file. Tests substitute a fake with the same
    surface."""

    def __init__(self, python: str = sys.executable) -> None:
        self.python = python

    def spawn(self, job_workdir: str,
              overrides: Dict[str, str]) -> subprocess.Popen:
        os.makedirs(job_workdir, exist_ok=True)
        cmd = [self.python, "-m", "tony_tpu.cli", "submit",
               "--workdir", job_workdir]
        for k in sorted(overrides):
            cmd += ["--conf", f"{k}={overrides[k]}"]
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep +
                             env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        clog = open(os.path.join(job_workdir, "client.log"), "ab")
        popen = subprocess.Popen(cmd, stdout=clog,
                                 stderr=subprocess.STDOUT, env=env,
                                 start_new_session=True)
        clog.close()
        return popen

    def poll(self, handle: Any) -> Optional[int]:
        return handle.poll()

    def _coordinator_rpc(self, job_workdir: str) -> Optional[Any]:
        app_id = _discover_app(job_workdir)
        if app_id is None:
            return None
        addr_path = os.path.join(job_workdir, "jobs", app_id,
                                 "coordinator.addr")
        try:
            with open(addr_path, encoding="utf-8") as f:
                addr = json.load(f)
        except (OSError, ValueError):
            return None
        from tony_tpu.rpc.wire import RpcClient

        return RpcClient(addr["host"], int(addr["port"]),
                         token=addr.get("token") or None,
                         max_retries=2, retry_sleep_s=0.2,
                         connect_timeout_s=5.0, call_timeout_s=15.0)

    def resize(self, job_workdir: str, size: int) -> bool:
        """Elastic resize (shrink = preempt-to-reclaim, grow =
        grow-back restore) via the job's own resize_application RPC."""
        rpc = self._coordinator_rpc(job_workdir)
        if rpc is None:
            return False
        try:
            res = rpc.call("resize_application", size=int(size), job="")
            return bool(isinstance(res, dict) and res.get("ok"))
        except Exception as e:  # noqa: BLE001 — a dead victim is a no
            log.warning("fleet resize of %s to %d failed: %s",
                        job_workdir, size, e)
            return False
        finally:
            rpc.close()

    def kill(self, job_workdir: str) -> bool:
        rpc = self._coordinator_rpc(job_workdir)
        if rpc is None:
            return False
        try:
            rpc.call("kill_application")
            return True
        except Exception as e:  # noqa: BLE001
            log.warning("fleet kill of %s failed: %s", job_workdir, e)
            return False
        finally:
            rpc.close()


class _FleetService:
    """RPC surface (rpc/wire.py namespacing: ``fleet.submit`` etc.)."""

    def __init__(self, daemon: "FleetDaemon") -> None:
        self._d = daemon

    def fleet__submit(self, tenant: str, hosts: int, priority: int = 0,
                      min_hosts: int = 0, model: str = "",
                      conf: Optional[dict] = None) -> dict:
        return self._d.submit(str(tenant), int(hosts),
                              priority=int(priority or 0),
                              min_hosts=int(min_hosts or 0),
                              model=str(model or ""),
                              conf=dict(conf or {}))

    def fleet__status(self) -> dict:
        return self._d.status()

    def fleet__cancel(self, job: str) -> dict:
        return self._d.cancel(str(job))

    def fleet__stop(self) -> bool:
        self._d.request_stop()
        return True


class FleetDaemon:
    def __init__(self, fleet_dir: str, slices: int = 1,
                 hosts_per_slice: int = 8, quotas: str = "",
                 pool_dir: str = "", cache_root: str = "",
                 tick_s: float = 0.5, recover: bool = False,
                 runner: Optional[Any] = None,
                 python: str = sys.executable) -> None:
        self.fleet_dir = os.path.abspath(os.path.expanduser(fleet_dir))
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.slices = max(1, int(slices))
        self.hosts_per_slice = max(1, int(hosts_per_slice))
        self.quotas = parse_quotas(quotas)
        self.pool_dir = pool_dir
        self.cache_root = cache_root
        self.tick_s = max(0.05, float(tick_s))
        self.history_root = os.path.join(self.fleet_dir, "history")
        self.runner = runner if runner is not None \
            else SubprocessJobRunner(python)
        self.engine = PolicyEngine(self.slices, self.hosts_per_slice,
                                   self.quotas)
        self.jobs: Dict[str, _FleetJob] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._started = False

        journal_path = os.path.join(self.fleet_dir,
                                    constants.FLEET_JOURNAL_FILE)
        replayed: Optional[fjournal.FleetReplayState] = None
        if os.path.exists(journal_path):
            replayed = fjournal.replay(journal_path)
            live = [f for f in replayed.jobs.values()
                    if f.state not in fjournal.TERMINAL_STATES]
            if live and not recover:
                raise FleetError(
                    f"fleet dir {self.fleet_dir} holds journaled state "
                    f"for {len(live)} non-terminal job(s) — start with "
                    f"--recover to resume it, or point --dir elsewhere")
        # Generation: strictly monotonic across daemon lives (journal-
        # persisted, fences zombie daemons out of the RPC plane).
        self.generation = (replayed.generation if replayed else 0) + 1
        self.journal = fjournal.FleetJournal(journal_path)
        self.journal.generation(self.generation, self.slices,
                                self.hosts_per_slice)

        self.metrics = MetricsRegistry()
        self._counters_path = os.path.join(self.fleet_dir,
                                           constants.FLEET_COUNTERS_FILE)
        self.metrics.load_counters(self._counters_path)
        self.events = EventHandler(self.fleet_dir,
                                   constants.FLEET_EVENTS_FILE,
                                   on_emit=self._count_event)
        # The writer thread runs from construction (not start()): every
        # scheduler decision is evented, including ones taken before the
        # RPC plane is up (recovery re-folds, embedded/test daemons).
        self.events.start()
        import secrets

        self.token = secrets.token_hex(16)
        from tony_tpu.rpc.wire import RpcServer

        self.rpc = RpcServer(_FleetService(self), host="127.0.0.1",
                             port=0, token=self.token,
                             generation=self.generation)
        if replayed is not None and recover:
            self._recover(replayed)

    # -- recovery ---------------------------------------------------------
    def _recover(self, st: fjournal.FleetReplayState) -> None:
        """Rebuild queue + accounting from the replayed journal: queued
        jobs re-enqueue in submission order; running jobs are re-adopted
        by their recorded client pid; granted-but-never-started jobs
        re-spawn against their journaled grant; finished jobs keep their
        verdicts for the status surface."""
        self._seq = st.seq
        for fold in sorted(st.jobs.values(), key=lambda f: f.seq):
            req = JobRequest(fold.job_id, fold.tenant,
                             priority=fold.priority,
                             hosts=fold.hosts_requested,
                             min_hosts=fold.min_hosts, model=fold.model,
                             seq=fold.seq)
            job = _FleetJob(req, fold.conf,
                            os.path.join(self.fleet_dir, "jobs",
                                         fold.job_id))
            job.app_id = fold.app_id
            job.pid = fold.pid
            job.exit_code = fold.exit_code
            self.jobs[fold.job_id] = job
            if fold.state in fjournal.TERMINAL_STATES:
                job.state = fold.state
                continue
            if fold.state == "QUEUED":
                self.engine.submit(req)
                continue
            # GRANTED / SPAWNED / RUNNING: the grant stands — decide
            # between adopt, respawn, and post-mortem.
            app_id = fold.app_id or _discover_app(job.workdir)
            if fold.pid and _pid_alive(fold.pid):
                self.engine.force_grant(req, fold.hosts, fold.placement)
                job.state = RUNNING
                job.hosts = fold.hosts
                job.placement = dict(fold.placement)
                job.handle = _AdoptedHandle(fold.pid, self.history_root,
                                            job)
                log.info("fleet recover: adopted running job %s "
                         "(client pid %d, app %s)", fold.job_id,
                         fold.pid, app_id or "?")
            elif app_id:
                # The client is gone but the job got as far as an app
                # dir: read its outcome from history (an unfinished
                # app with a dead client is a crashed job).
                job.app_id = app_id
                handle = _AdoptedHandle(fold.pid or 1, self.history_root,
                                        job)
                status = handle._history_status()
                exit_code = 0 if status == "SUCCEEDED" else 1
                state = fjournal.STATE_FINISHED if exit_code == 0 \
                    else fjournal.STATE_FAILED
                self.journal.state(fold.job_id, state, app_id=app_id,
                                   exit_code=exit_code)
                job.state = state
                job.exit_code = exit_code
                log.info("fleet recover: job %s finished %s while the "
                         "daemon was down", fold.job_id, state)
            else:
                # Granted (journaled write-ahead) but the spawn never
                # produced an app: carry the grant out now — this is
                # the zero-LOST-grants half of the recovery contract
                # (the fgen record above licenses the re-grant).
                self.engine.submit(req)
                job.state = QUEUED
                log.info("fleet recover: re-queued granted-but-never-"
                         "started job %s", fold.job_id)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._started = True
        self.rpc.start()
        host, port = self.rpc.address
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_ADDR_FILE),
            json.dumps({"host": host, "port": port, "token": self.token,
                        "pid": os.getpid(),
                        "generation": self.generation}).encode("utf-8"),
            mode=0o600)
        log.info("fleet daemon up at %s:%d (generation %d, %d slice(s) "
                 "x %d hosts, quotas %s)", host, port, self.generation,
                 self.slices, self.hosts_per_slice, self.quotas or "none")

    def run(self) -> int:
        self.start()
        try:
            while not self._stop_evt.wait(self.tick_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the daemon must live
                    log.exception("fleet tick failed")
        finally:
            self._shutdown()
        return 0

    def request_stop(self) -> None:
        self._stop_evt.set()

    def _shutdown(self) -> None:
        # Running jobs are NOT killed: they belong to their tenants and
        # their client/coordinator processes are independent session
        # leaders — the same leave-leased-work-alone posture as the
        # pool daemon's shutdown.
        self._export()
        try:
            os.unlink(os.path.join(self.fleet_dir,
                                   constants.FLEET_ADDR_FILE))
        except OSError:
            pass
        if self._started:
            # Stopping a never-serving TCP server deadlocks in
            # shutdown(); unit tests drive tick() without start().
            self.rpc.stop()
        # Final name == in-progress name: the fleet stream is append-only
        # across daemon lives, never finalized like a job's jhist.
        self.events.stop(constants.FLEET_EVENTS_FILE)
        self.journal.close()

    def _count_event(self, ev: Event) -> None:
        self.metrics.counter("tony_events_total",
                             {"type": ev.type.value},
                             help="job-history events emitted, "
                                  "by type").inc()

    # -- RPC behaviour ----------------------------------------------------
    def submit(self, tenant: str, hosts: int, priority: int = 0,
               min_hosts: int = 0, model: str = "",
               conf: Optional[Dict[str, str]] = None) -> dict:
        if not tenant:
            return {"ok": False, "message": "submission needs a tenant"}
        if hosts <= 0 or hosts > self.engine.pool.total:
            return {"ok": False,
                    "message": f"hosts must be 1..{self.engine.pool.total} "
                               f"(the pool), got {hosts}"}
        if min_hosts > hosts:
            return {"ok": False,
                    "message": f"min_hosts {min_hosts} > hosts {hosts}"}
        quota = self.quotas.get(tenant, 0)
        if quota > 0 and hosts > quota:
            # Refuse outright rather than queue forever: this request
            # can never be granted under the tenant's quota.
            return {"ok": False,
                    "message": f"{hosts} hosts exceeds tenant "
                               f"{tenant!r}'s quota of {quota}"}
        conf = {str(k): str(v) for k, v in (conf or {}).items()}
        with self._lock:
            self._seq += 1
            seq = self._seq
        job_id = f"fj-{seq:04d}"
        req = JobRequest(job_id, tenant, priority=priority, hosts=hosts,
                         min_hosts=min_hosts, model=model, seq=seq)
        # Write-ahead of the ack: a submission the caller saw accepted
        # must survive a daemon crash into the recovered queue.
        self.journal.submit(job_id, tenant, priority, hosts, min_hosts,
                            model, seq, conf)
        job = _FleetJob(req, conf,
                        os.path.join(self.fleet_dir, "jobs", job_id))
        with self._lock:
            self.jobs[job_id] = job
            self.engine.submit(req)
        self.events.emit(Event(EventType.FLEET_JOB_QUEUED, {
            "job": job_id, "tenant": tenant, "priority": priority,
            "hosts": hosts, "min_hosts": min_hosts, "model": model}))
        log.info("fleet submit: %s tenant=%s priority=%d hosts=%d",
                 job_id, tenant, priority, hosts)
        return {"ok": True, "job": job_id, "state": QUEUED}

    def cancel(self, job_id: str) -> dict:
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                return {"ok": False, "message": f"unknown job {job_id!r}"}
            if job.state in fjournal.TERMINAL_STATES:
                return {"ok": False,
                        "message": f"{job_id} already {job.state}"}
            was_queued = job.state == QUEUED
            job.cancelled = True
            if was_queued:
                self.engine.withdraw(job_id)
                job.state = fjournal.STATE_CANCELLED
        if was_queued:
            self.journal.state(job_id, fjournal.STATE_CANCELLED)
            self._finish_event(job_id, fjournal.STATE_CANCELLED, None)
            return {"ok": True, "state": fjournal.STATE_CANCELLED}
        # Running: ask its coordinator to die; the poll loop records the
        # exit as CANCELLED (job.cancelled wins over the exit code).
        self.runner.kill(job.workdir)
        return {"ok": True, "state": "CANCELLING"}

    def status(self) -> dict:
        from tony_tpu.coordinator.coordphases import histogram_quantile

        with self._lock:
            used = self.engine.tenant_used()
            rows = []
            now = time.monotonic()
            for job in sorted(self.jobs.values(),
                              key=lambda j: j.req.seq):
                wait = job.wait_s if job.wait_s is not None else (
                    now - job.submitted_mono
                    if job.state == QUEUED else None)
                rows.append({
                    "job": job.req.job_id, "tenant": job.req.tenant,
                    "priority": job.req.priority, "state": job.state,
                    "hosts_requested": job.req.hosts,
                    "hosts": job.hosts, "model": job.req.model,
                    "app_id": job.app_id, "exit": job.exit_code,
                    "wait_s": round(wait, 3) if wait is not None
                    else None,
                    "denial": job.denial})
            queue_depth = self.engine.queue_depth
            free = self.engine.pool.free_total
        hist = self.metrics.histogram(
            "tony_fleet_queue_wait_seconds",
            help="submit-to-grant wait latency").snapshot()
        total = self.slices * self.hosts_per_slice
        return {
            "fleet_dir": self.fleet_dir, "generation": self.generation,
            "pool": {"slices": self.slices,
                     "hosts_per_slice": self.hosts_per_slice,
                     "total": total, "used": total - free, "free": free},
            "tenants": {t: {"used": n,
                            "quota": self.quotas.get(t, 0) or None}
                        for t, n in sorted(used.items())},
            "queue_depth": queue_depth,
            "jobs": rows,
            "queue_wait": {
                "p50_s": round(histogram_quantile(hist, 0.5), 4),
                "p99_s": round(histogram_quantile(hist, 0.99), 4),
                "count": hist.get("count", 0)},
        }

    # -- the scheduler tick ----------------------------------------------
    def tick(self) -> None:
        self._poll_jobs()
        self._discover_apps()
        self._apply_plan()
        self._restore()
        self._export()

    def _poll_jobs(self) -> None:
        done: List[_FleetJob] = []
        with self._lock:
            candidates = [j for j in self.jobs.values()
                          if j.handle is not None
                          and j.state in (GRANTED, RUNNING)]
        for job in candidates:
            rc = self.runner.poll(job.handle)
            if rc is None:
                continue
            if job.cancelled:
                state = fjournal.STATE_CANCELLED
            elif rc == 0:
                state = fjournal.STATE_FINISHED
            else:
                state = fjournal.STATE_FAILED
            self.journal.state(job.req.job_id, state,
                               app_id=job.app_id, exit_code=int(rc))
            with self._lock:
                job.state = state
                job.exit_code = int(rc)
                job.handle = None
                self.engine.release(job.req.job_id)
            done.append(job)
            self._finish_event(job.req.job_id, state, int(rc))
        if done:
            log.info("fleet: %d job(s) finished this tick (%s)",
                     len(done), ", ".join(j.req.job_id for j in done))

    def _finish_event(self, job_id: str, state: str,
                      exit_code: Optional[int]) -> None:
        job = self.jobs.get(job_id)
        self.events.emit(Event(EventType.FLEET_JOB_FINISHED, {
            "job": job_id, "state": state, "exit": exit_code,
            "app_id": job.app_id if job else ""}))

    def _discover_apps(self) -> None:
        with self._lock:
            pending = [j for j in self.jobs.values()
                       if j.state == RUNNING and not j.app_id]
        for job in pending:
            app_id = _discover_app(job.workdir)
            if app_id is None:
                continue
            self.journal.state(job.req.job_id, fjournal.STATE_RUNNING,
                               app_id=app_id, pid=job.pid)
            with self._lock:
                job.app_id = app_id

    def _apply_plan(self) -> None:
        with self._lock:
            plan = self.engine.schedule()
        for d in plan:
            if d.action == GRANT:
                if not self._apply_grant(d.job_id, d.placement):
                    return          # retry the rest next tick
            elif d.action == SHRINK:
                if not self._apply_preempt(d.job_id, d.hosts, d.for_job,
                                           d.reason):
                    return
            elif d.action in (QUOTA_DENIED, CAPACITY_DENIED):
                self._note_denial(d.job_id, d.action, d.reason)

    def _note_denial(self, job_id: str, kind: str, reason: str) -> None:
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                return
            first = job.denial != reason
            job.denial = reason
        if first and kind == QUOTA_DENIED:
            self.metrics.counter(
                "tony_fleet_quota_denials_total",
                help="grants deferred by tenant quota").inc()
            self.events.emit(Event(EventType.FLEET_QUOTA_DENIED, {
                "job": job_id, "reason": reason}))

    def _grant_overrides(self, job: _FleetJob) -> Dict[str, str]:
        """The fleet's injections on a granted job's conf: granted gang
        size, elastic preemptibility, the shared warm pool, the
        per-model compile cache, and the fleet history root (one portal
        over every tenant's jobs). The submission's own keys win where
        they name the same knob explicitly."""
        ov = dict(job.conf)
        ov["tony.worker.instances"] = str(job.hosts)
        if 0 < job.req.min_hosts < job.req.hosts:
            ov.setdefault(K.ELASTIC_ENABLED, "true")
            ov.setdefault(K.ELASTIC_MIN_TASKS, str(job.req.min_hosts))
        if self.pool_dir:
            ov.setdefault(K.POOL_DIR, self.pool_dir)
        if self.cache_root and job.req.model:
            ov.setdefault(K.JAX_COMPILE_CACHE_DIR,
                          os.path.join(self.cache_root, job.req.model))
        ov.setdefault(K.HISTORY_LOCATION, self.history_root)
        return ov

    def _apply_grant(self, job_id: str,
                     placement: Dict[int, int]) -> bool:
        try:
            faults.check("fleet.grant")
        except faults.InjectedFault as e:
            # The job stays QUEUED (nothing journaled, nothing
            # accounted) and the next tick retries — a grant failure
            # must never lose a submission.
            log.warning("fleet grant of %s failed (%s); job stays "
                        "queued", job_id, e)
            return False
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None or job.state != QUEUED:
                return True         # cancelled mid-plan: skip
        hosts = sum(placement.values())
        # Write-ahead: the grant record lands before the spawn, so a
        # crash in between recovers into "re-carry the grant out", never
        # a lost grant.
        self.journal.grant(job_id, hosts, placement)
        with self._lock:
            try:
                self.engine.grant(job_id, placement)
            except KeyError:
                return True         # withdrawn between plan and apply
            job.state = GRANTED
            job.hosts = hosts
            job.placement = dict(placement)
            job.wait_s = time.monotonic() - job.submitted_mono
            job.denial = ""
        try:
            popen = self.runner.spawn(job.workdir,
                                      self._grant_overrides(job))
        except OSError as e:
            log.error("fleet: spawn of %s failed: %s", job_id, e)
            self.journal.state(job_id, fjournal.STATE_FAILED,
                               exit_code=1)
            with self._lock:
                job.state = fjournal.STATE_FAILED
                job.exit_code = 1
                self.engine.release(job_id)
            self._finish_event(job_id, fjournal.STATE_FAILED, 1)
            return True
        self.journal.state(job_id, fjournal.STATE_SPAWNED,
                           pid=popen.pid)
        with self._lock:
            job.handle = popen
            job.pid = popen.pid
            job.state = RUNNING
        self.metrics.counter("tony_fleet_grants_total",
                             help="job grants applied").inc()
        self.metrics.histogram(
            "tony_fleet_queue_wait_seconds",
            help="submit-to-grant wait latency").observe(job.wait_s)
        self.events.emit(Event(EventType.FLEET_JOB_GRANTED, {
            "job": job_id, "tenant": job.req.tenant, "hosts": hosts,
            "placement": {str(i): n for i, n in placement.items()},
            "wait_s": round(job.wait_s, 3)}))
        log.info("fleet grant: %s -> %d host(s) on slice(s) %s "
                 "(waited %.2fs)", job_id, hosts,
                 sorted(placement), job.wait_s)
        return True

    def _apply_preempt(self, victim_id: str, to_hosts: int,
                       for_job: str, reason: str) -> bool:
        try:
            faults.check("fleet.preempt")
        except faults.InjectedFault as e:
            log.warning("fleet preempt of %s failed (%s); retried next "
                        "tick", victim_id, e)
            return False
        with self._lock:
            victim = self.jobs.get(victim_id)
            if victim is None or victim.state != RUNNING:
                return True
            from_hosts = victim.hosts
        # The victim shrinks through its own elastic machinery
        # (drain→remesh→barrier — coordinator/elastic.py): the epoch
        # survives, nothing is killed. The resize lands first, then the
        # accounting: a crash between the two under-frees for one
        # recovery (grow-back reconciles) rather than double-booking.
        if not self.runner.resize(victim.workdir, to_hosts):
            log.warning("fleet preempt: %s resize to %d refused/"
                        "unreachable; retried next tick", victim_id,
                        to_hosts)
            return False
        with self._lock:
            new_placement = self.engine.shrink_applied(victim_id,
                                                       to_hosts)
            victim.hosts = to_hosts
            victim.placement = new_placement
        self.journal.preempt(victim_id, from_hosts, to_hosts, for_job,
                             new_placement)
        self.metrics.counter(
            "tony_fleet_preemptions_total",
            help="preempt-to-reclaim shrinks applied").inc()
        self.events.emit(Event(EventType.FLEET_JOB_PREEMPTED, {
            "job": victim_id, "from": from_hosts, "to": to_hosts,
            "for": for_job, "reason": reason}))
        log.warning("fleet preempt: %s shrunk %d->%d host(s) for %s",
                    victim_id, from_hosts, to_hosts, for_job)
        return True

    def _restore(self) -> None:
        """Grow shrunk victims back toward their requested size once the
        queue has drained — preemption is a loan. The grow rides the
        same elastic resize path (and, with a warm pool configured, the
        fresh members adopt pre-warmed executors — the ≤2s regrow)."""
        with self._lock:
            candidates = self.engine.restore_candidates()
        for job_id, new_hosts, delta in candidates:
            with self._lock:
                job = self.jobs.get(job_id)
                if job is None or job.state != RUNNING:
                    continue
            if not self.runner.resize(job.workdir, new_hosts):
                continue
            with self._lock:
                placement = self.engine.grow_applied(job_id, delta)
                job.hosts = new_hosts
                job.placement = placement
            self.journal.state(job_id, fjournal.STATE_RESTORED,
                               hosts=new_hosts, placement=placement)
            log.info("fleet restore: %s grown back to %d host(s)",
                     job_id, new_hosts)

    # -- surfaces ---------------------------------------------------------
    def _export(self) -> None:
        snap = self.status()
        pool = snap["pool"]
        for state in ("total", "used", "free"):
            self.metrics.gauge("tony_fleet_hosts", {"state": state},
                               help="pool hosts by state").set(
                pool[state])
        by_state = {s: 0 for s in (QUEUED, GRANTED, RUNNING)
                    + fjournal.TERMINAL_STATES}
        for row in snap["jobs"]:
            by_state[row["state"]] = by_state.get(row["state"], 0) + 1
        for state, n in by_state.items():
            # Zero-filled over the full state set so a drained queue
            # reads as 0, not as a frozen last value.
            self.metrics.gauge("tony_fleet_jobs", {"state": state},
                               help="fleet jobs by state").set(n)
        self.metrics.gauge("tony_fleet_queue_depth",
                           help="submissions waiting for a grant").set(
            snap["queue_depth"])
        for tenant, row in snap["tenants"].items():
            self.metrics.gauge("tony_fleet_tenant_hosts",
                               {"tenant": tenant},
                               help="granted hosts per tenant").set(
                row["used"])
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_PROM_FILE),
            self.metrics.render().encode("utf-8"))
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_STATUS_FILE),
            json.dumps(snap, sort_keys=True).encode("utf-8"))
        self.metrics.save_counters(self._counters_path)
