"""Fleet daemon: the persistent cluster scheduler process.

``tony-tpu fleet start`` (or ``python -m tony_tpu.fleet serve``) runs one
of these per cluster. It owns a pool of TPU slices (LocalSim hosts in
drills), accepts submissions over the ordinary token-authed RPC plane
(``fleet.submit`` / ``fleet.status`` / ``fleet.cancel`` / ``fleet.stop``,
generation-fenced like every other surface), lets the stdlib policy
engine (``fleet/policy.py``) decide who runs where, and carries out the
decisions:

- a **grant** spawns the granted job through the ordinary single-job
  stack — one ``tony-tpu submit`` client subprocess per job, with the
  fleet's injections on its conf: granted gang size, elastic knobs for
  preemptible jobs, the shared warm executor pool (``tony.pool.dir``)
  and the per-model compile-cache mount
  (``tony.jax.compilation-cache-dir = <root>/<model>``) so every
  tenant's resubmit rides the warm paths;
- a **preemption** shrinks the victim through its coordinator's elastic
  resize RPC (``coordinator/elastic.py`` drain→remesh — the absorb path:
  no kill, no epoch burned) and hands the reclaimed hosts to the
  higher-priority demander;
- a **grow-back** restores shrunk victims once the queue drains;
- a **migration** moves a running job between slices through its
  coordinator's live-migration op (``coordinator/migrate.py``
  drain→async-snapshot→relaunch — no kill, no epoch burned): planned by
  the policy engine to cure FRAGMENTATION holds, triggered proactively
  by a slice-preemption notice (the ``slice.preempt`` fault site in
  drills, the queued-resource reclaim feed — ``cluster/gcloud.py``
  ``reclaim_notices`` — in production), or requested by the operator
  via ``tony-tpu fleet migrate <job> <slice>``.

Every decision is write-ahead journaled (``fleet/journal.py``) so a
SIGKILLed daemon restarted with ``--recover`` resumes the same queue
state, re-adopts still-running jobs by their recorded pid (the client
subprocesses are session leaders and survive the daemon), and re-spawns
granted-but-never-started jobs — zero duplicated or lost grants.
Scheduler state surfaces as FLEET_* events, the ``tony_fleet_*`` metric
families (``<fleet_dir>/fleet.prom``), an atomically replaced
``fleet.status.json`` (the portal's /fleet source), and ``tony-tpu
fleet top``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from tony_tpu import constants, faults, tracing
from tony_tpu import alerts as falerts
from tony_tpu.conf import keys as K
from tony_tpu.devtools.race import guarded
from tony_tpu.events.events import Event, EventHandler, EventType
from tony_tpu.fleet import health as fhealth
from tony_tpu.fleet import journal as fjournal
from tony_tpu.fleet import ledger as fledger
from tony_tpu.fleet.policy import (GRANT, HOLD_ACTIONS, MIGRATE,
                                   QUOTA_DENIED, SHRINK, Decision,
                                   JobRequest, PolicyEngine,
                                   parse_quotas)
from tony_tpu.metrics import MetricsRegistry
from tony_tpu.utils.durable import DurableWriteError, atomic_write

log = logging.getLogger(__name__)

#: daemon-side job states (journal STATE_* plus the pre-grant ones)
QUEUED = "QUEUED"
GRANTED = "GRANTED"
RUNNING = "RUNNING"

#: queue-wait histogram buckets (seconds): submit→grant waits live in
#: the seconds-to-minutes range, not the sub-ms RPC-latency range the
#: default buckets cover — without these, any wait past 10s saturates
#: the top bucket and p99 reads as a flat 10.0.
QUEUE_WAIT_BUCKETS_S = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
                        40.0, 60.0, 120.0, 300.0, 600.0, 1800.0)


class FleetError(RuntimeError):
    pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


class _FleetJob:
    def __init__(self, req: JobRequest, conf: Dict[str, str],
                 workdir: str, decision_ring: int = 64) -> None:
        self.req = req
        self.conf = conf
        self.workdir = workdir
        self.state = QUEUED
        self.hosts = 0
        self.placement: Dict[int, int] = {}
        #: concrete host identities the grant landed on (fleet/health.py
        #: names, task-index order) — the failure-attribution target map
        self.host_ids: List[str] = []
        self.app_id = ""
        self.pid = 0
        self.exit_code: Optional[int] = None
        self.handle: Optional[Any] = None
        self.submitted_mono = time.monotonic()
        self.wait_s: Optional[float] = None    # queue wait, set at grant
        self.denial = ""                       # last quota/capacity note
        self.cancelled = False
        # --- observability (ledger + explainer + trace) ----------------
        # Wall-clock anchors for the goodput ledger (ms; the journal
        # records carry the same clock, so offline re-folds agree).
        self.submitted_ms = int(time.time() * 1000)
        self.granted_ms = 0
        self.finished_ms = 0
        self.host_events: List[Any] = []       # [(ts_ms, hosts)]
        # Bounded hold-reason transition ring behind `fleet explain`.
        self.decisions: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(2, int(decision_ring)))
        self.queue_span: Any = tracing.NULL_SPAN
        self.job_span: Any = tracing.NULL_SPAN


class _AdoptedHandle:
    """Popen-shaped handle over a RECOVERED job's client process: not
    our child (the previous daemon life spawned it), so liveness is a
    signal-0 probe and the outcome comes from the job's finalized
    history file — the same adopt-a-foreign-process shape as the pool
    backend's _LeasedProc."""

    def __init__(self, pid: int, history_root: str, job: "_FleetJob"):
        self.pid = pid
        self.history_root = history_root
        self.job = job
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if _pid_alive(self.pid):
            return None
        status = self._history_status()
        self.returncode = 0 if status == "SUCCEEDED" else 1
        return self.returncode

    def _history_status(self) -> str:
        from tony_tpu.events import history

        app_id = self.job.app_id or _discover_app(self.job.workdir) or ""
        if not app_id:
            return ""
        job_dir = history.list_job_dirs(self.history_root).get(app_id)
        if not job_dir:
            return ""
        path = history.find_history_file(job_dir)
        if not path:
            return ""
        meta = history.parse_metadata(os.path.basename(path))
        return meta.status if meta is not None else ""


def _discover_app(job_workdir: str) -> Optional[str]:
    """The app id of the single job submitted from ``job_workdir`` (the
    client creates ``jobs/<app_id>/`` there); newest wins if a re-grant
    ever left a sibling."""
    jobs_dir = os.path.join(job_workdir, "jobs")
    try:
        entries = sorted(os.listdir(jobs_dir))
    except OSError:
        return None
    return entries[-1] if entries else None


class SubprocessJobRunner:
    """Carries fleet decisions out against the real single-job stack:
    spawn = one ``tony-tpu submit`` client subprocess (session leader —
    it survives a daemon SIGKILL), resize/kill = RPCs against the job's
    coordinator address file. Tests substitute a fake with the same
    surface."""

    def __init__(self, python: str = sys.executable) -> None:
        self.python = python

    def spawn(self, job_workdir: str,
              overrides: Dict[str, str]) -> subprocess.Popen:
        os.makedirs(job_workdir, exist_ok=True)
        cmd = [self.python, "-m", "tony_tpu.cli", "submit",
               "--workdir", job_workdir]
        for k in sorted(overrides):
            cmd += ["--conf", f"{k}={overrides[k]}"]
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep +
                             env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        clog = open(os.path.join(job_workdir, "client.log"), "ab")
        popen = subprocess.Popen(cmd, stdout=clog,
                                 stderr=subprocess.STDOUT, env=env,
                                 start_new_session=True)
        clog.close()
        return popen

    def poll(self, handle: Any) -> Optional[int]:
        return handle.poll()

    def _coordinator_rpc(self, job_workdir: str) -> Optional[Any]:
        app_id = _discover_app(job_workdir)
        if app_id is None:
            return None
        addr_path = os.path.join(job_workdir, "jobs", app_id,
                                 "coordinator.addr")
        try:
            with open(addr_path, encoding="utf-8") as f:
                addr = json.load(f)
        except (OSError, ValueError):
            return None
        from tony_tpu.rpc.wire import RpcClient

        return RpcClient(addr["host"], int(addr["port"]),
                         token=addr.get("token") or None,
                         max_retries=2, retry_sleep_s=0.2,
                         connect_timeout_s=5.0, call_timeout_s=15.0,
                         peer="coordinator")

    def resize(self, job_workdir: str, size: int) -> bool:
        """Elastic resize (shrink = preempt-to-reclaim, grow =
        grow-back restore) via the job's own resize_application RPC."""
        rpc = self._coordinator_rpc(job_workdir)
        if rpc is None:
            return False
        try:
            res = rpc.call("resize_application", size=int(size), job="")
            return bool(isinstance(res, dict) and res.get("ok"))
        except Exception as e:  # noqa: BLE001 — a dead victim is a no
            log.warning("fleet resize of %s to %d failed: %s",
                        job_workdir, size, e)
            return False
        finally:
            rpc.close()

    def migrate(self, job_workdir: str, target: str) -> bool:
        """Live migration (defrag repack / slice evacuation) via the
        job's own migrate_application RPC — the coordinator's
        drain→move→reshard op, no epoch burned. A refusal (op already
        in flight, unreachable) is a no; the daemon retries next
        tick."""
        rpc = self._coordinator_rpc(job_workdir)
        if rpc is None:
            return False
        try:
            res = rpc.call("migrate_application", target=str(target),
                           job="")
            return bool(isinstance(res, dict) and res.get("ok"))
        except Exception as e:  # noqa: BLE001 — a dead mover is a no
            log.warning("fleet migrate of %s to %r failed: %s",
                        job_workdir, target, e)
            return False
        finally:
            rpc.close()

    def kill(self, job_workdir: str) -> bool:
        rpc = self._coordinator_rpc(job_workdir)
        if rpc is None:
            return False
        try:
            rpc.call("kill_application")
            return True
        except Exception as e:  # noqa: BLE001
            log.warning("fleet kill of %s failed: %s", job_workdir, e)
            return False
        finally:
            rpc.close()


class _FleetService:
    """RPC surface (rpc/wire.py namespacing: ``fleet.submit`` etc.)."""

    def __init__(self, daemon: "FleetDaemon") -> None:
        self._d = daemon

    def fleet__submit(self, tenant: str, hosts: int, priority: int = 0,
                      min_hosts: int = 0, model: str = "",
                      conf: Optional[dict] = None) -> dict:
        return self._d.submit(str(tenant), int(hosts),
                              priority=int(priority or 0),
                              min_hosts=int(min_hosts or 0),
                              model=str(model or ""),
                              conf=dict(conf or {}))

    def fleet__status(self) -> dict:
        return self._d.status()

    def fleet__cancel(self, job: str) -> dict:
        return self._d.cancel(str(job))

    def fleet__explain(self, job: str) -> dict:
        return self._d.explain(str(job))

    def fleet__migrate(self, job: str, target: int) -> dict:
        return self._d.migrate(str(job), int(target))

    def fleet__cordon(self, host: str, reason: str = "") -> dict:
        return self._d.cordon(str(host), reason=str(reason or ""))

    def fleet__uncordon(self, host: str) -> dict:
        return self._d.uncordon(str(host))

    def fleet__health(self) -> dict:
        return self._d.health_status()

    def fleet__alerts(self) -> dict:
        return self._d.alerts_status()

    def fleet__prom(self) -> dict:
        # Live tony_fleet_* exposition for the portal's /fleet view —
        # the file twin (fleet.prom) only refreshes on the export
        # cadence, so a running daemon answers from the registry.
        return {"text": self._d.metrics.render()}

    def fleet__stop(self) -> bool:
        self._d.request_stop()
        return True


@guarded
class FleetDaemon:
    #: tonyrace registry (devtools/race.py + the guarded-by lint): the
    #: job map, the policy-engine feed (_seq) and the goodput-ledger
    #: caches are shared between the scheduler tick and the
    #: fleet.submit/cancel/status/explain RPC threads — every touch
    #: holds the daemon lock. The scalars are single-writer throttle/
    #: degrade flags (atomic rebinds; a stale read costs one tick).
    GUARDED_BY = {
        "jobs": "_lock",
        "_seq": "_lock",
        "_ledgers": "_lock",
        "_ledger_rollup": "_lock",
        "_grant_waits": "_lock",
        "_preempts_per_job": "_lock",
        "_dying_slices": "_lock",
        "book": "_lock",
        "_health_offsets": "_lock",
        "_ledger_degraded": None,
        "_ledger_next_mono": None,
        "_explain_warned": None,
        "_alerts_degraded": None,
        "_started": None,
    }

    def __init__(self, fleet_dir: str, slices: int = 1,
                 hosts_per_slice: int = 8, quotas: str = "",
                 pool_dir: str = "", cache_root: str = "",
                 tick_s: float = 0.5, recover: bool = False,
                 runner: Optional[Any] = None,
                 reclaim_probe: Optional[Any] = None,
                 python: str = sys.executable,
                 decision_ring: int = 64,
                 ledger_interval_s: float = 5.0,
                 health_conf: Optional[fhealth.HealthConfig] = None
                 ) -> None:
        self.fleet_dir = os.path.abspath(os.path.expanduser(fleet_dir))
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.slices = max(1, int(slices))
        self.hosts_per_slice = max(1, int(hosts_per_slice))
        self.quotas = parse_quotas(quotas)
        self.pool_dir = pool_dir
        self.cache_root = cache_root
        self.tick_s = max(0.05, float(tick_s))
        self.decision_ring = max(2, int(decision_ring))
        self.ledger_interval_s = max(0.0, float(ledger_interval_s))
        self.history_root = os.path.join(self.fleet_dir, "history")
        self.runner = runner if runner is not None \
            else SubprocessJobRunner(python)
        self.engine = PolicyEngine(self.slices, self.hosts_per_slice,
                                   self.quotas)
        self.jobs: Dict[str, _FleetJob] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._started = False
        # Goodput ledger (fleet/ledger.py): per-job folds + rollup,
        # refreshed on a throttle; a fold failure (fleet.ledger fault
        # site) degrades the fleet to counters-only, never a dead tick.
        self._ledgers: Dict[str, Dict[str, Any]] = {}
        self._ledger_rollup: Optional[Dict[str, Any]] = None
        self._ledger_degraded = False
        self._ledger_next_mono = 0.0
        self._explain_warned = False
        self._grant_waits: List[float] = []
        self._preempts_per_job: Dict[str, int] = {}
        # Slice-preemption notices: slices the provider has marked for
        # reclaim. Remembered for the daemon's life and evacuated
        # proactively; ``reclaim_probe`` is an optional callable
        # returning dying slice indices (production: the queued-resource
        # reclaim feed, cluster/gcloud.py reclaim_notices).
        self.reclaim_probe = reclaim_probe
        self._dying_slices: set = set()
        # Host health (fleet/health.py): the per-host failure-attribution
        # ledger + quarantine state machine, kept in lockstep with the
        # policy engine's count accounting. Per-job event-stream tail
        # offsets feed the attribution loop incrementally.
        self.health_cfg = health_conf or fhealth.HealthConfig()
        self.book = fhealth.HostBook(self.slices, self.hosts_per_slice,
                                     self.health_cfg)
        self._health_offsets: Dict[str, int] = {}
        # Alerting (tony_tpu/alerts/): the fleet-scope pack, evaluated
        # each scheduler tick behind the fleet.ledger-style degrade
        # contract (fault site "alerts.eval"); transitions journal
        # write-ahead as REC_FLEET_ALERT and, like cordons, a firing
        # alert survives daemon lives via `fleet start --recover`.
        self.alerts = falerts.AlertEngine(falerts.default_fleet_pack())
        self._alerts_degraded = False

        journal_path = os.path.join(self.fleet_dir,
                                    constants.FLEET_JOURNAL_FILE)
        replayed: Optional[fjournal.FleetReplayState] = None
        if os.path.exists(journal_path):
            replayed = fjournal.replay(journal_path)
            live = [f for f in replayed.jobs.values()
                    if f.state not in fjournal.TERMINAL_STATES]
            if live and not recover:
                raise FleetError(
                    f"fleet dir {self.fleet_dir} holds journaled state "
                    f"for {len(live)} non-terminal job(s) — start with "
                    f"--recover to resume it, or point --dir elsewhere")
        # Generation: strictly monotonic across daemon lives (journal-
        # persisted, fences zombie daemons out of the RPC plane).
        self.generation = (replayed.generation if replayed else 0) + 1
        self.journal = fjournal.FleetJournal(journal_path)
        self.journal.generation(self.generation, self.slices,
                                self.hosts_per_slice,
                                quotas=self.quotas)
        # Fleet-wide trace (tony_tpu/tracing.py): queue spans, job
        # lifetimes, preempt/restore instants — and the trace id every
        # grant injects into its job so `tony-tpu trace --fleet`
        # renders the whole pool on one timeline. A recovered daemon
        # rejoins the original trace id (same contract as a recovered
        # coordinator) and closes the dead life's dangling spans.
        trace_path = os.path.join(self.fleet_dir, constants.TRACE_FILE)
        self.tracer = tracing.Tracer(
            trace_id=tracing.existing_trace_id(trace_path) or None,
            service="fleet", path=trace_path)
        if replayed is not None and recover:
            self._close_stale_spans(trace_path)

        self.metrics = MetricsRegistry()
        self._counters_path = os.path.join(self.fleet_dir,
                                           constants.FLEET_COUNTERS_FILE)
        self.metrics.load_counters(self._counters_path)
        self.events = EventHandler(self.fleet_dir,
                                   constants.FLEET_EVENTS_FILE,
                                   on_emit=self._count_event)
        # The writer thread runs from construction (not start()): every
        # scheduler decision is evented, including ones taken before the
        # RPC plane is up (recovery re-folds, embedded/test daemons).
        self.events.start()
        import secrets

        self.token = secrets.token_hex(16)
        from tony_tpu.rpc.wire import RpcServer

        self.rpc = RpcServer(_FleetService(self), host="127.0.0.1",
                             port=0, token=self.token,
                             generation=self.generation)
        if replayed is not None and recover:
            self._recover(replayed)
            if replayed.alerts:
                self.alerts.seed(replayed.alerts)

    def _close_stale_spans(self, trace_path: str) -> None:
        """A SIGKILLed daemon life leaves its queue/job spans open (B
        with no E). The recovering life owns the log: close them with a
        recovered marker so the fleet export stays zero-unclosed, then
        open fresh spans for the jobs it re-adopts."""
        opens: Dict[str, bool] = {}
        for rec in tracing.load_records(trace_path):
            span = str(rec.get("span", "") or "")
            if rec.get("ev") == "B":
                opens[span] = True
            elif rec.get("ev") == "E":
                opens.pop(span, None)
        now = tracing.now_us()
        self.tracer.write_records([
            {"ev": "E", "span": span, "ts_us": now,
             "args": {"recovered": True,
                      "note": "closed by the recovering daemon"}}
            for span in opens])

    # -- recovery ---------------------------------------------------------
    def _recover(self, st: fjournal.FleetReplayState) -> None:
        """Rebuild queue + accounting from the replayed journal: queued
        jobs re-enqueue in submission order; running jobs are re-adopted
        by their recorded client pid; granted-but-never-started jobs
        re-spawn against their journaled grant; finished jobs keep their
        verdicts for the status surface. Runs before the RPC plane is up,
        but the map/engine mutations take the lock anyway — the
        guarded-by discipline has no single-threaded carve-outs."""
        with self._lock:
            self._seq = st.seq
            # Health fold FIRST (last-wins per host): states land before
            # adoption re-books hosts, so a cordoned-while-assigned host
            # is re-booked to its job and stays cordoned-pending. Free-
            # list membership is resynced after the job loop below.
            now = time.monotonic()
            for rec in st.health.values():
                self.book.apply_record(rec, now)
        for fold in sorted(st.jobs.values(), key=lambda f: f.seq):
            req = JobRequest(fold.job_id, fold.tenant,
                             priority=fold.priority,
                             hosts=fold.hosts_requested,
                             min_hosts=fold.min_hosts, model=fold.model,
                             seq=fold.seq)
            job = _FleetJob(req, fold.conf,
                            os.path.join(self.fleet_dir, "jobs",
                                         fold.job_id),
                            decision_ring=self.decision_ring)
            job.app_id = fold.app_id
            job.pid = fold.pid
            job.exit_code = fold.exit_code
            # Ledger anchors + explain ring survive the daemon: the
            # journal is their write-ahead home, the fold re-seeds them.
            job.submitted_ms = fold.submitted_ms or job.submitted_ms
            job.granted_ms = fold.granted_ms
            job.finished_ms = fold.finished_ms
            job.host_events = list(fold.host_events)
            job.decisions.extend(fold.decisions)
            if fold.decisions:
                # Restore the dedup fence: the recovered life must not
                # re-journal the hold reason it already recorded.
                job.denial = str(fold.decisions[-1].get("reason", ""))
            with self._lock:
                self.jobs[fold.job_id] = job
            if fold.state in fjournal.TERMINAL_STATES:
                job.state = fold.state
                continue
            if fold.state == "QUEUED":
                with self._lock:
                    self.engine.submit(req)
                job.queue_span = self.tracer.start_span(
                    "fleet.queue", task=fold.job_id,
                    attrs={"tenant": fold.tenant, "recovered": True,
                           "priority": fold.priority,
                           "hosts": fold.hosts_requested})
                continue
            # GRANTED / SPAWNED / RUNNING: the grant stands — decide
            # between adopt, respawn, and post-mortem.
            app_id = fold.app_id or _discover_app(job.workdir)
            if fold.pid and _pid_alive(fold.pid):
                with self._lock:
                    self.engine.force_grant(req, fold.hosts,
                                            fold.placement)
                    job.host_ids = self.book.adopt(
                        fold.job_id, dict(fold.placement),
                        fold.host_ids)
                job.state = RUNNING
                job.hosts = fold.hosts
                job.placement = dict(fold.placement)
                job.handle = _AdoptedHandle(fold.pid, self.history_root,
                                            job)
                job.job_span = self.tracer.start_span(
                    "fleet.job", task=fold.job_id,
                    attrs={"tenant": fold.tenant, "hosts": fold.hosts,
                           "app_id": app_id or "", "recovered": True})
                log.info("fleet recover: adopted running job %s "
                         "(client pid %d, app %s)", fold.job_id,
                         fold.pid, app_id or "?")
                # Simultaneous-crash window: the daemon can die BETWEEN
                # a victim's resize RPC and the journal record of it
                # (_apply_preempt lands the resize first, then the
                # accounting). The victim kept draining while we were
                # down — its own write-ahead journal knows the size the
                # gang actually reached, and the books must agree with
                # the gang, not with our torn decision.
                self._reconcile_adopted_size(job, fold, app_id)
            elif app_id:
                # The client is gone but the job got as far as an app
                # dir: read its outcome from history (an unfinished
                # app with a dead client is a crashed job).
                job.app_id = app_id
                handle = _AdoptedHandle(fold.pid or 1, self.history_root,
                                        job)
                status = handle._history_status()
                exit_code = 0 if status == "SUCCEEDED" else 1
                state = fjournal.STATE_FINISHED if exit_code == 0 \
                    else fjournal.STATE_FAILED
                self.journal.state(fold.job_id, state, app_id=app_id,
                                   exit_code=exit_code)
                job.state = state
                job.exit_code = exit_code
                job.finished_ms = int(time.time() * 1000)
                log.info("fleet recover: job %s finished %s while the "
                         "daemon was down", fold.job_id, state)
            else:
                # Granted (journaled write-ahead) but the spawn never
                # produced an app: carry the grant out now — this is
                # the zero-LOST-grants half of the recovery contract
                # (the fgen record above licenses the re-grant).
                with self._lock:
                    self.engine.submit(req)
                job.state = QUEUED
                job.queue_span = self.tracer.start_span(
                    "fleet.queue", task=fold.job_id,
                    attrs={"tenant": fold.tenant, "recovered": True,
                           "regrant": True})
                log.info("fleet recover: re-queued granted-but-never-"
                         "started job %s", fold.job_id)
        # Resume the identical cordon set: drop cordoned hosts out of
        # the free identity lists and mirror the delta into the pool's
        # count accounting (hosts re-booked to adopted jobs are in-use,
        # not free — they cordon at release, the deferred sweep).
        with self._lock:
            for i, n in self.book.resync_free().items():
                for _ in range(n):
                    self.engine.pool.cordon_free(i)
            self._refresh_cordoned_names_locked()
            cordoned = self.book.cordoned_names()
        if cordoned:
            log.warning("fleet recover: resumed health cordon set %s",
                        cordoned)

    def _victim_gang_size(self, job: "_FleetJob",
                          app_id: Optional[str]) -> Optional[int]:
        """The member count an adopted victim's gang ACTUALLY settled
        at, replayed from the victim coordinator's own write-ahead
        journal (its jobs/<app>/session.journal.jsonl). None = unknown
        or still in flight — the caller must then keep the journaled
        (conservative, never-double-grant) accounting."""
        if not app_id:
            return None
        path = os.path.join(job.workdir, "jobs", app_id,
                            constants.JOURNAL_FILE)
        from tony_tpu.coordinator import journal as cjournal

        try:
            st = cjournal.replay(path)
        except Exception:  # noqa: BLE001 — an unreadable victim journal
            return None    # is an unknown, not a recovery failure
        if st.inflight_job:
            # A resize is STILL draining inside the victim: nothing has
            # landed, so the journaled size is the truthful one for now;
            # the ordinary preempt retry completes it (the resize RPC is
            # idempotent about already-at-size).
            return None
        if not st.applied_members:
            return None        # never resized: journaled size stands
        members = next(iter(st.applied_members.values()))
        return len(members)

    def _reconcile_adopted_size(self, job: "_FleetJob",
                                fold: fjournal.JobFold,
                                app_id: Optional[str]) -> None:
        """Complete (or supersede) a resize whose accounting the crash
        window swallowed: the victim's actual gang size wins over the
        journaled grant. A shrink that landed un-journaled frees the
        reclaimed hosts NOW (the demander's grant was equally torn, so
        nothing was double-booked); an un-journaled grow books the
        extra hosts so the pool cannot grant them twice."""
        actual = self._victim_gang_size(job, app_id)
        if actual is None or actual == fold.hosts or actual <= 0:
            return
        job_id = fold.job_id
        if actual < fold.hosts:
            with self._lock:
                placement = self.engine.shrink_applied(job_id, actual)
                job.hosts = actual
                job.placement = placement
                job.host_events.append((int(time.time() * 1000), actual))
                self._reconcile_hosts_locked(job, placement)
            self.journal.preempt(job_id, fold.hosts, actual, "",
                                 placement)
            log.warning(
                "fleet recover: %s gang is at %d host(s) but the journal "
                "granted %d — completing the in-flight shrink the crash "
                "window hid (reclaimed hosts freed, preempt journaled)",
                job_id, actual, fold.hosts)
        else:
            with self._lock:
                delta = self.engine.pool.place(actual - fold.hosts)
                if delta is None:
                    # The pool cannot cover the difference — the books
                    # are over-subscribed either way; keep the journaled
                    # size and say so loudly rather than corrupt the
                    # accounting.
                    log.error(
                        "fleet recover: %s gang is at %d host(s) but "
                        "only %d are journaled and the pool cannot "
                        "cover the difference — accounting left at the "
                        "journaled size", job_id, actual, fold.hosts)
                    return
                placement = self.engine.grow_applied(job_id, delta)
                job.hosts = actual
                job.placement = placement
                job.host_events.append((int(time.time() * 1000), actual))
                self._reconcile_hosts_locked(job, placement)
            self.journal.state(job_id, fjournal.STATE_RESTORED,
                               hosts=actual, placement=placement)
            log.warning(
                "fleet recover: %s gang is at %d host(s) but the journal "
                "granted %d — booking the un-journaled grow so the pool "
                "cannot double-grant those hosts", job_id, actual,
                fold.hosts)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._started = True
        self.rpc.start()
        host, port = self.rpc.address
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_ADDR_FILE),
            json.dumps({"host": host, "port": port, "token": self.token,
                        "pid": os.getpid(),
                        "generation": self.generation}).encode("utf-8"),
            mode=0o600)
        log.info("fleet daemon up at %s:%d (generation %d, %d slice(s) "
                 "x %d hosts, quotas %s)", host, port, self.generation,
                 self.slices, self.hosts_per_slice, self.quotas or "none")

    def run(self) -> int:
        self.start()
        rc = 0
        try:
            while not self._stop_evt.wait(self.tick_s):
                if self.journal.dead is not None:
                    # A submit/RPC handler hit the dead disk first.
                    log.critical(
                        "fleet journal is DEAD (%s) — stopping the "
                        "daemon; restart with `fleet start --recover` "
                        "once the disk is healthy", self.journal.dead)
                    rc = 1
                    break
                try:
                    self.tick()
                except DurableWriteError as e:
                    # The write-ahead journal died (ENOSPC/EIO): STOP.
                    # A daemon that keeps scheduling against a journal
                    # that cannot write ahead makes decisions `--recover`
                    # can never see — worse than being down. Running jobs
                    # are left alone (tenant-owned session leaders); the
                    # committed journal prefix stays replayable.
                    log.critical(
                        "fleet journal is DEAD (%s) — stopping the "
                        "daemon; restart with `fleet start --recover` "
                        "once the disk is healthy", e)
                    rc = 1
                    break
                except Exception:  # noqa: BLE001 — the daemon must live
                    if self.journal.dead is not None:
                        log.critical(
                            "fleet journal is DEAD (%s) — stopping the "
                            "daemon; restart with `fleet start "
                            "--recover` once the disk is healthy",
                            self.journal.dead)
                        rc = 1
                        break
                    log.exception("fleet tick failed")
        finally:
            self._shutdown()
        return rc

    def request_stop(self) -> None:
        self._stop_evt.set()

    def _shutdown(self) -> None:
        # Running jobs are NOT killed: they belong to their tenants and
        # their client/coordinator processes are independent session
        # leaders — the same leave-leased-work-alone posture as the
        # pool daemon's shutdown.
        self._export()
        try:
            os.unlink(os.path.join(self.fleet_dir,
                                   constants.FLEET_ADDR_FILE))
        except OSError:
            pass
        if self._started:
            # Stopping a never-serving TCP server deadlocks in
            # shutdown(); unit tests drive tick() without start().
            self.rpc.stop()
        # Final name == in-progress name: the fleet stream is append-only
        # across daemon lives, never finalized like a job's jhist.
        self.events.stop(constants.FLEET_EVENTS_FILE)
        # Close every span this life still holds open (queued jobs at
        # daemon stop, jobs still running when the operator stops the
        # daemon) so an orderly stop leaves zero unclosed spans.
        with self._lock:
            jobs = list(self.jobs.values())
        for job in jobs:
            job.queue_span.end(daemon_stopped=True)
            job.queue_span = tracing.NULL_SPAN
            job.job_span.end(daemon_stopped=True)
            job.job_span = tracing.NULL_SPAN
        self.tracer.close()
        self.journal.close()

    def _count_event(self, ev: Event) -> None:
        self.metrics.counter("tony_events_total",
                             {"type": ev.type.value},
                             help="job-history events emitted, "
                                  "by type").inc()

    # -- RPC behaviour ----------------------------------------------------
    def submit(self, tenant: str, hosts: int, priority: int = 0,
               min_hosts: int = 0, model: str = "",
               conf: Optional[Dict[str, str]] = None) -> dict:
        if self.journal.dead is not None:
            # Sticky-dead journal: appends silently no-op from here on,
            # so an ack would promise crash-survival the write-ahead
            # log can no longer give. Refuse until --recover.
            return {"ok": False,
                    "message": f"fleet journal is dead "
                               f"({self.journal.dead}); the daemon is "
                               f"stopping — resubmit after `fleet "
                               f"start --recover`"}
        if not tenant:
            return {"ok": False, "message": "submission needs a tenant"}
        if hosts <= 0 or hosts > self.engine.pool.total:
            return {"ok": False,
                    "message": f"hosts must be 1..{self.engine.pool.total} "
                               f"(the pool), got {hosts}"}
        if min_hosts > hosts:
            return {"ok": False,
                    "message": f"min_hosts {min_hosts} > hosts {hosts}"}
        quota = self.quotas.get(tenant, 0)
        if quota > 0 and hosts > quota:
            # Refuse outright rather than queue forever: this request
            # can never be granted under the tenant's quota.
            return {"ok": False,
                    "message": f"{hosts} hosts exceeds tenant "
                               f"{tenant!r}'s quota of {quota}"}
        conf = {str(k): str(v) for k, v in (conf or {}).items()}
        with self._lock:
            self._seq += 1
            seq = self._seq
        job_id = f"fj-{seq:04d}"
        req = JobRequest(job_id, tenant, priority=priority, hosts=hosts,
                         min_hosts=min_hosts, model=model, seq=seq)
        # Write-ahead of the ack: a submission the caller saw accepted
        # must survive a daemon crash into the recovered queue — so a
        # submission that CANNOT be journaled must be refused, never
        # acked on the side of a dead journal (chaos schedule
        # disk.full x submit: the RPC verb must answer, not traceback).
        try:
            self.journal.submit(job_id, tenant, priority, hosts,
                                min_hosts, model, seq, conf)
        except DurableWriteError as e:
            return {"ok": False,
                    "message": f"fleet journal is dead ({e}); the "
                               f"daemon is stopping — resubmit after "
                               f"`fleet start --recover`"}
        job = _FleetJob(req, conf,
                        os.path.join(self.fleet_dir, "jobs", job_id),
                        decision_ring=self.decision_ring)
        job.queue_span = self.tracer.start_span(
            "fleet.queue", task=job_id,
            attrs={"tenant": tenant, "priority": priority,
                   "hosts": hosts, "min_hosts": min_hosts,
                   "model": model})
        with self._lock:
            self.jobs[job_id] = job
            self.engine.submit(req)
        self.events.emit(Event(EventType.FLEET_JOB_QUEUED, {
            "job": job_id, "tenant": tenant, "priority": priority,
            "hosts": hosts, "min_hosts": min_hosts, "model": model}))
        log.info("fleet submit: %s tenant=%s priority=%d hosts=%d",
                 job_id, tenant, priority, hosts)
        return {"ok": True, "job": job_id, "state": QUEUED}

    def cancel(self, job_id: str) -> dict:
        if self.journal.dead is not None:
            return {"ok": False,
                    "message": f"fleet journal is dead "
                               f"({self.journal.dead}); restart with "
                               f"`fleet start --recover`"}
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                return {"ok": False, "message": f"unknown job {job_id!r}"}
            if job.state in fjournal.TERMINAL_STATES:
                return {"ok": False,
                        "message": f"{job_id} already {job.state}"}
            was_queued = job.state == QUEUED
            job.cancelled = True
            if was_queued:
                self.engine.withdraw(job_id)
        if was_queued:
            try:
                self._finish_job(job_id, fjournal.STATE_CANCELLED, None)
            except DurableWriteError as e:
                # Same contract as submit: an RPC verb answers, the
                # daemon's run loop does the dying.
                return {"ok": False,
                        "message": f"fleet journal is dead ({e}); "
                                   f"restart with `fleet start "
                                   f"--recover`"}
            return {"ok": True, "state": fjournal.STATE_CANCELLED}
        # Running: ask its coordinator to die; the poll loop records the
        # exit as CANCELLED (job.cancelled wins over the exit code).
        self.runner.kill(job.workdir)
        return {"ok": True, "state": "CANCELLING"}

    def status(self) -> dict:
        from tony_tpu.coordinator.coordphases import histogram_quantile

        ledger = self._ledger_snapshot()
        tenant_ledgers = (ledger or {}).get("tenants", {})
        with self._lock:
            used = self.engine.tenant_used()
            rows = []
            now = time.monotonic()
            for job in sorted(self.jobs.values(),
                              key=lambda j: j.req.seq):
                wait = job.wait_s if job.wait_s is not None else (
                    now - job.submitted_mono
                    if job.state == QUEUED else None)
                last = job.decisions[-1] if job.decisions else None
                held = ""
                if job.state == QUEUED and last \
                        and last.get("action") != "granted":
                    held = f"{last.get('action')}: " \
                           f"{last.get('reason', '')}"
                rows.append({
                    "job": job.req.job_id, "tenant": job.req.tenant,
                    "priority": job.req.priority, "state": job.state,
                    "hosts_requested": job.req.hosts,
                    "hosts": job.hosts, "model": job.req.model,
                    "app_id": job.app_id, "exit": job.exit_code,
                    "wait_s": round(wait, 3) if wait is not None
                    else None,
                    "denial": job.denial,
                    "held": held})
            queue_depth = self.engine.queue_depth
            free = self.engine.pool.free_total
            cordoned_n = self.engine.pool.cordoned_total
            dying = sorted(self._dying_slices)
            health = {"enabled": self.health_cfg.enabled,
                      "cordoned": self.book.cordoned_names(),
                      "sick_slices": self.book.sick_slices}
        hist = self.metrics.histogram(
            "tony_fleet_queue_wait_seconds",
            buckets=QUEUE_WAIT_BUCKETS_S,
            help="submit-to-grant wait latency").snapshot()
        total = self.slices * self.hosts_per_slice
        tenants = {}
        for t, n in sorted(used.items()):
            row: Dict[str, Any] = {
                "used": n, "quota": self.quotas.get(t, 0) or None}
            lrow = tenant_ledgers.get(t)
            if lrow is not None:
                row["goodput"] = lrow.get("goodput_fraction")
            tenants[t] = row
        # Tenants with a ledger but nothing running still get a row —
        # a tenant whose jobs all finished keeps its goodput visible.
        for t, lrow in sorted(tenant_ledgers.items()):
            tenants.setdefault(t, {
                "used": 0, "quota": self.quotas.get(t, 0) or None,
                "goodput": lrow.get("goodput_fraction")})
        return {
            "fleet_dir": self.fleet_dir, "generation": self.generation,
            "pool": {"slices": self.slices,
                     "hosts_per_slice": self.hosts_per_slice,
                     "total": total,
                     "used": total - free - cordoned_n, "free": free,
                     "cordoned": cordoned_n, "dying": dying},
            "health": health,
            "tenants": tenants,
            "queue_depth": queue_depth,
            "jobs": rows,
            "queue_wait": {
                "p50_s": round(histogram_quantile(hist, 0.5), 4),
                "p99_s": round(histogram_quantile(hist, 0.99), 4),
                "count": hist.get("count", 0)},
            "ledger": ledger,
            "alerts": {"degraded": self._alerts_degraded,
                       "firing": self.alerts.firing()},
            "pool_dir": self.pool_dir,
            "trace_id": self.tracer.trace_id,
        }

    # -- the scheduler tick ----------------------------------------------
    def tick(self) -> None:
        self._poll_jobs()
        self._discover_apps()
        # Health before the plan: this tick's cordons shape this tick's
        # placements, and a sick slice joins _dying_slices in time for
        # _evacuate below.
        self._health_tick()
        self._poll_reclaim()
        self._apply_plan()
        self._evacuate()
        self._restore()
        # Alerts before the export so a transition's gauge/counter
        # updates land in this tick's exposition.
        self._alerts_tick()
        self._export()

    def _poll_jobs(self) -> None:
        done: List[_FleetJob] = []
        with self._lock:
            # Snapshot (job, handle) pairs: a cancel RPC can terminalize
            # a job (handle → None) between this scan and the poll —
            # re-reading job.handle outside the lock would poll None.
            candidates = [(j, j.handle) for j in self.jobs.values()
                          if j.handle is not None
                          and j.state in (GRANTED, RUNNING)]
        for job, handle in candidates:
            rc = self.runner.poll(handle)
            if rc is None:
                continue
            if job.cancelled:
                state = fjournal.STATE_CANCELLED
            elif rc == 0:
                state = fjournal.STATE_FINISHED
            else:
                state = fjournal.STATE_FAILED
            if self._finish_job(job.req.job_id, state, int(rc)):
                done.append(job)
        if done:
            log.info("fleet: %d job(s) finished this tick (%s)",
                     len(done), ", ".join(j.req.job_id for j in done))

    def _finish_job(self, job_id: str, state: str,
                    exit_code: Optional[int]) -> bool:
        """THE terminal-accounting path — every way a fleet job ends
        (poll exit, cancel, spawn failure, recovery post-mortem) funnels
        here so the journal record, pool release, span closure, ledger
        fold and FLEET_JOB_FINISHED event each happen EXACTLY once per
        job. The terminal claim is atomic under the lock: a cancel RPC
        racing the poll tick cannot double-book. Returns False when the
        job was already terminal (nothing re-emitted)."""
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None or job.state in fjournal.TERMINAL_STATES:
                return False
            job.state = state
            job.exit_code = None if exit_code is None else int(exit_code)
            job.handle = None
            job.finished_ms = int(time.time() * 1000)
            self.engine.release(job_id)
            # Deferred cordon sweep + canary resolution: hosts
            # quarantined while this job held them leave service NOW
            # (free -> cordoned), a probation canary resolves on the
            # job's verdict (clean run restores it, a failure
            # re-quarantines with doubled cooldown).
            newly_cordoned, health_recs = self.book.release(
                job_id, time.monotonic(),
                failed=state == fjournal.STATE_FAILED)
            for i, n in newly_cordoned.items():
                for _ in range(n):
                    self.engine.pool.cordon_free(i)
            self._refresh_cordoned_names_locked()
            self._health_offsets.pop(job_id, None)
            app_id = job.app_id
        self.journal.state(job_id, state, app_id=app_id,
                           exit_code=exit_code)
        if health_recs:
            self._apply_health_records(health_recs)
        job.queue_span.end(state=state)        # cancelled while queued
        job.queue_span = tracing.NULL_SPAN
        job.job_span.end(state=state, exit=exit_code)
        job.job_span = tracing.NULL_SPAN
        self._fold_ledger_job(job)
        self.events.emit(Event(EventType.FLEET_JOB_FINISHED, {
            "job": job_id, "state": state, "exit": exit_code,
            "app_id": app_id}))
        return True

    def _discover_apps(self) -> None:
        with self._lock:
            pending = [j for j in self.jobs.values()
                       if j.state == RUNNING and not j.app_id]
        for job in pending:
            app_id = _discover_app(job.workdir)
            if app_id is None:
                continue
            self.journal.state(job.req.job_id, fjournal.STATE_RUNNING,
                               app_id=app_id, pid=job.pid)
            with self._lock:
                job.app_id = app_id

    def _apply_plan(self) -> None:
        with self._lock:
            plan = self.engine.schedule()
        for d in plan:
            if d.action == GRANT:
                if not self._apply_grant(d.job_id, d.placement):
                    return          # retry the rest next tick
            elif d.action == SHRINK:
                if not self._apply_preempt(d.job_id, d.hosts, d.for_job,
                                           d.reason):
                    return
            elif d.action == MIGRATE:
                if not self._apply_migrate(d):
                    return
            elif d.action in HOLD_ACTIONS:
                self._note_decision(d)

    def _note_decision(self, d: Decision) -> None:
        """The scheduler decision explainer's recorder: a queued job's
        not-placed reason TRANSITIONED. Dedup per transition (never per
        tick), then three sinks — the bounded per-job ring behind
        `fleet explain`, a write-ahead REC_FLEET_DECISION journal
        record (fault site ``fleet.explain``: a failed write warns once
        and never blocks the decision), and a FLEET_JOB_HELD event."""
        with self._lock:
            job = self.jobs.get(d.job_id)
            if job is None or job.state != QUEUED:
                return
            if job.denial == d.reason:
                return             # same hold as last tick: no news
            prev_action = job.decisions[-1].get("action") \
                if job.decisions else ""
            job.denial = d.reason
            entry = {"ts_ms": int(time.time() * 1000),
                     "action": d.action, "reason": d.reason,
                     "blocking": list(d.blocking), "free": int(d.free)}
            job.decisions.append(entry)
        try:
            faults.check("fleet.explain")
            self.journal.decision(d.job_id, d.action, d.reason,
                                  blocking=d.blocking, free=d.free)
        except faults.InjectedFault as e:
            if not self._explain_warned:
                self._explain_warned = True
                log.warning(
                    "fleet: decision-record write failed (%s) — the "
                    "decision ring and events still carry the "
                    "explainer; the journal will miss hold records "
                    "until the daemon restarts", e)
        self.tracer.instant("fleet.held", parent=job.queue_span,
                            task=d.job_id,
                            attrs={"action": d.action,
                                   "reason": d.reason,
                                   "blocking": list(d.blocking)})
        self.events.emit(Event(EventType.FLEET_JOB_HELD, {
            "job": d.job_id, "action": d.action, "reason": d.reason,
            "blocking": list(d.blocking)}))
        if d.action == QUOTA_DENIED and prev_action != QUOTA_DENIED:
            # The legacy quota event dedups on ACTION: a reason-wording
            # refinement (the blocking list filling in) is a new ring/
            # journal entry but not a second QUOTA_DENIED episode.
            self.metrics.counter(
                "tony_fleet_quota_denials_total",
                help="grants deferred by tenant quota").inc()
            self.events.emit(Event(EventType.FLEET_QUOTA_DENIED, {
                "job": d.job_id, "reason": d.reason}))

    def _grant_overrides(self, job: _FleetJob) -> Dict[str, str]:
        """The fleet's injections on a granted job's conf: granted gang
        size, elastic preemptibility, the shared warm pool, the
        per-model compile cache, and the fleet history root (one portal
        over every tenant's jobs). The submission's own keys win where
        they name the same knob explicitly."""
        ov = dict(job.conf)
        ov["tony.worker.instances"] = str(job.hosts)
        if 0 < job.req.min_hosts < job.req.hosts:
            ov.setdefault(K.ELASTIC_ENABLED, "true")
            ov.setdefault(K.ELASTIC_MIN_TASKS, str(job.req.min_hosts))
        if self.pool_dir:
            ov.setdefault(K.POOL_DIR, self.pool_dir)
        if self.cache_root and job.req.model:
            ov.setdefault(K.JAX_COMPILE_CACHE_DIR,
                          os.path.join(self.cache_root, job.req.model))
        ov.setdefault(K.HISTORY_LOCATION, self.history_root)
        # Cross-layer trace stitching: the grant stamps the fleet trace
        # id into the job's conf; the client adopts it instead of
        # minting its own, so the whole pool renders as ONE Perfetto
        # timeline (`tony-tpu trace --fleet <fleet_dir>`).
        if self.tracer.enabled:
            ov[K.INTERNAL_FLEET_TRACE_ID] = self.tracer.trace_id
            if getattr(job.job_span, "span_id", ""):
                ov[K.INTERNAL_FLEET_TRACE_PARENT] = job.job_span.span_id
        return ov

    def _apply_grant(self, job_id: str,
                     placement: Dict[int, int]) -> bool:
        try:
            faults.check("fleet.grant")
        except faults.InjectedFault as e:
            # The job stays QUEUED (nothing journaled, nothing
            # accounted) and the next tick retries — a grant failure
            # must never lose a submission.
            log.warning("fleet grant of %s failed (%s); job stays "
                        "queued", job_id, e)
            return False
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None or job.state != QUEUED:
                return True         # cancelled mid-plan: skip
        hosts = sum(placement.values())
        # Concrete host identities + preflight probes (fleet/health.py):
        # a probe failure cordons the bad host and substitutes a spare
        # (the self-repairing grant); an uncoverable placement stays
        # queued and the next tick re-plans around the cordons.
        host_ids: List[str] = []
        canary_recs: List[Dict[str, Any]] = []
        if self.health_cfg.enabled:
            assigned = self._assign_with_probe(job, placement)
            if assigned is None:
                return False
            host_ids, canary_recs = assigned
        # Write-ahead: the grant record lands before the spawn, so a
        # crash in between recovers into "re-carry the grant out", never
        # a lost grant.
        self.journal.grant(job_id, hosts, placement,
                           host_ids=host_ids or None)
        with self._lock:
            try:
                self.engine.grant(job_id, placement)
            except KeyError:
                # Withdrawn between plan and apply: give the picked
                # identities back (canaries keep their probation state).
                self.book.unassign(job_id)
                return True
            job.state = GRANTED
            job.host_ids = host_ids
            job.hosts = hosts
            job.placement = dict(placement)
            job.wait_s = time.monotonic() - job.submitted_mono
            job.denial = ""
            job.granted_ms = int(time.time() * 1000)
            job.host_events = [(job.granted_ms, hosts)]
            self._grant_waits.append(job.wait_s)
            del self._grant_waits[:-512]
            # The grant closes the job's hold timeline in the ring.
            job.decisions.append({
                "ts_ms": job.granted_ms, "action": "granted",
                "reason": f"granted {hosts} host(s) on slice(s) "
                          f"{sorted(placement)} after "
                          f"{job.wait_s:.2f}s", "blocking": [],
                "free": 0})
        if canary_recs:
            # The probation canary took one of the granted slots: the
            # pool slot it vacated returns to accounting (uncordon) now
            # that the grant's own booking has landed.
            self._apply_health_records(canary_recs)
            log.info("fleet grant %s: probation canary %s riding "
                     "along", job_id,
                     [r.get("host") for r in canary_recs])
        job.queue_span.end(wait_s=round(job.wait_s, 3), granted=True)
        job.queue_span = tracing.NULL_SPAN
        job.job_span = self.tracer.start_span(
            "fleet.job", task=job_id,
            attrs={"tenant": job.req.tenant, "hosts": hosts,
                   "placement": {str(i): n
                                 for i, n in sorted(placement.items())}})
        try:
            popen = self.runner.spawn(job.workdir,
                                      self._grant_overrides(job))
        except OSError as e:
            log.error("fleet: spawn of %s failed: %s", job_id, e)
            self._finish_job(job_id, fjournal.STATE_FAILED, 1)
            return True
        self.journal.state(job_id, fjournal.STATE_SPAWNED,
                           pid=popen.pid)
        with self._lock:
            job.handle = popen
            job.pid = popen.pid
            job.state = RUNNING
        self.metrics.counter("tony_fleet_grants_total",
                             help="job grants applied").inc()
        self.metrics.histogram(
            "tony_fleet_queue_wait_seconds",
            buckets=QUEUE_WAIT_BUCKETS_S,
            help="submit-to-grant wait latency").observe(job.wait_s)
        self.events.emit(Event(EventType.FLEET_JOB_GRANTED, {
            "job": job_id, "tenant": job.req.tenant, "hosts": hosts,
            "placement": {str(i): n for i, n in placement.items()},
            "wait_s": round(job.wait_s, 3)}))
        log.info("fleet grant: %s -> %d host(s) on slice(s) %s "
                 "(waited %.2fs)", job_id, hosts,
                 sorted(placement), job.wait_s)
        return True

    def _apply_preempt(self, victim_id: str, to_hosts: int,
                       for_job: str, reason: str) -> bool:
        try:
            faults.check("fleet.preempt")
        except faults.InjectedFault as e:
            log.warning("fleet preempt of %s failed (%s); retried next "
                        "tick", victim_id, e)
            return False
        with self._lock:
            victim = self.jobs.get(victim_id)
            if victim is None or victim.state != RUNNING:
                return True
            from_hosts = victim.hosts
        # The victim shrinks through its own elastic machinery
        # (drain→remesh→barrier — coordinator/elastic.py): the epoch
        # survives, nothing is killed. The resize lands first, then the
        # accounting: a crash between the two under-frees for one
        # recovery (grow-back reconciles) rather than double-booking.
        if not self.runner.resize(victim.workdir, to_hosts):
            log.warning("fleet preempt: %s resize to %d refused/"
                        "unreachable; retried next tick", victim_id,
                        to_hosts)
            return False
        with self._lock:
            new_placement = self.engine.shrink_applied(victim_id,
                                                       to_hosts)
            victim.hosts = to_hosts
            victim.placement = new_placement
            victim.host_events.append((int(time.time() * 1000),
                                       to_hosts))
            self._preempts_per_job[victim_id] = \
                self._preempts_per_job.get(victim_id, 0) + 1
            self._reconcile_hosts_locked(victim, new_placement)
        self.journal.preempt(victim_id, from_hosts, to_hosts, for_job,
                             new_placement)
        self.tracer.instant("fleet.preempt", parent=victim.job_span,
                            task=victim_id,
                            attrs={"from": from_hosts, "to": to_hosts,
                                   "for": for_job, "reason": reason})
        self.metrics.counter(
            "tony_fleet_preemptions_total",
            help="preempt-to-reclaim shrinks applied").inc()
        self.events.emit(Event(EventType.FLEET_JOB_PREEMPTED, {
            "job": victim_id, "from": from_hosts, "to": to_hosts,
            "for": for_job, "reason": reason}))
        log.warning("fleet preempt: %s shrunk %d->%d host(s) for %s",
                    victim_id, from_hosts, to_hosts, for_job)
        return True

    # -- live migration (coordinator/migrate.py over the fleet) -----------
    @staticmethod
    def _slice_pool(i: int) -> str:
        """The node-pool name slice ``i`` presents to coordinators —
        the migrate RPC's target string (symbolic on LocalSim)."""
        return f"slice-{int(i)}"

    def _poll_reclaim(self) -> None:
        """Slice-preemption notice intake. Two feeds: the
        ``slice.preempt`` fault site (drills: each daemon tick is one
        call; the injected notice marks the lowest-indexed slice still
        holding running jobs as dying) and the optional
        ``reclaim_probe``. A dying slice is remembered for the daemon's
        life and evacuated proactively every tick (_evacuate)."""
        notices: List[int] = []
        try:
            faults.check("slice.preempt")
        except faults.InjectedFault:
            with self._lock:
                held = sorted(
                    i for j in self.jobs.values()
                    if j.state == RUNNING for i in j.placement)
            if held:
                notices.append(held[0])
        if self.reclaim_probe is not None:
            try:
                notices.extend(int(i) for i in self.reclaim_probe())
            except Exception as e:  # noqa: BLE001 — a flaky feed is no notice
                log.debug("fleet reclaim probe failed: %s", e)
        fresh: List[int] = []
        with self._lock:
            for i in notices:
                if 0 <= i < self.slices and i not in self._dying_slices:
                    self._dying_slices.add(i)
                    fresh.append(i)
        for i in fresh:
            self.metrics.counter(
                "tony_fleet_reclaim_notices_total",
                help="slice-preemption notices received").inc()
            self.tracer.instant("fleet.reclaim-notice",
                                attrs={"slice": i})
            log.warning("fleet: slice %d preemption notice — evacuating "
                        "its jobs by live migration", i)

    def _evacuate(self) -> None:
        """Move every elastic job off the dying slices (policy
        ``evacuation_candidates``); jobs with no landing room stay and
        the ordinary host-loss ladder absorbs them when the slice
        actually dies."""
        with self._lock:
            dying = sorted(self._dying_slices)
            moves = self.engine.evacuation_candidates(dying) \
                if dying else []
        for d in moves:
            if not self._apply_migrate(d):
                return              # retry the rest next tick

    def _apply_migrate(self, d: Decision) -> bool:
        with self._lock:
            job = self.jobs.get(d.job_id)
            if job is None or job.state != RUNNING:
                return True
        # The move lands through the job's own coordinator (drain →
        # async snapshot → relaunch on the target), then the
        # accounting — same order as preempt: a crash in between
        # leaves the journal one move behind, which the next life's
        # placement replay tolerates (host COUNT never drifts).
        if not self.runner.migrate(job.workdir,
                                   self._slice_pool(d.target)):
            log.warning("fleet migrate: %s move to slice %d refused/"
                        "unreachable; retried next tick", d.job_id,
                        d.target)
            return False
        with self._lock:
            placement = self.engine.migrate_applied(d.job_id,
                                                    d.placement)
            job.placement = placement
            self._reconcile_hosts_locked(job, placement)
        self.journal.migrate(d.job_id, d.source, d.target, placement,
                             reason=d.reason)
        self.tracer.instant("fleet.migrate", parent=job.job_span,
                            task=d.job_id,
                            attrs={"source": d.source,
                                   "target": d.target,
                                   "reason": d.reason})
        self.metrics.counter("tony_fleet_migrations_total",
                             help="live job migrations applied").inc()
        self.events.emit(Event(EventType.FLEET_JOB_MIGRATED, {
            "job": d.job_id, "source": d.source, "target": d.target,
            "reason": d.reason}))
        log.warning("fleet migrate: %s moved slice %d -> %d (%s)",
                    d.job_id, d.source, d.target, d.reason)
        return True

    def migrate(self, job_id: str, target: int) -> dict:
        """`tony-tpu fleet migrate <job> <slice>`: operator-initiated
        live move (defrag by hand, pre-maintenance evacuation)."""
        t = int(target)
        if self.journal.dead is not None:
            return {"ok": False,
                    "message": f"fleet journal is dead "
                               f"({self.journal.dead}); restart with "
                               f"`fleet start --recover`"}
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                return {"ok": False,
                        "message": f"unknown job {job_id!r}"}
            if job.state != RUNNING:
                return {"ok": False,
                        "message": f"{job_id} is {job.state}, not "
                                   f"RUNNING"}
            if not 0 <= t < self.slices:
                return {"ok": False,
                        "message": f"target slice {t} outside the pool "
                                   f"(0..{self.slices - 1})"}
            if set(job.placement) == {t}:
                return {"ok": False,
                        "message": f"{job_id} already runs on slice "
                                   f"{t}"}
            trial = self.engine.pool.clone()
            trial.release(job.placement)
            free_t = trial.free_on(t)
            if free_t < job.hosts:
                return {"ok": False,
                        "message": f"slice {t} has only {free_t} free "
                                   f"host(s); {job_id} holds "
                                   f"{job.hosts}"}
            d = Decision(MIGRATE, job_id, hosts=job.hosts,
                         placement={t: job.hosts},
                         source=min(job.placement), target=t,
                         reason=f"operator migrate to slice {t}")
        try:
            applied = self._apply_migrate(d)
        except DurableWriteError as e:
            return {"ok": False,
                    "message": f"fleet journal is dead ({e}); restart "
                               f"with `fleet start --recover`"}
        if not applied:
            return {"ok": False,
                    "message": "the job's coordinator refused the move "
                               "or is unreachable — see the daemon log"}
        return {"ok": True, "job": job_id, "source": d.source,
                "target": t, "placement": {str(t): job.hosts}}

    def _restore(self) -> None:
        """Grow shrunk victims back toward their requested size once the
        queue has drained — preemption is a loan. The grow rides the
        same elastic resize path (and, with a warm pool configured, the
        fresh members adopt pre-warmed executors — the ≤2s regrow)."""
        with self._lock:
            candidates = self.engine.restore_candidates()
        for job_id, new_hosts, delta in candidates:
            with self._lock:
                job = self.jobs.get(job_id)
                if job is None or job.state != RUNNING:
                    continue
            if not self.runner.resize(job.workdir, new_hosts):
                continue
            with self._lock:
                placement = self.engine.grow_applied(job_id, delta)
                job.hosts = new_hosts
                job.placement = placement
                job.host_events.append((int(time.time() * 1000),
                                        new_hosts))
                self._reconcile_hosts_locked(job, placement)
            self.journal.state(job_id, fjournal.STATE_RESTORED,
                               hosts=new_hosts, placement=placement)
            self.tracer.instant("fleet.restore", parent=job.job_span,
                                task=job_id,
                                attrs={"hosts": new_hosts})
            log.info("fleet restore: %s grown back to %d host(s)",
                     job_id, new_hosts)

    # -- host health (tony_tpu/fleet/health.py) ---------------------------
    def _refresh_cordoned_names_locked(self) -> None:
        """Caller holds the lock. The CAPACITY_DENIED explainer names
        cordoned hosts that are actually out of the pool — a probation
        canary currently leased to a job is in-use, not a hold cause."""
        leased = {h for hs in self.book.assigned.values() for h in hs}
        self.engine.cordoned_names = [
            n for n in self.book.cordoned_names() if n not in leased]

    def _reconcile_hosts_locked(self, job: _FleetJob,
                         placement: Dict[int, int]) -> None:
        """Caller holds the lock. A resize/migration changed the job's
        per-slice counts: trim/extend its concrete host set to match,
        moving any freed cordon-pending slot out of the pool's free
        accounting (a shrink is the fastest way to get a sick slot out
        of a live gang — the book frees those first)."""
        for i, n in self.book.reconcile(job.req.job_id,
                                        placement).items():
            for _ in range(n):
                self.engine.pool.cordon_free(i)
        job.host_ids = list(self.book.assigned.get(job.req.job_id)
                            or [])
        self._refresh_cordoned_names_locked()

    def _apply_health_records(
            self, records: List[Dict[str, Any]]) -> None:
        """Land a batch of host-health transitions: write-ahead journal
        each record, mirror the free/cordoned delta into the pool's
        count accounting, and emit the operator-facing events. Journal
        appends run OUTSIDE the lock (they fsync)."""
        for rec in records:
            self.journal.health(rec)
            i = int(rec.get("slice", -1))
            with self._lock:
                if rec.get("canary") or rec.get("now_free"):
                    self.engine.pool.uncordon(i)
                elif rec.get("was_free"):
                    self.engine.pool.cordon_free(i)
                self._refresh_cordoned_names_locked()
            state = str(rec.get("state", ""))
            if state == fhealth.QUARANTINED:
                self.metrics.counter(
                    "tony_fleet_quarantines_total",
                    help="host quarantines applied (score, probe, "
                         "manual, sick-slice)").inc()
                self.events.emit(Event(EventType.FLEET_HOST_QUARANTINED, {
                    "host": rec.get("host", ""), "slice": i,
                    "score": rec.get("score", 0.0),
                    "manual": bool(rec.get("manual")),
                    "reason": rec.get("reason", "")}))
                log.warning("fleet health: %s quarantined (%s)",
                            rec.get("host"), rec.get("reason"))
            elif state == fhealth.HEALTHY and (
                    rec.get("now_free") is not None
                    or "canary" in str(rec.get("reason", ""))):
                self.events.emit(Event(EventType.FLEET_HOST_RESTORED, {
                    "host": rec.get("host", ""), "slice": i,
                    "reason": rec.get("reason", "")}))
                log.info("fleet health: %s restored (%s)",
                         rec.get("host"), rec.get("reason"))

    def _tail_job_events(self, job: _FleetJob,
                         path: str) -> List[Dict[str, Any]]:
        """Incremental tail of one job's event stream from the last
        byte offset: complete JSON lines only (a torn tail stays unread
        until its newline lands), offsets survive file finalization via
        monotonic-size heuristics (the rename keeps the content)."""
        job_id = job.req.job_id
        with self._lock:
            offset = self._health_offsets.get(job_id, 0)
        try:
            size = os.path.getsize(path)
            if size <= offset:
                return []
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            return []
        # Only complete lines advance the offset.
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        with self._lock:
            self._health_offsets[job_id] = offset + end + 1
        out: List[Dict[str, Any]] = []
        for raw in chunk[:end].split(b"\n"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                out.append(json.loads(raw.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    #: event-type -> evidence kind for non-TASK_FINISHED feeders
    _HEALTH_EVENT_KINDS = {"TASK_HUNG": "hang",
                           "TASK_STRAGGLER": "straggler"}

    def _attribute_failures(self) -> List[Any]:
        """(host, kind, job_id, ts_ms) attributions tailed from running
        jobs' event streams: TASK_FINISHED with an infra failure domain
        (heartbeat expiries and host.loss absorbs arrive this way,
        domain INFRA_TRANSIENT), hang kills, straggler flags.
        USER_ERROR never counts — a user bug says nothing about the
        machine."""
        with self._lock:
            running = [(j, list(j.host_ids)) for j in self.jobs.values()
                       if j.state == RUNNING and j.app_id and j.host_ids]
        if not running:
            return []
        dirs = fledger.job_history_dirs(self.fleet_dir)
        out: List[Any] = []
        for job, host_ids in running:
            job_dir = dirs.get(job.app_id)
            if not job_dir:
                continue
            path = None
            try:
                for name in sorted(os.listdir(job_dir)):
                    if name.endswith(constants.EVENTS_SUFFIX) \
                            or name.endswith(constants.INPROGRESS_SUFFIX):
                        path = os.path.join(job_dir, name)
                        break
            except OSError:
                continue
            if path is None:
                continue
            for rec in self._tail_job_events(job, path):
                etype = str(rec.get("type", ""))
                payload = rec.get("event") or {}
                ts_ms = int(rec.get("timestamp", 0) or 0)
                task = str(payload.get("task", "") or "")
                kind = ""
                if etype == "TASK_FINISHED":
                    kind = str(payload.get("failure_domain", "") or "")
                    if kind not in ("INFRA_TRANSIENT", "PREEMPTION"):
                        continue    # success or USER_ERROR: no evidence
                else:
                    kind = self._HEALTH_EVENT_KINDS.get(etype, "")
                    if not kind:
                        continue
                try:
                    idx = int(task.rsplit(":", 1)[-1])
                except ValueError:
                    continue
                host = host_ids[idx % len(host_ids)]
                out.append((host, kind, job.req.job_id, ts_ms))
        return out

    def _health_tick(self) -> None:
        """The attribution + state-machine pass, before the scheduler
        plan so fresh cordons shape this tick's placements. Also the
        ``host.flaky`` drill feed: a fired site kills the pinned host's
        job (the real-world analogue is the task dying there) and
        attributes the failure."""
        if not self.health_cfg.enabled:
            return
        now = time.monotonic()
        attributions = self._attribute_failures()
        with self._lock:
            running = [(j, list(j.host_ids)) for j in self.jobs.values()
                       if j.state == RUNNING and j.host_ids]
        for job, host_ids in running:
            for host in host_ids:
                if faults.fire("host.flaky", task_id=host):
                    attributions.append(
                        (host, "INFRA_TRANSIENT", job.req.job_id,
                         int(time.time() * 1000)))
                    log.warning(
                        "fleet health: host.flaky fired on %s — "
                        "killing %s (drill)", host, job.req.job_id)
                    self.runner.kill(job.workdir)
                    with self._lock:
                        # The fake runners used in drills have no
                        # process to reap; mark the exit so _poll_jobs
                        # terminalizes the job this tick. Real Popen
                        # handles reap through poll() as usual.
                        if job.handle is not None \
                                and not isinstance(job.handle,
                                                   subprocess.Popen) \
                                and getattr(job.handle, "returncode",
                                            137) is None:
                            job.handle.returncode = 137
        records: List[Dict[str, Any]] = []
        with self._lock:
            for host, kind, job_id, ts_ms in attributions:
                records.extend(self.book.record_failure(
                    host, kind, job_id, now, ts_ms=ts_ms))
            tick_recs, sick = self.book.tick(now)
            records.extend(tick_recs)
        if records:
            self._apply_health_records(records)
        for i in sick:
            self.metrics.counter(
                "tony_fleet_sick_slices_total",
                help="whole-slice cordons from correlated host "
                     "failures").inc()
            self.events.emit(Event(EventType.FLEET_SLICE_CORDONED, {
                "slice": i, "blast_n": self.health_cfg.blast_n,
                "window_s": self.health_cfg.blast_window_s}))
            log.warning("fleet health: slice %d is SICK (>= %d "
                        "correlated suspects) — cordoned, evacuating "
                        "its jobs", i, self.health_cfg.blast_n)
            with self._lock:
                self._dying_slices.add(i)

    def _assign_with_probe(
            self, job: _FleetJob, placement: Dict[int, int]
    ) -> Optional[Any]:
        """Pick concrete hosts for a grant and preflight-probe each.
        A probe failure cordons the host and the loop re-picks with a
        spare substituted — the grant self-repairs instead of failing
        the job. Returns (host_ids, canary records), or None when the
        placement can no longer be covered (the job stays queued; the
        next tick re-plans around the new cordons)."""
        job_id = job.req.job_id
        probe_dir = os.path.join(self.fleet_dir, "probe")
        for _ in range(self.slices * self.hosts_per_slice + 1):
            now = time.monotonic()
            with self._lock:
                try:
                    host_ids, canaries = self.book.assign(
                        job_id, placement, job.req.priority, now)
                except ValueError as e:
                    log.warning("fleet health: grant of %s cannot be "
                                "covered (%s); job stays queued",
                                job_id, e)
                    return None
            failed = []
            for h in host_ids:
                why = fhealth.preflight_probe(h, probe_dir)
                if why is not None:
                    failed.append((h, why))
            if not failed:
                return host_ids, canaries
            recs: List[Dict[str, Any]] = []
            with self._lock:
                self.book.unassign(job_id)
                for h, why in failed:
                    rec = self.book.cordon(
                        h, reason=f"preflight probe failed: {why}",
                        now=now, kind="probe",
                        ts_ms=int(time.time() * 1000))
                    if rec is not None:
                        recs.append(rec)
            self._apply_health_records(recs)
            log.warning("fleet grant %s: preflight probe cordoned "
                        "%s — substituting spare(s)", job_id,
                        [h for h, _ in failed])
        return None

    # -- operator verbs (fleet cordon|uncordon|health) --------------------
    def cordon(self, host: str, reason: str = "") -> dict:
        if self.journal.dead is not None:
            return {"ok": False,
                    "message": f"fleet journal is dead "
                               f"({self.journal.dead}); restart with "
                               f"`fleet start --recover`"}
        why = f"operator cordon: {reason}" if reason \
            else "operator cordon"
        with self._lock:
            rec = self.book.cordon(host, reason=why,
                                   now=time.monotonic(), manual=True)
        if rec is None:
            return {"ok": False, "message": f"unknown host {host!r} "
                    f"(hosts are s<slice>h<index>)"}
        try:
            self._apply_health_records([rec])
        except DurableWriteError as e:
            return {"ok": False,
                    "message": f"fleet journal is dead ({e}); restart "
                               f"with `fleet start --recover`"}
        return {"ok": True, "host": host, "state": rec["state"],
                "was_free": bool(rec.get("was_free"))}

    def uncordon(self, host: str) -> dict:
        if self.journal.dead is not None:
            return {"ok": False,
                    "message": f"fleet journal is dead "
                               f"({self.journal.dead}); restart with "
                               f"`fleet start --recover`"}
        with self._lock:
            rec = self.book.uncordon(host, now=time.monotonic())
        if rec is None:
            return {"ok": False,
                    "message": f"host {host!r} is unknown or not "
                               f"cordoned"}
        try:
            self._apply_health_records([rec])
        except DurableWriteError as e:
            return {"ok": False,
                    "message": f"fleet journal is dead ({e}); restart "
                               f"with `fleet start --recover`"}
        return {"ok": True, "host": host, "state": rec["state"],
                "leased": not bool(rec.get("now_free"))}

    def health_status(self) -> dict:
        """`tony-tpu fleet health`: the per-host ledger, worst first."""
        with self._lock:
            rows = self.book.snapshot(time.monotonic())
            cordoned = self.book.cordoned_names()
            sick = self.book.sick_slices
        return {"ok": True, "enabled": self.health_cfg.enabled,
                "hosts": rows, "cordoned": cordoned,
                "sick_slices": sick}

    # -- goodput ledger (tony_tpu/fleet/ledger.py) ------------------------
    def _ledger_fold_input(self, job: _FleetJob) -> fjournal.JobFold:
        return fjournal.JobFold(
            job_id=job.req.job_id, tenant=job.req.tenant,
            priority=job.req.priority, hosts_requested=job.req.hosts,
            min_hosts=job.req.min_hosts, model=job.req.model,
            seq=job.req.seq, state=job.state, hosts=job.hosts,
            app_id=job.app_id, submitted_ms=job.submitted_ms,
            granted_ms=job.granted_ms, finished_ms=job.finished_ms,
            host_events=list(job.host_events))

    def _fold_ledger_job(self, job: _FleetJob,
                         dirs: Optional[Dict[str, str]] = None) -> None:
        """Fold ONE job's ledger (terminal jobs fold exactly once, at
        finish). Fault site ``fleet.ledger``: any failure degrades the
        fleet to counters-only — goodput gauges and the per-tenant
        table go absent, the scheduler tick never blocks."""
        if self._ledger_degraded:
            return
        try:
            faults.check("fleet.ledger")
            if dirs is None:
                dirs = fledger.job_history_dirs(self.fleet_dir)
            # Compute OUTSIDE the lock (the fold reads job-dir files);
            # only the cache install is a critical section — status()
            # RPC threads snapshot the same maps under the same lock.
            row = fledger.compute_job_ledger(
                self._ledger_fold_input(job),
                job_dir=dirs.get(job.app_id),
                now_ms=int(time.time() * 1000))
            with self._lock:
                self._ledgers[job.req.job_id] = row
                self._ledger_rollup = None  # dirty: rebuilt on export
        except Exception as e:  # noqa: BLE001 — observability, not duty
            self._ledger_degraded = True
            log.warning(
                "fleet: goodput-ledger fold failed (%s) — degrading to "
                "counters-only (no goodput gauges / per-tenant table) "
                "for the rest of this daemon life", e)

    def _refresh_ledger(self) -> None:
        """Throttled refresh for RUNNING jobs (their queued/startup/
        train phases are provisional and keep growing); terminal jobs
        folded at finish are left alone."""
        if self._ledger_degraded:
            return
        now = time.monotonic()
        if now < self._ledger_next_mono:
            return
        self._ledger_next_mono = now + self.ledger_interval_s
        with self._lock:
            live = [j for j in self.jobs.values()
                    if j.state not in fjournal.TERMINAL_STATES]
            missing = [j for j in self.jobs.values()
                       if j.state in fjournal.TERMINAL_STATES
                       and j.req.job_id not in self._ledgers]
        dirs = fledger.job_history_dirs(self.fleet_dir)
        for job in live + missing:
            self._fold_ledger_job(job, dirs=dirs)
            if self._ledger_degraded:
                return

    def _ledger_snapshot(self) -> Optional[Dict[str, Any]]:
        if self._ledger_degraded:
            return None
        # status() runs on RPC threads while the tick thread folds: the
        # rollup cache check-then-build must be one critical section
        # (the tonyrace bring-up flagged the unlocked read/write pair
        # here — tick fold vs fleet.status).
        with self._lock:
            if self._ledger_rollup is None:
                self._ledger_rollup = fledger.rollup(
                    list(self._ledgers.values()))
            return self._ledger_rollup

    # -- the decision explainer's query surface ---------------------------
    def explain(self, job_id: str) -> dict:
        """`tony-tpu fleet explain <job>`: the job's causal hold
        timeline — every recorded reason transition with the blocking
        jobs/tenants named, plus the grant/preempt/finish milestones."""
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                return {"ok": False,
                        "message": f"unknown job {job_id!r}"}
            decisions = list(job.decisions)
            milestones: List[Dict[str, Any]] = [
                {"ts_ms": job.submitted_ms,
                 "what": f"submitted by tenant {job.req.tenant!r} "
                         f"(priority {job.req.priority}, "
                         f"{job.req.hosts} host(s))"}]
            if job.granted_ms:
                milestones.append({"ts_ms": job.granted_ms,
                                   "what": f"granted {job.hosts or '?'}"
                                           f" host(s)"})
            for ts, hosts in job.host_events[1:]:
                milestones.append({"ts_ms": ts,
                                   "what": f"resized to {hosts} "
                                           f"host(s)"})
            if job.finished_ms:
                milestones.append({"ts_ms": job.finished_ms,
                                   "what": f"finished {job.state}"})
            from tony_tpu.fleet import timeline as ftimeline

            return {"ok": True, "job": job_id, "state": job.state,
                    "tenant": job.req.tenant, "app_id": job.app_id,
                    "decisions": decisions,
                    # Decision.blocking/free rolled up into attributed
                    # hold seconds (same algebra as the offline path
                    # and the what-if differ — fleet/timeline.py).
                    "holds": ftimeline.holds_summary(
                        ftimeline.hold_intervals(
                            decisions, granted_ms=job.granted_ms,
                            finished_ms=job.finished_ms,
                            now_ms=int(time.time() * 1000),
                            hosts=job.req.hosts)),
                    "milestones": milestones}

    # -- alerting ---------------------------------------------------------
    def _alerts_tick(self) -> None:
        """Evaluate the fleet-scope alert pack against the daemon's own
        registry. Degrade contract (the fleet.ledger shape): any
        evaluator failure disables alerting for the rest of this daemon
        life with one warning — the scheduler tick never blocks."""
        if self._alerts_degraded:
            return
        try:
            faults.check("alerts.eval")
            for tr in self.alerts.evaluate(
                    falerts.RegistrySource(self.metrics)):
                self._apply_alert_transition(tr)
        except Exception as e:  # noqa: BLE001 — observability, not duty
            self._alerts_degraded = True
            log.warning(
                "fleet: alert evaluation failed (%s) — degrading: "
                "alerting disabled for the rest of this daemon life", e)

    def _apply_alert_transition(self, tr: falerts.Transition) -> None:
        """REC_FLEET_ALERT write-ahead (dedup-fenced by the engine),
        then counter + firing gauge + the fleet-scope ALERT event."""
        if tr.journal:
            self.journal.alert(tr.rule, tr.state, tr.severity, tr.value,
                               tr.labels, tr.summary)
        self.metrics.counter(
            "tony_alert_transitions_total", {"state": tr.state},
            help="alert state-machine transitions journaled").inc()
        for sev, n in self.alerts.firing_count().items():
            self.metrics.gauge(
                "tony_alerts_firing", {"severity": sev},
                help="alerts currently firing, by severity").set(n)
        payload = {"rule": tr.rule, "severity": tr.severity,
                   "value": tr.value, "labels": tr.labels,
                   "summary": tr.summary, "scope": "fleet"}
        if tr.state == "firing":
            log.warning("fleet ALERT firing [%s]: %s (value=%s)",
                        tr.severity, tr.rule, tr.value)
            self.events.emit(Event(EventType.ALERT_FIRING, payload))
        elif tr.state == "resolved":
            log.info("fleet alert resolved: %s", tr.rule)
            self.events.emit(Event(EventType.ALERT_RESOLVED, payload))

    def alerts_status(self) -> dict:
        """The `fleet.alerts` RPC: full per-rule state."""
        return {"fleet_dir": self.fleet_dir, "scope": "fleet",
                "degraded": self._alerts_degraded,
                "alerts": self.alerts.snapshot()}

    def _diagnosis_bundle(self) -> Dict[str, Any]:
        """The in-memory twin of diagnose.bundle_from_dir — same keys,
        no file reads, cheap enough for every export."""
        with self._lock:
            now = time.monotonic()
            queue = [{
                "job": j.req.job_id, "tenant": j.req.tenant,
                "priority": j.req.priority, "hosts": j.req.hosts,
                "wait_s": now - j.submitted_mono,
                "last_decision": j.decisions[-1] if j.decisions else {}}
                for j in self.jobs.values() if j.state == QUEUED]
            used = self.engine.tenant_used()
            waits = sorted(self._grant_waits)
            per_job = dict(self._preempts_per_job)
            health = {
                "enabled": self.health_cfg.enabled,
                "cordoned": [dict(host=h.host, state=h.state,
                                  score=round(h.score, 3),
                                  manual=h.manual,
                                  evidence=list(h.evidence[-4:]))
                             for h in self.book.cordoned_hosts()],
                "sick_slices": self.book.sick_slices,
            }
        return {
            "fleet_dir": self.fleet_dir,
            "quotas": dict(self.quotas), "tenants_used": used,
            "queue": queue,
            "median_grant_wait_s": waits[len(waits) // 2]
            if waits else 0.0,
            "grants_total": int(self.metrics.counter(
                "tony_fleet_grants_total").value),
            "preemptions_total": int(self.metrics.counter(
                "tony_fleet_preemptions_total").value),
            "preempts_per_job": per_job,
            "ledger": self._ledger_snapshot() or {},
            "health": health,
            # Firing alerts as rule evidence: an alert that was firing
            # when the incident was built is a precedence-boosted input
            # to the fleet diagnosis rules.
            "alerts": self.alerts.firing(),
            "pool_dir": self.pool_dir,
        }

    # -- surfaces ---------------------------------------------------------
    def _export(self) -> None:
        self._refresh_ledger()
        snap = self.status()
        pool = snap["pool"]
        for state in ("total", "used", "free", "cordoned"):
            self.metrics.gauge("tony_fleet_hosts", {"state": state},
                               help="pool hosts by state").set(
                pool[state])
        # Host-health families + the cordon handshake file the warm
        # pool reads (fleet/health.py): snapshot under the lock, write
        # outside it.
        rank = {fhealth.HEALTHY: 0, fhealth.SUSPECT: 1,
                fhealth.PROBATION: 2, fhealth.QUARANTINED: 3}
        with self._lock:
            host_states = [(h.host, h.state)
                           for h in self.book.hosts.values()]
            cordons = {h.host: h.state
                       for h in self.book.cordoned_hosts()}
        for host, state in host_states:
            self.metrics.gauge(
                "tony_fleet_host_health", {"host": host},
                help="per-host health state (0 healthy, 1 suspect, "
                     "2 probation, 3 quarantined)").set(
                rank.get(state, 0))
        self.metrics.gauge(
            "tony_fleet_quarantined_hosts",
            help="hosts currently cordoned by health quarantine or "
                 "probation").set(len(cordons))
        for root in filter(None, (self.fleet_dir, self.pool_dir)):
            try:
                fhealth.write_cordon_file(
                    os.path.join(root, constants.FLEET_CORDON_FILE),
                    cordons)
            except OSError:
                log.debug("cordon-file export to %s failed", root)
        by_state = {s: 0 for s in (QUEUED, GRANTED, RUNNING)
                    + fjournal.TERMINAL_STATES}
        for row in snap["jobs"]:
            by_state[row["state"]] = by_state.get(row["state"], 0) + 1
        for state, n in by_state.items():
            # Zero-filled over the full state set so a drained queue
            # reads as 0, not as a frozen last value.
            self.metrics.gauge("tony_fleet_jobs", {"state": state},
                               help="fleet jobs by state").set(n)
        self.metrics.gauge("tony_fleet_queue_depth",
                           help="submissions waiting for a grant").set(
            snap["queue_depth"])
        for tenant, row in snap["tenants"].items():
            self.metrics.gauge("tony_fleet_tenant_hosts",
                               {"tenant": tenant},
                               help="granted hosts per tenant").set(
                row["used"])
        ledger = snap.get("ledger")
        if ledger:
            # The goodput families (tony_tpu/fleet/ledger.py): absent
            # entirely while the ledger is degraded — counters-only, the
            # fleet.ledger fault-site contract.
            fleet_row = ledger.get("fleet") or {}
            if fleet_row.get("goodput_fraction") is not None:
                self.metrics.gauge(
                    "tony_fleet_goodput_fraction",
                    help="chip-seconds doing useful train steps / "
                         "chip-seconds held, per tenant and "
                         "fleet-wide").set(
                    fleet_row["goodput_fraction"])
            for tenant, trow in (ledger.get("tenants") or {}).items():
                if trow.get("goodput_fraction") is not None:
                    self.metrics.gauge(
                        "tony_fleet_goodput_fraction",
                        {"tenant": tenant}).set(
                        trow["goodput_fraction"])
                for phase, secs in (trow.get("phase_chip_s")
                                    or {}).items():
                    self.metrics.gauge(
                        "tony_fleet_phase_seconds",
                        {"phase": phase, "tenant": tenant},
                        help="cumulative ledger chip-seconds per "
                             "goodput phase and tenant").set(secs)
        try:
            from tony_tpu.fleet import diagnose as fdiagnose

            fdiagnose.save_incident(
                self.fleet_dir,
                fdiagnose.build_incident(self._diagnosis_bundle()))
        except Exception:  # noqa: BLE001 — diagnosis must degrade
            log.exception("fleet incident export failed")
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_PROM_FILE),
            self.metrics.render().encode("utf-8"))
        atomic_write(
            os.path.join(self.fleet_dir, constants.FLEET_STATUS_FILE),
            json.dumps(snap, sort_keys=True).encode("utf-8"))
        self.metrics.save_counters(self._counters_path)
