"""Fleet policy engine: who runs, where, and at whose expense.

The YARN ResourceManager decided this for the reference (CapacityScheduler
queues + container preemption); here the decision logic is one small,
deterministic, stdlib-only module so it can be unit-tested exhaustively
and smoke-run in the no-deps CI lint job (``python -m
tony_tpu.fleet.policy``). The daemon (``fleet/daemon.py``) owns every
side effect — journal records, spawns, resize RPCs — and calls in here
only to decide and to account.

Model:

- The pool is ``slices × hosts_per_slice`` hosts. A **sub-slice** job
  (fewer hosts than a slice) must land in ONE slice — a gang wants ICI
  locality — and slices are shared, best-fit, between sub-slice jobs.
  A larger job takes whole free slices plus a best-fit remainder.
- **Priority** orders the queue (higher first), submission sequence
  breaks ties (FIFO within a priority band).
- **Quotas** cap a tenant's granted hosts. A quota-denied submission
  stays queued and is SKIPPED — it never blocks other tenants' grants
  (no head-of-line quota starvation).
- A **capacity-denied** job at the head of the queue holds the line:
  nothing behind it is granted this pass (strict priority — backfill
  behind a starving large job is how large jobs starve forever), but
  quota-denials never hold.
- **Preempt-to-reclaim**: when the head job cannot fit, victims are
  chosen among strictly-lower-priority running jobs that declared a
  shrink floor (``min_hosts``), lowest priority first, youngest first
  within a priority, each shrunk only as far as needed and never below
  its floor. The plan reserves the reclaimed hosts for the demander;
  the daemon applies the shrinks through the victims' elastic resize
  (drain→remesh — no victim epoch burned) and the grant lands on a
  later pass once the hosts are free.
- **Grow-back**: with the queue drained and hosts free, previously
  shrunk jobs are restored toward their requested size, highest
  priority first — preemption is a loan, not a confiscation.
- **Defrag-by-migration**: when a FRAGMENTATION hold is computed (free
  hosts exist but do not pack), the planner looks for ONE running
  sub-slice elastic job whose live migration to another slice merges
  the holes so the demander places — cheaper than preempting anybody
  (no victim loses a host, the mover loses only its drain window).
- **Slice evacuation**: on a slice-preemption notice (the cloud is
  reclaiming a queued resource), every elastic job touching the dying
  slice gets a MIGRATE plan onto surviving capacity — spot survival by
  moving, not by dying and retrying.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

#: decision kinds (Decision.action)
GRANT = "grant"
SHRINK = "shrink"          # preempt-to-reclaim: victim shrinks via resize
MIGRATE = "migrate"        # live move between slices (defrag / evacuation)
QUOTA_DENIED = "quota"     # tenant at quota: stays queued, never holds
CAPACITY_DENIED = "capacity"  # pool full and nothing preemptible: holds
# Explainer-only decisions (tony-tpu fleet explain): the policy engine
# states why every OTHER queued job did not place this pass, not just
# the head of the line. The daemon records them (decision ring +
# REC_FLEET_DECISION journal) and applies nothing.
PREEMPT_WAIT = "preempt-wait"  # head job: shrinks planned, reclaim landing
PRIORITY_HELD = "held"         # queued behind the head-of-line hold

#: decisions that hold a job in the queue (vs. act on the pool) — the
#: set the daemon's decision recorder consumes.
HOLD_ACTIONS = (QUOTA_DENIED, CAPACITY_DENIED, PREEMPT_WAIT,
                PRIORITY_HELD)


@dataclasses.dataclass(frozen=True)
class JobRequest:
    """One submission as the policy engine sees it. ``min_hosts`` > 0
    marks the job elastic-shrinkable (a preemption victim candidate and
    a grow-back beneficiary); 0 means never preempt it."""

    job_id: str
    tenant: str
    priority: int = 0
    hosts: int = 1
    min_hosts: int = 0
    model: str = ""
    seq: int = 0


@dataclasses.dataclass
class Decision:
    """One step of a scheduling plan, applied in order by the daemon."""

    action: str
    job_id: str
    hosts: int = 0                       # grant size / shrink target
    placement: Dict[int, int] = dataclasses.field(default_factory=dict)
    reason: str = ""
    for_job: str = ""                    # SHRINK: the demanding job
    #: the jobs/tenants holding the capacity this decision waits on —
    #: the explainer's "who is blocking me" answer (hold decisions only)
    blocking: List[str] = dataclasses.field(default_factory=list)
    #: free hosts in the pool when a capacity hold was computed: free >=
    #: requested means the hosts EXIST but do not pack — fragmentation,
    #: not capacity (the fleet-diagnose FRAGMENTATION rule keys off it)
    free: int = 0
    #: MIGRATE only: the slice the job vacates and the slice it lands
    #: on (``placement`` already holds the POST-move layout)
    source: int = -1
    target: int = -1


@dataclasses.dataclass
class _Running:
    req: JobRequest
    hosts: int
    placement: Dict[int, int]


class SlicePool:
    """Host accounting over ``slices`` slices of ``hosts_per_slice``."""

    def __init__(self, slices: int, hosts_per_slice: int) -> None:
        self.slices = max(1, int(slices))
        self.hosts_per_slice = max(1, int(hosts_per_slice))
        self._free: List[int] = [self.hosts_per_slice] * self.slices
        #: hosts cordoned out of service by health quarantine (fleet/
        #: health.py) or operator cordon: per slice, free + in-use +
        #: cordoned == hosts_per_slice. Cordoned hosts are invisible to
        #: place() because they are simply not free.
        self._cordoned: List[int] = [0] * self.slices

    @property
    def total(self) -> int:
        return self.slices * self.hosts_per_slice

    @property
    def free_total(self) -> int:
        return sum(self._free)

    @property
    def cordoned_total(self) -> int:
        return sum(self._cordoned)

    def free_on(self, i: int) -> int:
        """Free hosts on one slice (the operator-migrate room check)."""
        return self._free[int(i)]

    def cordon_free(self, i: int) -> None:
        """Move one FREE host on slice ``i`` out of service. Occupied
        hosts are cordoned at release time instead (the daemon defers
        the sweep until the holding job frees them)."""
        i = int(i)
        if self._free[i] <= 0:
            raise ValueError(f"slice {i} has no free host to cordon")
        self._free[i] -= 1
        self._cordoned[i] += 1

    def uncordon(self, i: int) -> None:
        """Return one cordoned host on slice ``i`` to the free pool."""
        i = int(i)
        if self._cordoned[i] <= 0:
            raise ValueError(f"slice {i} has no cordoned host")
        self._cordoned[i] -= 1
        self._free[i] += 1

    def clone(self) -> "SlicePool":
        c = SlicePool(self.slices, self.hosts_per_slice)
        c._free = list(self._free)
        c._cordoned = list(self._cordoned)
        return c

    def place(self, hosts: int) -> Optional[Dict[int, int]]:
        """Placement for a gang of ``hosts``, or None when it cannot be
        packed. Sub-slice gangs go best-fit into ONE slice (tightest
        fitting slice — leaves big holes big); larger gangs take whole
        free slices first, then a best-fit remainder. Deterministic:
        ties break on the lowest slice index."""
        hosts = int(hosts)
        if hosts <= 0 or hosts > self.free_total:
            return None
        hps = self.hosts_per_slice
        if hosts < hps:
            best: Optional[int] = None
            for i, free in enumerate(self._free):
                if free >= hosts and (best is None
                                      or free < self._free[best]):
                    best = i
            return None if best is None else {best: hosts}
        placement: Dict[int, int] = {}
        remaining = hosts
        for i, free in enumerate(self._free):
            if remaining < hps:
                break
            if free == hps:
                placement[i] = hps
                remaining -= hps
        if remaining > 0:
            best = None
            for i, free in enumerate(self._free):
                if i in placement:
                    continue
                if free >= remaining and (best is None
                                          or free < self._free[best]):
                    best = i
            if best is None:
                return None
            placement[best] = remaining
        return placement

    def allocate(self, placement: Dict[int, int]) -> None:
        for i, n in placement.items():
            if self._free[i] < n:
                raise ValueError(
                    f"slice {i} has {self._free[i]} free, need {n}")
            self._free[i] -= n

    def release(self, placement: Dict[int, int]) -> None:
        for i, n in placement.items():
            self._free[i] = min(self.hosts_per_slice - self._cordoned[i],
                                self._free[i] + n)

    def shrink(self, placement: Dict[int, int],
               by: int) -> Dict[int, int]:
        """Free ``by`` hosts from ``placement``, CONCENTRATED: each
        host comes off the placement slice already closest to free
        (ties → lowest index), so shrinks vacate whole slices instead
        of fragmenting one hole per slice — a waiting gang needs
        contiguous slice capacity, not a scattered host count. Mutates
        and returns the placement; the preemption planner relies on
        plan-time and apply-time shrinks freeing the SAME slices."""
        for _ in range(int(by)):
            if not placement:
                break
            best = min(sorted(placement), key=lambda i: -self._free[i])
            placement[best] -= 1
            self._free[best] = min(
                self.hosts_per_slice - self._cordoned[best],
                self._free[best] + 1)
            if placement[best] == 0:
                del placement[best]
        return placement


class PolicyEngine:
    """Queue + accounting state; ``schedule()`` computes a plan, the
    mutators apply what the daemon actually carried out (write-ahead:
    the daemon journals each step before calling its mutator)."""

    def __init__(self, slices: int, hosts_per_slice: int,
                 quotas: Optional[Dict[str, int]] = None) -> None:
        self.pool = SlicePool(slices, hosts_per_slice)
        self.quotas: Dict[str, int] = dict(quotas or {})
        self._queued: Dict[str, JobRequest] = {}
        self._running: Dict[str, _Running] = {}
        #: host ids currently cordoned by health quarantine (set by the
        #: daemon, read by the CAPACITY_DENIED explainer: a hold caused
        #: by sick hardware must NAME the sick hardware, or the
        #: operator debugs a phantom capacity shortage).
        self.cordoned_names: List[str] = []

    # -- queries ---------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queued)

    def queued_order(self) -> List[JobRequest]:
        return sorted(self._queued.values(),
                      key=lambda r: (-r.priority, r.seq))

    def running(self, job_id: str) -> Optional[Tuple[int, Dict[int, int]]]:
        r = self._running.get(job_id)
        return (r.hosts, dict(r.placement)) if r is not None else None

    def tenant_used(self) -> Dict[str, int]:
        used: Dict[str, int] = {}
        for r in self._running.values():
            used[r.req.tenant] = used.get(r.req.tenant, 0) + r.hosts
        return used

    # -- lifecycle mutators (the daemon journals, then calls these) ------
    def submit(self, req: JobRequest) -> None:
        if req.job_id in self._queued or req.job_id in self._running:
            raise ValueError(f"job {req.job_id!r} already known")
        if req.hosts > self.pool.total:
            raise ValueError(
                f"job {req.job_id!r} wants {req.hosts} hosts; the pool "
                f"only has {self.pool.total}")
        self._queued[req.job_id] = req

    def withdraw(self, job_id: str) -> bool:
        """Cancel a still-queued submission."""
        return self._queued.pop(job_id, None) is not None

    def grant(self, job_id: str, placement: Dict[int, int]) -> None:
        req = self._queued.pop(job_id)
        self.pool.allocate(placement)
        self._running[job_id] = _Running(req, sum(placement.values()),
                                         dict(placement))

    def force_grant(self, req: JobRequest, hosts: int,
                    placement: Dict[int, int]) -> None:
        """Recovery path: re-account a job the journal says is running
        (no queue transit, placement replayed verbatim)."""
        self.pool.allocate(placement)
        self._queued.pop(req.job_id, None)
        self._running[req.job_id] = _Running(req, hosts, dict(placement))

    def shrink_applied(self, job_id: str, to_hosts: int) -> Dict[int, int]:
        """A preemption shrink (or any downward resize) landed: free the
        difference and return the new placement."""
        r = self._running[job_id]
        by = r.hosts - int(to_hosts)
        if by > 0:
            self.pool.shrink(r.placement, by)
            r.hosts = int(to_hosts)
        return dict(r.placement)

    def grow_applied(self, job_id: str,
                     placement_delta: Dict[int, int]) -> Dict[int, int]:
        """A grow-back resize landed: account the extra hosts."""
        r = self._running[job_id]
        self.pool.allocate(placement_delta)
        for i, n in placement_delta.items():
            r.placement[i] = r.placement.get(i, 0) + n
        r.hosts += sum(placement_delta.values())
        return dict(r.placement)

    def release(self, job_id: str) -> None:
        """Terminal job: free everything it held."""
        r = self._running.pop(job_id, None)
        if r is not None:
            self.pool.release(r.placement)
        else:
            self._queued.pop(job_id, None)

    # -- the scheduling pass ---------------------------------------------
    def schedule(self) -> List[Decision]:
        """One scheduling pass over the queue (pure: mutates nothing —
        the daemon applies each Decision write-ahead and calls the
        mutators above for the ones that actually happened)."""
        plan: List[Decision] = []
        tentative = self.pool.clone()
        used = self.tenant_used()
        queue = self.queued_order()
        head_id = ""
        for pos, req in enumerate(queue):
            quota_hold = self._quota_hold(req, used)
            if quota_hold is not None:
                plan.append(quota_hold)
                continue            # quota never blocks other tenants
            placement = tentative.place(req.hosts)
            if placement is not None:
                tentative.allocate(placement)
                used[req.tenant] = used.get(req.tenant, 0) + req.hosts
                plan.append(Decision(GRANT, req.job_id, hosts=req.hosts,
                                     placement=placement))
                continue
            free = tentative.free_total
            shrinks = self._plan_preemption(req, tentative)
            if shrinks:
                plan.extend(shrinks)
                victims = [d.job_id for d in shrinks]
                plan.append(Decision(
                    PREEMPT_WAIT, req.job_id, hosts=req.hosts, free=free,
                    blocking=victims,
                    reason=f"reclaiming {max(0, req.hosts - free)} "
                           f"host(s) via elastic shrink of {victims} "
                           f"(priority {req.priority}); the grant lands "
                           f"once the drain completes"))
            elif free >= req.hosts \
                    and (moves := self._plan_defrag(req, tentative)):
                # FRAGMENTATION with a cure: one live migration merges
                # the holes. Nobody shrinks — the mover only pays its
                # drain window; the grant lands once the move completes.
                plan.extend(moves)
                movers = [d.job_id for d in moves]
                plan.append(Decision(
                    PREEMPT_WAIT, req.job_id, hosts=req.hosts,
                    free=free, blocking=movers,
                    reason=f"defragmentation: repacking via live "
                           f"migration of {movers} — the grant lands "
                           f"once the move completes"))
            else:
                holders = self._largest_holders()
                if free >= req.hosts:
                    why = (f"fragmentation: {free} free host(s) exist "
                           f"but do not pack into a {req.hosts}-host "
                           f"gang (sub-slice gangs need ONE slice)")
                else:
                    why = (f"{req.hosts} hosts do not fit ({free} free) "
                           f"and no lower-priority elastic capacity "
                           f"exists")
                if self.cordoned_names:
                    why += (f"; {len(self.cordoned_names)} host(s) "
                            f"cordoned by health quarantine: "
                            f"{self.cordoned_names}")
                plan.append(Decision(
                    CAPACITY_DENIED, req.job_id, hosts=req.hosts,
                    free=free, blocking=holders, reason=why))
            # Head-of-line hold: the reclaimed (or awaited) hosts belong
            # to THIS job; granting anything behind it would re-consume
            # them and starve the large/high-priority job forever. The
            # rest of the queue still gets an EXPLAINER decision each —
            # quota-denied where at quota, priority-held otherwise.
            head_id = req.job_id
            for later in queue[pos + 1:]:
                hold = self._quota_hold(later, used)
                if hold is None:
                    hold = Decision(
                        PRIORITY_HELD, later.job_id, hosts=later.hosts,
                        free=free, blocking=[head_id],
                        reason=f"held behind {head_id!r} (priority "
                               f"{req.priority}, seq {req.seq}) — "
                               f"head-of-line hold, no backfill")
                plan.append(hold)
            break
        return plan

    def _quota_hold(self, req: JobRequest,
                    used: Dict[str, int]) -> Optional[Decision]:
        quota = self.quotas.get(req.tenant, 0)
        if quota <= 0 or used.get(req.tenant, 0) + req.hosts <= quota:
            return None
        blocking = sorted(
            r.req.job_id for r in self._running.values()
            if r.req.tenant == req.tenant)
        return Decision(
            QUOTA_DENIED, req.job_id, hosts=req.hosts,
            blocking=blocking or [req.tenant],
            reason=f"tenant {req.tenant!r} at quota "
                   f"({used.get(req.tenant, 0)}/{quota} hosts; running: "
                   f"{blocking or 'none'})")

    def _largest_holders(self, limit: int = 5) -> List[str]:
        """Running jobs holding the most hosts — the 'who is blocking
        me' answer on a capacity hold."""
        holders = sorted(self._running.values(),
                         key=lambda r: (-r.hosts, r.req.seq))
        return [r.req.job_id for r in holders[:limit]]

    def _plan_preemption(self, req: JobRequest,
                         tentative: SlicePool) -> List[Decision]:
        """Shrink plan reclaiming enough PACKABLE capacity for ``req``
        from strictly lower-priority elastic jobs, or [] when
        impossible. Victim order: lowest priority first, then youngest
        (highest seq) — the job that has run longest is disturbed last.
        Placement-aware: each victim is shrunk one host at a time until
        the demander actually places (quantity alone is not enough — 3
        free hosts on one slice plus 2 on another never fit a 4-host
        gang), so victims are disturbed minimally and a geometrically
        unsatisfiable demand preempts nobody."""
        victims = sorted(
            (r for r in self._running.values()
             if r.req.priority < req.priority
             and r.req.min_hosts > 0 and r.hosts > r.req.min_hosts),
            key=lambda r: (r.req.priority, -r.req.seq))
        shrinks: List[Decision] = []
        trial = tentative.clone()
        for v in victims:
            if trial.place(req.hosts) is not None:
                break
            placement = dict(v.placement)
            to = v.hosts
            while to > v.req.min_hosts \
                    and trial.place(req.hosts) is None:
                trial.shrink(placement, 1)
                to -= 1
            if to < v.hosts:
                shrinks.append(Decision(
                    SHRINK, v.req.job_id, hosts=to,
                    for_job=req.job_id,
                    reason=f"reclaim {v.hosts - to} host(s) for "
                           f"{req.job_id!r} (priority {req.priority} > "
                           f"{v.req.priority})"))
        if not shrinks or trial.place(req.hosts) is None:
            # Failure MUST leave ``tentative`` untouched: schedule()
            # falls through to _plan_defrag next, and a defrag placement
            # computed against phantom reclaimed capacity is a MIGRATE
            # nobody can apply (migrate_applied would overfill a slice).
            return []
        tentative._free = list(trial._free)
        return shrinks

    def _plan_defrag(self, req: JobRequest,
                     tentative: SlicePool) -> List[Decision]:
        """ONE live migration that merges the fragmented holes so
        ``req`` places, or []. Candidates are running sub-slice elastic
        jobs (``min_hosts`` > 0 — migration rides the same drain
        machinery as a shrink) at or below the demander's priority,
        cheapest move first (fewest hosts), then youngest — the job
        that has run longest is disturbed last. Pure: works on clones
        of ``tentative``."""
        hps = self.pool.hosts_per_slice
        movers = sorted(
            (r for r in self._running.values()
             if len(r.placement) == 1 and r.hosts < hps
             and r.req.min_hosts > 0
             and r.req.priority <= req.priority),
            key=lambda r: (r.hosts, -r.req.seq))
        for v in movers:
            src = next(iter(v.placement))
            trial = tentative.clone()
            trial.release(v.placement)
            # Land the mover anywhere BUT its own slice — the point is
            # to merge the hole it leaves behind.
            src_free = trial._free[src]
            trial._free[src] = 0
            dest = trial.place(v.hosts)
            if dest is None or src in dest:
                continue
            trial.allocate(dest)
            trial._free[src] = src_free
            if trial.place(req.hosts) is None:
                continue
            tgt = next(iter(dest))
            return [Decision(
                MIGRATE, v.req.job_id, hosts=v.hosts, placement=dest,
                source=src, target=tgt, for_job=req.job_id,
                reason=f"defragmentation: moving {v.hosts} host(s) "
                       f"from slice {src} to slice {tgt} packs a "
                       f"{req.hosts}-host gang for {req.job_id!r}")]
        return []

    def evacuation_candidates(self, dying: List[int]) -> List[Decision]:
        """MIGRATE plan moving every elastic job off the ``dying``
        slices (a slice-preemption notice) onto surviving capacity,
        highest priority first. Jobs with no landing room — or without
        the elastic machinery a live move rides — are skipped; the
        ordinary host-loss ladder absorbs them when the slice dies.
        Pure: the daemon applies each move write-ahead and calls
        ``migrate_applied`` when it lands."""
        dying_set = {int(i) for i in dying
                     if 0 <= int(i) < self.pool.slices}
        if not dying_set:
            return []
        tentative = self.pool.clone()
        for i in dying_set:
            tentative._free[i] = 0      # never a migration target
        out: List[Decision] = []
        for r in sorted(self._running.values(),
                        key=lambda r: (-r.req.priority, r.req.seq)):
            doomed = {i: n for i, n in r.placement.items()
                      if i in dying_set}
            if not doomed or r.req.min_hosts <= 0:
                continue
            # The WHOLE gang moves (drain→move→reshard is one op), so
            # its healthy hosts free up for the placement too.
            for i, n in r.placement.items():
                if i not in dying_set:
                    tentative._free[i] = min(
                        self.pool.hosts_per_slice
                        - tentative._cordoned[i],
                        tentative._free[i] + n)
            dest = tentative.place(r.hosts)
            if dest is None:
                for i, n in r.placement.items():
                    if i not in dying_set:
                        tentative._free[i] -= n
                continue
            tentative.allocate(dest)
            src = min(doomed)
            tgt = min(dest)
            out.append(Decision(
                MIGRATE, r.req.job_id, hosts=r.hosts, placement=dest,
                source=src, target=tgt,
                reason=f"slice {sorted(doomed)} preemption notice: "
                       f"evacuating {r.hosts} host(s) to slice(s) "
                       f"{sorted(dest)} before the reclaim lands"))
        return out

    def migrate_applied(self, job_id: str,
                        placement: Dict[int, int]) -> Dict[int, int]:
        """A live migration landed: re-account the job's hosts at the
        new placement (host COUNT unchanged — a move, not a resize)."""
        r = self._running[job_id]
        self.pool.release(r.placement)
        self.pool.allocate(placement)
        r.placement = dict(placement)
        r.hosts = sum(placement.values())
        return dict(r.placement)

    def restore_candidates(self) -> List[Tuple[str, int, Dict[int, int]]]:
        """Grow-back plan: with an empty queue and free hosts, restore
        shrunk jobs toward their requested size, highest priority
        first. Returns (job_id, new_total_hosts, placement_delta)."""
        if self._queued:
            return []               # reclaimed space belongs to the queue
        out: List[Tuple[str, int, Dict[int, int]]] = []
        tentative = self.pool.clone()
        for r in sorted(self._running.values(),
                        key=lambda r: (-r.req.priority, r.req.seq)):
            want = r.req.hosts - r.hosts
            if want <= 0:
                continue
            grow = min(want, tentative.free_total)
            if grow <= 0:
                continue
            delta = tentative.place(grow)
            if delta is None:
                continue
            tentative.allocate(delta)
            out.append((r.req.job_id, r.hosts + grow, delta))
        return out


def parse_quotas(spec: str) -> Dict[str, int]:
    """'teamA=8,teamB=4' → {'teamA': 8, 'teamB': 4} (the
    tony.fleet.quotas grammar; blank entries skipped, bad ones raise)."""
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        tenant, sep, hosts = part.partition("=")
        if not sep:
            raise ValueError(
                f"bad quota entry {part!r} (need tenant=hosts)")
        out[tenant.strip()] = int(hosts)
    return out


def _self_check() -> None:
    """Deterministic scenario asserting the four policy behaviours —
    the no-deps CI smoke (``python -m tony_tpu.fleet.policy``)."""
    eng = PolicyEngine(2, 4, quotas={"capped": 2})
    # Bin-pack: two sub-slice jobs share one slice (best-fit).
    eng.submit(JobRequest("a", "t1", hosts=2, seq=1))
    eng.submit(JobRequest("b", "t1", hosts=2, seq=2))
    plan = eng.schedule()
    assert [d.action for d in plan] == [GRANT, GRANT], plan
    assert plan[0].placement == {0: 2} and plan[1].placement == {0: 2}
    for d in plan:
        eng.grant(d.job_id, d.placement)
    # Quota: the capped tenant queues WITHOUT blocking others.
    eng.submit(JobRequest("q", "capped", hosts=4, seq=3))
    eng.submit(JobRequest("c", "t2", hosts=4, seq=4))
    plan = eng.schedule()
    assert [(d.action, d.job_id) for d in plan] == [
        (QUOTA_DENIED, "q"), (GRANT, "c")], plan
    eng.grant("c", plan[1].placement)
    # Priority + preempt-to-reclaim: a priority-10 job arrives into a
    # full pool; with no declared floors nothing is preemptible...
    eng._queued.pop("q")
    eng.submit(JobRequest("hi", "t3", priority=10, hosts=3, seq=5))
    plan = eng.schedule()
    assert [d.action for d in plan] == [CAPACITY_DENIED], plan
    # ...but once the lower-priority job declares a shrink floor, the
    # plan reclaims exactly what the demander needs via elastic shrink.
    eng._running["c"].req = dataclasses.replace(
        eng._running["c"].req, min_hosts=1)
    plan = eng.schedule()
    # ...and the explainer records WHY the demander still waits this
    # pass (the reclaim is in flight), with the victim named.
    assert [d.action for d in plan] == [SHRINK, PREEMPT_WAIT], plan
    assert plan[0].job_id == "c" and plan[0].hosts == 1
    assert plan[1].job_id == "hi" and plan[1].blocking == ["c"]
    eng.shrink_applied("c", plan[0].hosts)
    plan = eng.schedule()
    assert [(d.action, d.job_id) for d in plan] == [(GRANT, "hi")], plan
    eng.grant("hi", plan[0].placement)
    # Grow-back: the demander leaves, the victim is restored.
    eng.release("hi")
    restores = eng.restore_candidates()
    assert restores and restores[0][0] == "c" and restores[0][1] == 4
    # Defrag-by-migration: 2+2 free hosts split across both slices
    # cannot pack a 4-host gang — moving one sub-slice elastic job
    # merges the holes, nobody shrinks.
    eng = PolicyEngine(2, 4)
    eng.submit(JobRequest("m1", "t1", hosts=2, min_hosts=1, seq=1))
    eng.grant("m1", {0: 2})
    eng.submit(JobRequest("m2", "t1", hosts=2, min_hosts=1, seq=2))
    eng.grant("m2", {1: 2})
    eng.submit(JobRequest("big", "t2", hosts=4, seq=3))
    plan = eng.schedule()
    assert [d.action for d in plan] == [MIGRATE, PREEMPT_WAIT], plan
    mv = plan[0]
    assert mv.job_id == "m2" and (mv.source, mv.target) == (1, 0), mv
    assert plan[1].blocking == ["m2"] \
        and plan[1].reason.startswith("defragmentation"), plan[1]
    eng.migrate_applied(mv.job_id, mv.placement)
    plan = eng.schedule()
    assert [(d.action, d.job_id) for d in plan] == [(GRANT, "big")], plan
    # Slice evacuation: a preemption notice on slice 0 moves the
    # elastic job there to surviving capacity; the job without a
    # shrink floor is left to the ordinary retry ladder.
    eng = PolicyEngine(2, 4)
    eng.submit(JobRequest("ev", "t1", hosts=2, min_hosts=1, seq=1))
    eng.grant("ev", {0: 2})
    eng.submit(JobRequest("pin", "t1", hosts=2, seq=2))
    eng.grant("pin", {0: 2})
    plan = eng.evacuation_candidates([0])
    assert [(d.action, d.job_id) for d in plan] == [(MIGRATE, "ev")], plan
    assert (plan[0].source, plan[0].target) == (0, 1), plan[0]
    eng.migrate_applied("ev", plan[0].placement)
    assert eng.running("ev") == (2, {1: 2})
    # Health cordon: a cordoned host is simply not free — placements
    # route around it, releases never resurrect it, and a capacity hold
    # caused by the cordon NAMES the sick host.
    eng = PolicyEngine(1, 4)
    eng.pool.cordon_free(0)
    assert (eng.pool.free_total, eng.pool.cordoned_total) == (3, 1)
    eng.cordoned_names = ["s0h3"]
    eng.submit(JobRequest("w", "t1", hosts=4, seq=1))
    plan = eng.schedule()
    assert [d.action for d in plan] == [CAPACITY_DENIED], plan
    assert "s0h3" in plan[0].reason, plan[0].reason
    eng._queued.pop("w")
    eng.submit(JobRequest("x", "t1", hosts=3, seq=2))
    plan = eng.schedule()
    assert [d.action for d in plan] == [GRANT], plan
    eng.grant("x", plan[0].placement)
    eng.release("x")
    assert eng.pool.free_total == 3   # release never refills the cordon
    eng.pool.uncordon(0)
    assert (eng.pool.free_total, eng.pool.cordoned_total) == (4, 0)
    print("fleet policy self-check OK")


if __name__ == "__main__":
    _self_check()
