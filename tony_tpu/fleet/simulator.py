"""Fleet time machine: deterministic what-if scheduler simulation.

Every fleet number the repo produces is retrospective — the goodput
ledger says where chip-seconds WENT, ``fleet diagnose`` says which
tenant is starving NOW. An operator who suspects a quota bump, a
priority flip, or a bigger pool would fix a STARVATION or FRAGMENTATION
verdict had no way to test the hypothesis short of touching production
(ROADMAP item 5b). This module closes the loop:

1. ``fold_workload`` folds a recorded fleet journal (via the shared
   ``fleet/timeline.py`` replay) into a workload: submit times, tenants,
   priorities, gang sizes, shrink floors, and each job's OBSERVED work —
   the chip-millisecond integral of its piecewise host count from grant
   to terminal (a job shrunk to half rate for half its life carries that
   into every counterfactual).
2. ``parity_replay`` is the calibration gate: the journal's own
   decision/grant/preempt/migrate sequence is re-derived record by
   record through the REAL :class:`fleet.policy.PolicyEngine` and
   compared bit-for-bit. A journal that parity-replays clean proves the
   simulator and the daemon share one scheduling brain — which is what
   makes a counterfactual trustworthy.
3. ``simulate`` re-executes the workload as a discrete-event simulation
   against the same engine under OVERRIDDEN configuration — quotas,
   pool shape, per-job priorities, preemption/defrag/restore toggles
   (``tony.fleet.sim-*``) — with work consumed at the granted host
   rate, so shrinks stretch runtimes and bigger pools compress them.
4. ``whatif`` diffs counterfactual metrics (goodput fraction, queue-wait
   p50/p99, preemptions, per-tenant quota/fragmentation hold seconds —
   the same hold algebra ``fleet explain`` renders) against the
   simulated baseline, expands ``--sweep`` grids, and cites which holds
   each counterfactual removed.

Everything is integer-millisecond arithmetic on journal timestamps —
no wall clock, no randomness — so the same journal plus the same
overrides produce a byte-identical report (test-enforced). The
simulator can also RECORD a run as a real fleet journal
(:class:`JournalRecorder`) — parity-clean by construction — which is
how the checked-in ``tests/fixtures/whatif_mix`` 50-job fixture and the
BENCH_WHATIF suite are generated.

Known limits (documented in docs/operations.md "Capacity planning and
what-if"): observed durations were measured UNDER the recorded
contention (a job that thrashed may carry inflated work into the
counterfactual), migrations/restores apply instantly (no drain
window), and host-health cordons mid-journal are approximated from the
fhealth fold. Stdlib-only, side-effect-free, like the policy engine.

The no-deps CI smoke runs ``python -m tony_tpu.fleet.simulator
<fleet_dir-or-journal> --expect-parity`` (plus counterfactual flags)
against the checked-in fixtures.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tony_tpu.conf import keys as K
from tony_tpu.fleet import journal as fjournal
from tony_tpu.fleet import ledger as fledger
from tony_tpu.fleet import policy as fpolicy
from tony_tpu.fleet import timeline as ftimeline

#: fallback per-host work for a job the journal never ran (submitted
#: but never granted): the median observed per-host duration is used
#: instead when any job finished; this only when NONE did.
DEFAULT_HOST_WORK_MS = 60_000

#: cap on the expanded sweep grid — a fat-fingered sweep should fail
#: loudly, not run for an hour.
SWEEP_CAP = 64

#: hold kind -> report metric key ("-" and the policy's terse "held"
#: are report-hostile).
HOLD_METRIC = {
    fpolicy.QUOTA_DENIED: "quota_hold_s",
    fpolicy.CAPACITY_DENIED: "capacity_hold_s",
    ftimeline.FRAGMENTATION: "fragmentation_hold_s",
    fpolicy.PREEMPT_WAIT: "preempt_wait_hold_s",
    fpolicy.PRIORITY_HELD: "priority_hold_s",
}

#: metric direction for the diff report (mirrors profiling/benchdiff.py
#: suffix conventions; used to mark each delta improves/regresses).
LOWER_BETTER = (
    "queue_wait_p50_s", "queue_wait_p99_s", "queue_wait_mean_s",
    "makespan_s", "preemptions", "preemptions_per_job", "migrations",
    "restores", "ungranted", "refused") + tuple(HOLD_METRIC.values())
HIGHER_BETTER = ("goodput_fraction", "utilization_fraction", "granted")


# ---------------------------------------------------------------------------
# workload fold: journal -> replayable submissions with observed work
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SimJob:
    """One recorded submission as the simulator replays it."""

    job_id: str
    tenant: str
    priority: int
    hosts: int
    min_hosts: int
    model: str
    seq: int
    submit_ms: int
    #: observed work in chip-milliseconds (host-count integral from
    #: grant to terminal) — consumed at the granted host rate, so a
    #: counterfactual that grants more hosts finishes the job sooner.
    work_chip_ms: int
    #: recorded terminal state (FINISHED/FAILED/CANCELLED), or "" when
    #: the journal never finished it — re-emitted by record mode.
    recorded_state: str = ""


@dataclasses.dataclass
class Workload:
    """The folded timeline ``simulate()`` re-executes."""

    slices: int
    hosts_per_slice: int
    quotas: Dict[str, int]
    jobs: List[SimJob]

    @property
    def pool_chips(self) -> int:
        return self.slices * self.hosts_per_slice


def _work_chip_ms(fold: fjournal.JobFold, end_ms: int) -> int:
    """Exact chip-ms integral of the fold's piecewise host count from
    the grant to its terminal anchor (or ``end_ms`` for a live job)."""
    events = fold.host_events
    stop = fold.finished_ms if fold.finished_ms else end_ms
    total = 0
    for i, (ts, hosts) in enumerate(events):
        nxt = events[i + 1][0] if i + 1 < len(events) else stop
        nxt = min(max(nxt, ts), stop)
        total += max(0, nxt - ts) * max(0, hosts)
    return total


def fold_workload(tl: ftimeline.FleetTimeline) -> Workload:
    """Fold the shared timeline into the simulator's workload. Jobs the
    journal never granted get the median observed per-host duration as
    their work estimate (their TRUE duration was never observed — the
    docs call this out as a trust caveat)."""
    st = tl.state
    end_ms = max((int(r.get("ts", 0) or 0) for r in tl.records),
                 default=0)
    per_host: List[int] = []
    for fold in st.jobs.values():
        work = _work_chip_ms(fold, end_ms)
        if work > 0 and fold.hosts_requested > 0:
            per_host.append(work // fold.hosts_requested)
    per_host.sort()
    median = per_host[len(per_host) // 2] if per_host \
        else DEFAULT_HOST_WORK_MS
    jobs: List[SimJob] = []
    for fold in sorted(st.jobs.values(), key=lambda f: f.seq):
        work = _work_chip_ms(fold, end_ms)
        if work <= 0:
            work = median * max(1, fold.hosts_requested)
        jobs.append(SimJob(
            job_id=fold.job_id, tenant=fold.tenant,
            priority=fold.priority, hosts=fold.hosts_requested,
            min_hosts=fold.min_hosts, model=fold.model, seq=fold.seq,
            submit_ms=fold.submitted_ms, work_chip_ms=work,
            recorded_state=fold.state
            if fold.state in fjournal.TERMINAL_STATES else ""))
    return Workload(slices=st.slices, hosts_per_slice=st.hosts_per_slice,
                    quotas=dict(st.quotas), jobs=jobs)


# ---------------------------------------------------------------------------
# counterfactual overrides
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Overrides:
    """One counterfactual configuration: what differs from the
    recorded policy. Everything defaults to "as recorded"."""

    quotas: Dict[str, int] = dataclasses.field(default_factory=dict)
    slices: Optional[int] = None
    hosts_per_slice: Optional[int] = None
    priorities: Dict[str, int] = dataclasses.field(default_factory=dict)
    preemption: bool = True
    defrag: bool = True
    restore: bool = True

    def describe(self) -> str:
        parts: List[str] = []
        for t in sorted(self.quotas):
            parts.append(f"quota.{t}={self.quotas[t]}")
        if self.slices is not None:
            parts.append(f"slices={self.slices}")
        if self.hosts_per_slice is not None:
            parts.append(f"hosts-per-slice={self.hosts_per_slice}")
        for j in sorted(self.priorities):
            parts.append(f"priority.{j}={self.priorities[j]}")
        if not self.preemption:
            parts.append("preemption=off")
        if not self.defrag:
            parts.append("defrag=off")
        if not self.restore:
            parts.append("restore=off")
        return " ".join(parts) or "baseline"

    def clone(self) -> "Overrides":
        return Overrides(quotas=dict(self.quotas), slices=self.slices,
                         hosts_per_slice=self.hosts_per_slice,
                         priorities=dict(self.priorities),
                         preemption=self.preemption, defrag=self.defrag,
                         restore=self.restore)


def _parse_bool(value: str) -> bool:
    v = value.strip().lower()
    if v in ("true", "1", "yes", "on"):
        return True
    if v in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"not a boolean: {value!r}")


def apply_override(ov: Overrides, key: str, value: str) -> None:
    """One ``--set``/``--sweep`` assignment onto ``ov``. Accepts the
    registered ``tony.fleet.*`` keys plus the whatif shorthands
    (``quota.<tenant>``, ``priority.<job>``, ``pool=SxH``). Inside
    sweep grids ``|`` stands in for ``,`` in quota specs."""
    key = key.strip()
    value = value.strip()
    if key in (K.FLEET_QUOTAS, "quotas"):
        ov.quotas.update(fpolicy.parse_quotas(value.replace("|", ",")))
    elif key.startswith("quota.") or key.startswith("quota:"):
        ov.quotas[key[len("quota."):]] = int(value)
    elif key in (K.FLEET_SLICES, "slices"):
        ov.slices = int(value)
    elif key in (K.FLEET_HOSTS_PER_SLICE, "hosts-per-slice"):
        ov.hosts_per_slice = int(value)
    elif key == "pool":
        ov.slices, ov.hosts_per_slice = parse_pool(value)
    elif key.startswith("priority.") or key.startswith("priority:"):
        ov.priorities[key[len("priority."):]] = int(value)
    elif key in (K.FLEET_SIM_PREEMPTION, "preemption"):
        ov.preemption = _parse_bool(value)
    elif key in (K.FLEET_SIM_DEFRAG, "defrag"):
        ov.defrag = _parse_bool(value)
    elif key in (K.FLEET_SIM_RESTORE, "restore"):
        ov.restore = _parse_bool(value)
    else:
        raise ValueError(
            f"unknown whatif key {key!r} (settable: {K.FLEET_QUOTAS}, "
            f"{K.FLEET_SLICES}, {K.FLEET_HOSTS_PER_SLICE}, "
            f"{K.FLEET_SIM_PREEMPTION}, {K.FLEET_SIM_DEFRAG}, "
            f"{K.FLEET_SIM_RESTORE}, quota.<tenant>, priority.<job>, "
            f"pool)")


def parse_pool(spec: str) -> Tuple[int, int]:
    """``2x4`` / ``2×4`` -> (slices, hosts_per_slice)."""
    s = spec.strip().lower().replace("×", "x")
    slices, sep, hps = s.partition("x")
    if not sep:
        raise ValueError(f"bad pool spec {spec!r} (need SLICESxHOSTS)")
    return int(slices), int(hps)


def build_overrides(sets: Optional[Iterable[str]] = None,
                    quotas: Optional[Iterable[str]] = None,
                    pool: Optional[str] = None,
                    priorities: Optional[Iterable[str]] = None
                    ) -> Overrides:
    """The CLI surface: ``--set k=v``, ``--quota tenant=N``,
    ``--pool SxH``, ``--priority job=P`` folded into one Overrides."""
    ov = Overrides()
    for spec in sets or []:
        key, sep, value = spec.partition("=")
        if not sep:
            raise ValueError(f"bad --set {spec!r} (need key=value)")
        apply_override(ov, key, value)
    for spec in quotas or []:
        tenant, sep, n = spec.partition("=")
        if not sep:
            raise ValueError(f"bad --quota {spec!r} (need tenant=N)")
        ov.quotas[tenant.strip()] = int(n)
    if pool:
        ov.slices, ov.hosts_per_slice = parse_pool(pool)
    for spec in priorities or []:
        job, sep, p = spec.partition("=")
        if not sep:
            raise ValueError(f"bad --priority {spec!r} (need job=P)")
        ov.priorities[job.strip()] = int(p)
    return ov


def expand_sweeps(base: Overrides,
                  sweeps: Iterable[str]) -> List[Tuple[str, Overrides]]:
    """``--sweep key=a,b,c`` grids -> the cartesian product of
    (label, Overrides), each a clone of ``base`` with the grid point
    applied. Capped at SWEEP_CAP combinations."""
    axes: List[Tuple[str, List[str]]] = []
    for spec in sweeps:
        key, sep, values = spec.partition("=")
        if not sep:
            raise ValueError(f"bad --sweep {spec!r} (need key=a,b,c)")
        vals = [v for v in (s.strip() for s in values.split(",")) if v]
        if not vals:
            raise ValueError(f"--sweep {spec!r} has no values")
        axes.append((key.strip(), vals))
    combos: List[List[Tuple[str, str]]] = [[]]
    for key, vals in axes:
        combos = [c + [(key, v)] for c in combos for v in vals]
        if len(combos) > SWEEP_CAP:
            raise ValueError(
                f"sweep grid exceeds {SWEEP_CAP} combinations")
    out: List[Tuple[str, Overrides]] = []
    for combo in combos:
        if not combo:
            continue
        ov = base.clone()
        for key, value in combo:
            apply_override(ov, key, value)
        out.append((" ".join(f"{k}={v}" for k, v in combo), ov))
    return out


# ---------------------------------------------------------------------------
# journal recorder: a simulated run written as a REAL fleet journal
# ---------------------------------------------------------------------------
class JournalRecorder:
    """Writes the simulated sequence as an ordinary fleet journal with
    the simulation's own timestamps — the fixture generator behind
    ``tests/fixtures/whatif_mix`` and the round-trip determinism tests.
    Record shapes match :class:`fleet.journal.FleetJournal`'s typed
    appenders exactly (explicit ``ts`` wins over the appender's
    wall-clock setdefault), so the output replays, parity-checks and
    invariant-checks like a daemon's journal."""

    def __init__(self, path: str) -> None:
        self._journal = fjournal.FleetJournal(path)

    def _append(self, ts: int, rec: Dict[str, Any]) -> None:
        rec["ts"] = int(ts)
        self._journal.append(rec)

    def generation(self, ts: int, wl: Workload) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_GEN, "generation": 1,
            "slices": wl.slices, "hosts_per_slice": wl.hosts_per_slice,
            "quotas": {str(t): int(q) for t, q in wl.quotas.items()}})

    def submit(self, ts: int, job: SimJob) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_SUBMIT, "job": job.job_id,
            "tenant": job.tenant, "priority": job.priority,
            "hosts": job.hosts, "min_hosts": job.min_hosts,
            "model": job.model, "seq": job.seq, "conf": {}})

    def grant(self, ts: int, job_id: str, hosts: int,
              placement: Dict[int, int]) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_GRANT, "job": job_id, "hosts": hosts,
            "placement": {str(i): int(n) for i, n in placement.items()}})

    def preempt(self, ts: int, job_id: str, from_hosts: int,
                to_hosts: int, for_job: str,
                placement: Dict[int, int]) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_PREEMPT, "job": job_id,
            "from": int(from_hosts), "to": int(to_hosts),
            "for": for_job,
            "placement": {str(i): int(n) for i, n in placement.items()}})

    def migrate(self, ts: int, job_id: str, source: int, target: int,
                placement: Dict[int, int], reason: str) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_MIGRATE, "job": job_id,
            "source": int(source), "target": int(target),
            "placement": {str(i): int(n) for i, n in placement.items()},
            "reason": reason})

    def decision(self, ts: int, d: fpolicy.Decision) -> None:
        self._append(ts, {
            "t": fjournal.REC_FLEET_DECISION, "job": d.job_id,
            "action": d.action, "reason": d.reason,
            "blocking": [str(b) for b in d.blocking],
            "free": int(d.free)})

    def state(self, ts: int, job_id: str, state: str,
              exit_code: Optional[int] = None, hosts: int = 0,
              placement: Optional[Dict[int, int]] = None) -> None:
        rec: Dict[str, Any] = {"t": fjournal.REC_FLEET_STATE,
                               "job": job_id, "state": state}
        if exit_code is not None:
            rec["exit"] = int(exit_code)
        if hosts:
            rec["hosts"] = int(hosts)
        if placement is not None:
            rec["placement"] = {str(i): int(n)
                                for i, n in placement.items()}
        self._append(ts, rec)

    def close(self) -> None:
        self._journal.close()


# ---------------------------------------------------------------------------
# the discrete-event simulation
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Run:
    """One granted job mid-flight: remaining chip-ms consumed at the
    current host rate; ``version`` invalidates stale finish events
    after a shrink/restore re-rates the job."""

    remaining_ms: int
    hosts: int
    last_ms: int
    version: int = 0
    done: bool = False


class _Sim:
    def __init__(self, wl: Workload, ov: Overrides,
                 recorder: Optional[JournalRecorder]) -> None:
        self.recorder = recorder
        self.defrag_on = ov.defrag
        self.restore_on = ov.restore
        slices = ov.slices if ov.slices is not None else wl.slices
        hps = ov.hosts_per_slice if ov.hosts_per_slice is not None \
            else wl.hosts_per_slice
        quotas = dict(wl.quotas)
        quotas.update(ov.quotas)
        self.quotas = {t: q for t, q in quotas.items() if q > 0}
        self.slices, self.hps = slices, hps
        self.engine = fpolicy.PolicyEngine(slices, hps, self.quotas)
        self.jobs: Dict[str, SimJob] = {}
        for j in wl.jobs:
            prio = ov.priorities.get(j.job_id, j.priority)
            # preemption off = every gang is rigid: no shrink floor, so
            # the preemption AND defrag planners find no elastic victims.
            minh = j.min_hosts if ov.preemption else 0
            self.jobs[j.job_id] = dataclasses.replace(
                j, priority=prio, min_hosts=minh)
        self.runs: Dict[str, _Run] = {}
        self.fence: Dict[str, str] = {}      # job -> last hold reason
        self.decisions: Dict[str, List[Dict[str, Any]]] = {}
        self.placements: Dict[str, Dict[int, int]] = {}
        self.host_events: Dict[str, List[Tuple[int, int]]] = {}
        self.granted_ms: Dict[str, int] = {}
        self.finished_ms: Dict[str, int] = {}
        self.refused: List[Dict[str, Any]] = []
        self.preemptions = self.migrations = self.restores = 0
        self._order = 0
        self._heap: List[Tuple[int, int, int, str, str, int]] = []

    # -- event plumbing --------------------------------------------------
    def _push(self, ms: int, kind: int, name: str, job_id: str,
              version: int) -> None:
        self._order += 1
        heapq.heappush(self._heap,
                       (ms, kind, self._order, name, job_id, version))

    def _consume(self, run: _Run, ts: int) -> None:
        run.remaining_ms -= (ts - run.last_ms) * run.hosts
        run.last_ms = ts

    def _push_finish(self, job_id: str, run: _Run, ts: int) -> None:
        left_ms = -(-max(0, run.remaining_ms) // max(1, run.hosts))
        self._push(ts + left_ms, 0, "finish", job_id, run.version)

    # -- the run ---------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        for j in sorted(self.jobs.values(), key=lambda j: j.seq):
            self._push(j.submit_ms, 1, "submit", j.job_id, 0)
        origin_ms = self._heap[0][0] if self._heap else 0
        if self.recorder:
            self.recorder.generation(
                origin_ms, Workload(self.slices, self.hps, self.quotas,
                                    []))
        end_ms = origin_ms
        while self._heap:
            ts = self._heap[0][0]
            while self._heap and self._heap[0][0] == ts:
                _, _, _, name, job_id, version = heapq.heappop(self._heap)
                if name == "submit":
                    self._submit(self.jobs[job_id], ts)
                else:
                    self._finish(job_id, version, ts)
            self._passes(ts)
            if self.restore_on:
                self._restores(ts)
            end_ms = max(end_ms, ts)
        if self.recorder:
            self.recorder.close()
        return self._result(origin_ms, end_ms)

    def _submit(self, job: SimJob, ts: int) -> None:
        req = fpolicy.JobRequest(
            job.job_id, job.tenant, priority=job.priority,
            hosts=job.hosts, min_hosts=job.min_hosts, model=job.model,
            seq=job.seq)
        try:
            self.engine.submit(req)
        except ValueError as e:
            # A counterfactual pool can be too small for a recorded
            # gang — the daemon refuses those at submit; so do we.
            self.refused.append({"job": job.job_id, "tenant": job.tenant,
                                 "hosts": job.hosts, "reason": str(e)})
            return
        if self.recorder:
            self.recorder.submit(ts, job)

    def _finish(self, job_id: str, version: int, ts: int) -> None:
        run = self.runs.get(job_id)
        if run is None or run.done or run.version != version:
            return                     # stale event after a re-rate
        self._consume(run, ts)
        run.done = True
        self.engine.release(job_id)
        self.finished_ms[job_id] = ts
        if self.recorder:
            state = self.jobs[job_id].recorded_state \
                or fjournal.STATE_FINISHED
            self.recorder.state(
                ts, job_id, state,
                exit_code=1 if state == fjournal.STATE_FAILED else 0)

    def _passes(self, ts: int) -> None:
        """Apply scheduling plans until a pass applies nothing — the
        same fixpoint a daemon reaches across consecutive ticks at one
        instant, with holds journaled inline in plan order like
        ``_apply_plan`` does."""
        for _ in range(10_000):
            plan = self.engine.schedule()
            applied = False
            for d in plan:
                if d.action == fpolicy.GRANT:
                    self._grant(d, ts)
                    applied = True
                elif d.action == fpolicy.SHRINK:
                    self._shrink(d, ts)
                    applied = True
                elif d.action == fpolicy.MIGRATE:
                    if self.defrag_on:
                        self._migrate(d, ts)
                        applied = True
                    # defrag off: the move never lands; the demander
                    # keeps its preempt-wait hold until capacity frees.
                elif d.action in fpolicy.HOLD_ACTIONS:
                    self._hold(d, ts)
            if not applied:
                return
        raise RuntimeError("simulation did not reach a scheduling "
                           "fixpoint (policy engine livelock?)")

    def _hold(self, d: fpolicy.Decision, ts: int) -> None:
        if self.fence.get(d.job_id) == d.reason:
            return                     # the daemon's dedup fence
        self.fence[d.job_id] = d.reason
        self.decisions.setdefault(d.job_id, []).append({
            "ts_ms": ts, "action": d.action, "reason": d.reason,
            "blocking": [str(b) for b in d.blocking],
            "free": int(d.free)})
        if self.recorder:
            self.recorder.decision(ts, d)

    def _grant(self, d: fpolicy.Decision, ts: int) -> None:
        self.engine.grant(d.job_id, d.placement)
        self.fence.pop(d.job_id, None)
        run = _Run(remaining_ms=self.jobs[d.job_id].work_chip_ms,
                   hosts=d.hosts, last_ms=ts)
        self.runs[d.job_id] = run
        self.granted_ms[d.job_id] = ts
        self.placements[d.job_id] = dict(d.placement)
        self.host_events[d.job_id] = [(ts, d.hosts)]
        self._push_finish(d.job_id, run, ts)
        if self.recorder:
            self.recorder.grant(ts, d.job_id, d.hosts, d.placement)

    def _shrink(self, d: fpolicy.Decision, ts: int) -> None:
        run = self.runs[d.job_id]
        self._consume(run, ts)
        from_hosts = run.hosts
        placement = self.engine.shrink_applied(d.job_id, d.hosts)
        run.hosts = d.hosts
        run.version += 1
        self._push_finish(d.job_id, run, ts)
        self.preemptions += 1
        self.placements[d.job_id] = placement
        self.host_events[d.job_id].append((ts, d.hosts))
        if self.recorder:
            self.recorder.preempt(ts, d.job_id, from_hosts, d.hosts,
                                  d.for_job, placement)

    def _migrate(self, d: fpolicy.Decision, ts: int) -> None:
        placement = self.engine.migrate_applied(d.job_id, d.placement)
        self.migrations += 1
        self.placements[d.job_id] = placement
        if self.recorder:
            self.recorder.migrate(ts, d.job_id, d.source, d.target,
                                  placement, d.reason)

    def _restores(self, ts: int) -> None:
        """Grow-back like the daemon's ``_restore``: one candidate at a
        time, re-planned after each (a grow changes what still fits)."""
        for _ in range(10_000):
            cands = self.engine.restore_candidates()
            if not cands:
                return
            job_id, new_hosts, delta = cands[0]
            run = self.runs[job_id]
            self._consume(run, ts)
            placement = self.engine.grow_applied(job_id, delta)
            run.hosts = new_hosts
            run.version += 1
            self._push_finish(job_id, run, ts)
            self.restores += 1
            self.placements[job_id] = placement
            self.host_events[job_id].append((ts, new_hosts))
            if self.recorder:
                self.recorder.state(ts, job_id,
                                    fjournal.STATE_RESTORED,
                                    hosts=new_hosts, placement=placement)
        raise RuntimeError("grow-back restores did not converge")

    # -- results ---------------------------------------------------------
    def _folds(self, end_ms: int) -> List[fjournal.JobFold]:
        out: List[fjournal.JobFold] = []
        refused = {r["job"] for r in self.refused}
        for j in sorted(self.jobs.values(), key=lambda j: j.seq):
            if j.job_id in refused:
                continue
            granted = self.granted_ms.get(j.job_id, 0)
            finished = self.finished_ms.get(j.job_id, 0)
            state = (j.recorded_state or fjournal.STATE_FINISHED) \
                if finished else "QUEUED" if not granted else "RUNNING"
            run = self.runs.get(j.job_id)
            out.append(fjournal.JobFold(
                job_id=j.job_id, tenant=j.tenant, priority=j.priority,
                hosts_requested=j.hosts, min_hosts=j.min_hosts,
                model=j.model, seq=j.seq, state=state,
                hosts=run.hosts if run else 0,
                placement=dict(self.placements.get(j.job_id, {})),
                submitted_ms=j.submit_ms, granted_ms=granted,
                finished_ms=finished,
                host_events=list(self.host_events.get(j.job_id, [])),
                decisions=list(self.decisions.get(j.job_id, []))))
        return out

    def _result(self, origin_ms: int, end_ms: int) -> Dict[str, Any]:
        folds = self._folds(end_ms)
        metrics, per_tenant = metrics_from_folds(
            folds, pool_chips=self.slices * self.hps, end_ms=end_ms,
            preemptions=self.preemptions, migrations=self.migrations,
            restores=self.restores, refused=len(self.refused))
        return {
            "config": {"slices": self.slices,
                       "hosts_per_slice": self.hps,
                       "quotas": dict(sorted(self.quotas.items()))},
            "metrics": metrics, "per_tenant": per_tenant,
            "refused": self.refused,
            "ungranted": sorted(f.job_id for f in folds
                                if not f.granted_ms),
        }


def simulate(wl: Workload, overrides: Optional[Overrides] = None,
             recorder: Optional[JournalRecorder] = None
             ) -> Dict[str, Any]:
    """Re-execute the workload through the real policy engine under
    ``overrides``; pure and deterministic (integer sim-time only)."""
    return _Sim(wl, overrides or Overrides(), recorder).run()


# ---------------------------------------------------------------------------
# shared metric fold (recorded journal and simulated run alike)
# ---------------------------------------------------------------------------
def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return round(sorted_vals[idx], 3)


def metrics_from_folds(folds: List[fjournal.JobFold], *,
                       pool_chips: int, end_ms: int, preemptions: int,
                       migrations: int, restores: int, refused: int = 0
                       ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """One metric/per-tenant rollup over job folds — the SAME code path
    for the recorded journal and every simulated run, so a diff never
    compares two accounting systems. Holds use the timeline module's
    interval algebra; goodput uses the journal-only ledger fold."""
    waits: List[float] = []
    tenant_waits: Dict[str, List[float]] = {}
    hold_s: Dict[str, float] = {k: 0.0 for k in HOLD_METRIC.values()}
    per_tenant: Dict[str, Dict[str, Any]] = {}
    ledgers: List[Dict[str, Any]] = []
    work_chip_ms = 0
    granted = ungranted = 0
    start_ms = min((f.submitted_ms for f in folds if f.submitted_ms),
                   default=0)
    for f in folds:
        bucket = per_tenant.setdefault(f.tenant, {
            "jobs": 0, "granted": 0,
            "holds_s": {}, "blocking": {}})
        bucket["jobs"] += 1
        if f.granted_ms:
            granted += 1
            bucket["granted"] += 1
            wait = max(0.0, (f.granted_ms - f.submitted_ms) / 1000.0)
            waits.append(wait)
            tenant_waits.setdefault(f.tenant, []).append(wait)
        else:
            ungranted += 1
        stop = f.finished_ms or end_ms
        work_chip_ms += _work_chip_ms(f, stop)
        intervals = ftimeline.hold_intervals(
            f.decisions, granted_ms=f.granted_ms,
            finished_ms=f.finished_ms, now_ms=end_ms,
            hosts=f.hosts_requested)
        for kind, summary in ftimeline.holds_summary(intervals).items():
            metric = HOLD_METRIC.get(kind)
            if metric is None:
                continue
            hold_s[metric] = round(hold_s[metric] + summary["seconds"], 3)
            hs = bucket["holds_s"]
            hs[metric] = round(hs.get(metric, 0.0)
                               + summary["seconds"], 3)
            blocking = bucket["blocking"].setdefault(metric, [])
            for b in summary["blocking"]:
                if b not in blocking:
                    blocking.append(b)
        ledgers.append(fledger.compute_job_ledger(f, job_dir=None,
                                                  now_ms=end_ms))
    roll = fledger.rollup(ledgers)
    makespan_s = max(0.0, (end_ms - start_ms) / 1000.0) if folds else 0.0
    util = round(work_chip_ms / 1000.0 / (pool_chips * makespan_s), 4) \
        if pool_chips > 0 and makespan_s > 0 else 0.0
    waits.sort()
    metrics: Dict[str, Any] = {
        "jobs": len(folds) + refused, "granted": granted,
        "ungranted": ungranted, "refused": refused,
        "makespan_s": round(makespan_s, 3),
        "queue_wait_p50_s": _pct(waits, 0.50),
        "queue_wait_p99_s": _pct(waits, 0.99),
        "queue_wait_mean_s": round(sum(waits) / len(waits), 3)
        if waits else 0.0,
        "preemptions": preemptions, "migrations": migrations,
        "restores": restores,
        "preemptions_per_job": round(preemptions / granted, 4)
        if granted else 0.0,
        "goodput_fraction": roll["fleet"]["goodput_fraction"],
        "utilization_fraction": util,
    }
    metrics.update(hold_s)
    for tenant, bucket in per_tenant.items():
        tw = sorted(tenant_waits.get(tenant, []))
        bucket["queue_wait_p50_s"] = _pct(tw, 0.50)
        bucket["queue_wait_p99_s"] = _pct(tw, 0.99)
        tb = roll["tenants"].get(tenant) or {}
        bucket["goodput_fraction"] = tb.get("goodput_fraction")
        bucket["blocking"] = {m: sorted(v)
                              for m, v in bucket["blocking"].items()}
    return metrics, {t: per_tenant[t] for t in sorted(per_tenant)}


def recorded_metrics(tl: ftimeline.FleetTimeline) -> Dict[str, Any]:
    """The journal's OWN metrics through the same fold the simulator
    uses — the 'recorded' column of every whatif report."""
    st = tl.state
    end_ms = max((int(r.get("ts", 0) or 0) for r in tl.records),
                 default=0)
    folds = sorted(st.jobs.values(), key=lambda f: f.seq)
    metrics, per_tenant = metrics_from_folds(
        folds, pool_chips=st.slices * st.hosts_per_slice, end_ms=end_ms,
        preemptions=tl.preemptions_total, migrations=tl.migrations_total,
        restores=tl.restores_total)
    return {"config": {"slices": st.slices,
                       "hosts_per_slice": st.hosts_per_slice,
                       "quotas": dict(sorted(st.quotas.items()))},
            "metrics": metrics, "per_tenant": per_tenant}


# ---------------------------------------------------------------------------
# parity mode: the calibration gate
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Mismatch:
    """One record the replayed policy engine would not have produced."""

    index: int          # record position in the journal
    kind: str           # grant | preempt | migrate | decision | restore
    expected: str
    recorded: str


def _fmt_decision(kind: str, d: fpolicy.Decision) -> str:
    if kind == "grant":
        return f"grant {d.job_id} hosts={d.hosts} placement={d.placement}"
    if kind == "preempt":
        return f"preempt {d.job_id} to={d.hosts} for={d.for_job}"
    if kind == "migrate":
        return (f"migrate {d.job_id} {d.source}->{d.target} "
                f"placement={d.placement}")
    return (f"decision {d.job_id} action={d.action} free={d.free} "
            f"blocking={d.blocking} reason={d.reason!r}")


def _fmt_record(kind: str, rec: Dict[str, Any]) -> str:
    job = rec.get("job", "?")
    if kind == "grant":
        return (f"grant {job} hosts={rec.get('hosts')} "
                f"placement={fjournal._placement(rec)}")
    if kind == "preempt":
        return (f"preempt {job} to={rec.get('to')} "
                f"for={rec.get('for', '')}")
    if kind == "migrate":
        return (f"migrate {job} {rec.get('source')}->{rec.get('target')} "
                f"placement={fjournal._placement(rec)}")
    if kind == "restore":
        return (f"restore {job} hosts={rec.get('hosts')} "
                f"placement={fjournal._placement(rec)}")
    return (f"decision {job} action={rec.get('action')} "
            f"free={rec.get('free')} blocking={rec.get('blocking')} "
            f"reason={str(rec.get('reason', ''))!r}")


class _ParityReplay:
    """Record-driven re-derivation: external records (submits, terminal
    states, generation bumps, health transitions) mutate the engine;
    actionable records (grants, preempts, migrates, decision holds) must
    match the head of the engine's own pending plan emissions. The
    daemon journals an applied plan in plan order within a tick, so the
    pending queue is consumed in order and rebuilt whenever external
    state lands (or, once, on a mismatch — a tick boundary after a
    partially-applied plan looks exactly like staleness)."""

    def __init__(self, tl: ftimeline.FleetTimeline) -> None:
        self.tl = tl
        self.engine: Optional[fpolicy.PolicyEngine] = None
        self.reqs: Dict[str, fpolicy.JobRequest] = {}
        self.job_state: Dict[str, str] = {}
        self.fence: Dict[str, str] = {}
        self.last_decision: Dict[str, str] = {}
        self.pending: List[Tuple[str, fpolicy.Decision]] = []
        self.mismatches: List[Mismatch] = []
        self.counts = {"grant": 0, "preempt": 0, "migrate": 0,
                       "decision": 0, "restore": 0}
        self.mismatch_counts = dict(self.counts)
        self.exogenous_migrations = 0
        self.notes: List[str] = []
        self.pool_sig: Optional[Tuple[int, int]] = None
        self.unsupported = ""

    # -- plan emissions --------------------------------------------------
    def _plan(self) -> List[Tuple[str, fpolicy.Decision]]:
        out: List[Tuple[str, fpolicy.Decision]] = []
        assert self.engine is not None
        for d in self.engine.schedule():
            if d.action == fpolicy.GRANT:
                out.append(("grant", d))
            elif d.action == fpolicy.SHRINK:
                out.append(("preempt", d))
            elif d.action == fpolicy.MIGRATE:
                out.append(("migrate", d))
            elif d.action in fpolicy.HOLD_ACTIONS \
                    and self.fence.get(d.job_id) != d.reason:
                out.append(("decision", d))
        return out

    def _invalidate(self) -> None:
        self.pending = []

    # -- record handlers -------------------------------------------------
    def replay(self) -> Dict[str, Any]:
        if self.tl.torn_tail:
            self.notes.append("torn tail: parity covers the decodable "
                              "prefix only")
        if not self.tl.terminal:
            return self._done(supported=False,
                              reason="journal is not terminal — a live "
                                     "queue's next decisions are not "
                                     "recorded yet")
        for idx, rec in enumerate(self.tl.records):
            t = rec.get("t")
            if t == fjournal.REC_FLEET_GEN:
                self._on_gen(rec)
            elif self.engine is None:
                return self._done(supported=False,
                                  reason="no fgen record before the "
                                         "first scheduler record")
            elif t == fjournal.REC_FLEET_SUBMIT:
                self._on_submit(rec, idx)
            elif t == fjournal.REC_FLEET_GRANT:
                self._match("grant", rec, idx)
            elif t == fjournal.REC_FLEET_PREEMPT:
                self._match("preempt", rec, idx)
            elif t == fjournal.REC_FLEET_DECISION:
                self._match("decision", rec, idx)
            elif t == fjournal.REC_FLEET_MIGRATE:
                self._on_migrate(rec, idx)
            elif t == fjournal.REC_FLEET_STATE:
                self._on_state(rec, idx)
            elif t == fjournal.REC_FLEET_HEALTH:
                self._on_health(rec)
            if self.unsupported:
                return self._done(supported=False,
                                  reason=self.unsupported)
        return self._done(supported=True)

    def _on_gen(self, rec: Dict[str, Any]) -> None:
        slices = int(rec.get("slices", 0) or 0)
        hps = int(rec.get("hosts_per_slice", 0) or 0)
        quotas = {str(t): int(q)
                  for t, q in (rec.get("quotas") or {}).items()}
        if self.engine is None:
            self.engine = fpolicy.PolicyEngine(slices, hps, quotas)
            self.pool_sig = (slices, hps)
            return
        if (slices, hps) != self.pool_sig:
            self.unsupported = ("pool shape changed mid-journal "
                                f"({self.pool_sig} -> {(slices, hps)})")
            return
        self.engine.quotas.clear()
        self.engine.quotas.update(quotas)
        # Recovery semantics (daemon._recover): GRANTED-but-never-
        # SPAWNED jobs are requeued at their original seq; RUNNING jobs
        # stay accounted at their journaled placement (our engine holds
        # them already). The recovered fence re-seeds from the fold.
        for job, state in sorted(self.job_state.items()):
            if state == "GRANTED":
                self.engine.release(job)
                req = self.reqs.get(job)
                if req is not None:
                    self.engine.submit(req)
                self.job_state[job] = "QUEUED"
                if job in self.last_decision:
                    self.fence[job] = self.last_decision[job]
        self._invalidate()

    def _on_submit(self, rec: Dict[str, Any], idx: int) -> None:
        job = str(rec.get("job", "") or "")
        req = fpolicy.JobRequest(
            job, str(rec.get("tenant", "") or ""),
            priority=int(rec.get("priority", 0) or 0),
            hosts=int(rec.get("hosts", 0) or 0),
            min_hosts=int(rec.get("min_hosts", 0) or 0),
            model=str(rec.get("model", "") or ""),
            seq=int(rec.get("seq", 0) or 0))
        self.reqs[job] = req
        assert self.engine is not None
        try:
            self.engine.submit(req)
            self.job_state[job] = "QUEUED"
        except ValueError as e:
            self.notes.append(f"record {idx}: fsubmit {job} not "
                              f"replayable ({e})")
        self._invalidate()

    def _on_state(self, rec: Dict[str, Any], idx: int) -> None:
        job = str(rec.get("job", "") or "")
        state = str(rec.get("state", "") or "")
        assert self.engine is not None
        if state in fjournal.TERMINAL_STATES:
            self.engine.release(job)
            self.job_state.pop(job, None)
            self.fence.pop(job, None)
            self._invalidate()
        elif state == fjournal.STATE_RESTORED:
            self._on_restore(rec, idx)
        elif state in (fjournal.STATE_SPAWNED, fjournal.STATE_RUNNING):
            if job in self.job_state:
                self.job_state[job] = "RUNNING"

    def _on_health(self, rec: Dict[str, Any]) -> None:
        """Best-effort cordon mirror. The journal does not carry the
        free/leased flag the live daemon used, so quarantines cordon a
        free host when one exists and restores uncordon when one is
        cordoned — exact for the common free-host case, approximate
        otherwise (noted; deferred cordon sweeps are invisible to
        parity either way)."""
        assert self.engine is not None
        i = int(rec.get("slice", -1))
        if not 0 <= i < self.engine.pool.slices:
            return
        state = str(rec.get("state", "") or "")
        try:
            if state == "quarantined":
                self.engine.pool.cordon_free(i)
            elif state == "healthy":
                # probation hosts STAY cordoned (canary re-admission);
                # only the healthy transition frees the cordon.
                self.engine.pool.uncordon(i)
            else:
                return
        except ValueError:
            return                    # leased host: the sweep is deferred
        note = "health cordon transitions approximated from fhealth fold"
        if note not in self.notes:
            self.notes.append(note)
        self._invalidate()

    # -- actionable record matching --------------------------------------
    def _compare(self, kind: str, d: fpolicy.Decision,
                 rec: Dict[str, Any]) -> bool:
        job = str(rec.get("job", "") or "")
        if d.job_id != job:
            return False
        if kind == "grant":
            return (int(rec.get("hosts", 0) or 0) == d.hosts
                    and fjournal._placement(rec) == d.placement)
        if kind == "preempt":
            return (int(rec.get("to", -1) or 0) == d.hosts
                    and str(rec.get("for", "") or "") == d.for_job)
        if kind == "migrate":
            return (int(rec.get("source", -2) or 0) == d.source
                    and int(rec.get("target", -2) or 0) == d.target
                    and fjournal._placement(rec) == d.placement)
        return (str(rec.get("action", "") or "") == d.action
                and str(rec.get("reason", "") or "") == d.reason
                and [str(b) for b in (rec.get("blocking") or [])]
                == [str(b) for b in d.blocking]
                and int(rec.get("free", 0) or 0) == d.free)

    def _apply(self, kind: str, d: fpolicy.Decision,
               rec: Dict[str, Any], idx: int) -> None:
        assert self.engine is not None
        job = d.job_id
        if kind == "grant":
            self.engine.grant(job, d.placement)
            self.job_state[job] = "GRANTED"
            self.fence.pop(job, None)
        elif kind == "preempt":
            applied = self.engine.shrink_applied(job, d.hosts)
            recorded = fjournal._placement(rec)
            if applied != recorded:
                # Plan-time and apply-time shrinks free the same slices
                # by contract; a divergence is a real finding.
                self._mismatch(
                    kind, idx,
                    expected=f"post-shrink placement {applied}",
                    recorded=f"post-shrink placement {recorded}")
                self._trust_placement(job, recorded)
        elif kind == "migrate":
            self.engine.migrate_applied(job, d.placement)
        else:
            self.fence[job] = d.reason
            self.last_decision[job] = d.reason

    def _match(self, kind: str, rec: Dict[str, Any], idx: int) -> None:
        self.counts[kind] += 1
        rebuilt = False
        for attempt in (0, 1):
            if not self.pending:
                self.pending = self._plan()
                rebuilt = True
            if self.pending:
                pkind, d = self.pending[0]
                if pkind == kind and self._compare(kind, d, rec):
                    self.pending.pop(0)
                    self._apply(kind, d, rec, idx)
                    return
            if rebuilt:
                break
            # Stale pending (tick boundary after a partial apply, or
            # external state since the plan): rebuild once and retry.
            self._invalidate()
        expected = _fmt_decision(*self.pending[0]) if self.pending \
            else "no planned emission"
        self._mismatch(kind, idx, expected=expected,
                       recorded=_fmt_record(kind, rec))
        self._trust(kind, rec)
        self._invalidate()

    def _on_migrate(self, rec: Dict[str, Any], idx: int) -> None:
        """A planned defrag/evacuation migrate must match like any
        emission; an UNPLANNED one is exogenous (operator `fleet
        migrate`) — applied and noted, never a mismatch."""
        self.counts["migrate"] += 1
        if not self.pending:
            self.pending = self._plan()
        if self.pending:
            pkind, d = self.pending[0]
            if pkind == "migrate" and self._compare("migrate", d, rec):
                self.pending.pop(0)
                self._apply("migrate", d, rec, idx)
                return
        job = str(rec.get("job", "") or "")
        self.exogenous_migrations += 1
        self.counts["migrate"] -= 1
        self.notes.append(
            f"record {idx}: exogenous migrate of {job} "
            f"(slice {rec.get('source')} -> {rec.get('target')}) — "
            f"applied as an operator move")
        self._trust_placement(job, fjournal._placement(rec))
        self._invalidate()

    def _on_restore(self, rec: Dict[str, Any], idx: int) -> None:
        self.counts["restore"] += 1
        assert self.engine is not None
        job = str(rec.get("job", "") or "")
        hosts = int(rec.get("hosts", 0) or 0)
        recorded = fjournal._placement(rec)
        for cand_job, new_hosts, delta in self.engine.restore_candidates():
            if cand_job != job or new_hosts != hosts:
                continue
            applied = self.engine.grow_applied(job, delta)
            if recorded and applied != recorded:
                self._mismatch(
                    "restore", idx,
                    expected=f"restore {job} placement {applied}",
                    recorded=_fmt_record("restore", rec))
                self._trust_placement(job, recorded)
            self._invalidate()
            return
        self._mismatch("restore", idx,
                       expected=f"no grow-back candidate for {job} "
                                f"at {hosts} hosts",
                       recorded=_fmt_record("restore", rec))
        self._trust(
            "restore", rec)
        self._invalidate()

    # -- mismatch bookkeeping & resync -----------------------------------
    def _mismatch(self, kind: str, idx: int, expected: str,
                  recorded: str) -> None:
        self.mismatch_counts[kind] += 1
        if len(self.mismatches) < 32:
            self.mismatches.append(Mismatch(index=idx, kind=kind,
                                            expected=expected,
                                            recorded=recorded))

    def _trust_placement(self, job: str,
                         placement: Dict[int, int]) -> None:
        """Resync the engine to a recorded placement we could not
        derive: re-book the job verbatim so later records still replay
        against a truthful pool."""
        assert self.engine is not None
        if not placement:
            return
        req = self.reqs.get(job) or fpolicy.JobRequest(job, "?")
        self.engine.release(job)
        try:
            self.engine.force_grant(req, sum(placement.values()),
                                    dict(placement))
            self.job_state.setdefault(job, "GRANTED")
        except ValueError as e:
            self.notes.append(f"resync of {job} at {placement} failed "
                              f"({e}) — pool accounting degraded")

    def _trust(self, kind: str, rec: Dict[str, Any]) -> None:
        job = str(rec.get("job", "") or "")
        if kind == "decision":
            reason = str(rec.get("reason", "") or "")
            self.fence[job] = reason
            self.last_decision[job] = reason
            return
        if kind == "preempt":
            self._trust_placement(job, fjournal._placement(rec))
            return
        self._trust_placement(job, fjournal._placement(rec))
        if kind == "grant":
            self.job_state[job] = "GRANTED"
            self.fence.pop(job, None)

    def _done(self, supported: bool, reason: str = "") -> Dict[str, Any]:
        gate = (self.mismatch_counts["grant"]
                + self.mismatch_counts["preempt"]) == 0
        return {
            "supported": supported,
            "reason": reason,
            "ok": supported and not self.mismatches,
            #: the check-rule gate: grant/preempt sequence bit-for-bit
            #: (decision/restore texts can legitimately drift across
            #: daemon versions; placements and victims cannot)
            "gate_ok": supported and gate,
            "records": len(self.tl.records),
            "torn_tail": self.tl.torn_tail,
            "counts": dict(self.counts),
            "mismatch_counts": dict(self.mismatch_counts),
            "mismatches": [dataclasses.asdict(m)
                           for m in self.mismatches],
            "exogenous_migrations": self.exogenous_migrations,
            "notes": list(self.notes),
        }


def parity_replay(tl: ftimeline.FleetTimeline) -> Dict[str, Any]:
    """The calibration gate: re-derive the journal's actionable records
    through the real policy engine and report every divergence."""
    return _ParityReplay(tl).replay()


# ---------------------------------------------------------------------------
# whatif: parity gate + baseline + counterfactual diffs
# ---------------------------------------------------------------------------
def diff_metrics(base: Dict[str, Any],
                 counter: Dict[str, Any]) -> Dict[str, Any]:
    """Per-metric delta with an improves/regresses verdict from the
    metric's direction (same convention profiling/benchdiff.py gates
    on)."""
    out: Dict[str, Any] = {}
    for key in sorted(set(base) | set(counter)):
        b, c = base.get(key), counter.get(key)
        if not isinstance(b, (int, float)) \
                or not isinstance(c, (int, float)) \
                or isinstance(b, bool) or isinstance(c, bool):
            continue
        delta = round(c - b, 4)
        entry: Dict[str, Any] = {"base": b, "counterfactual": c,
                                 "delta": delta}
        if delta and key in LOWER_BETTER:
            entry["improves"] = delta < 0
        elif delta and key in HIGHER_BETTER:
            entry["improves"] = delta > 0
        out[key] = entry
    return out


def _holds_removed(base_pt: Dict[str, Any],
                   cf_pt: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Which holds did the counterfactual remove, per tenant — the
    report's causal citation (blocking jobs come from the BASE run's
    hold summary: they held the capacity the change freed)."""
    out: List[Dict[str, Any]] = []
    for tenant in sorted(base_pt):
        base_holds = base_pt[tenant].get("holds_s") or {}
        cf_holds = (cf_pt.get(tenant) or {}).get("holds_s") or {}
        for metric in sorted(base_holds):
            before = float(base_holds.get(metric, 0.0) or 0.0)
            after = float(cf_holds.get(metric, 0.0) or 0.0)
            if before - after > 0.001:
                out.append({
                    "tenant": tenant, "hold": metric,
                    "before_s": round(before, 3),
                    "after_s": round(after, 3),
                    "removed_s": round(before - after, 3),
                    "was_blocking": (base_pt[tenant].get("blocking")
                                     or {}).get(metric, [])})
    return out


def whatif(tl: ftimeline.FleetTimeline,
           overrides: Optional[Overrides] = None,
           sweeps: Optional[Iterable[str]] = None, *,
           parity: bool = True) -> Dict[str, Any]:
    """The full report: parity gate, recorded metrics, simulated
    baseline (recorded config through the simulator — the honest
    comparison basis for counterfactuals), then one diffed run per
    override set / sweep grid point."""
    wl = fold_workload(tl)
    report: Dict[str, Any] = {
        "journal": tl.path,
        "jobs": len(wl.jobs),
        "records": len(tl.records),
    }
    if parity:
        report["parity"] = parity_replay(tl)
    report["recorded"] = recorded_metrics(tl)
    base = simulate(wl)
    report["base"] = base
    runs: List[Tuple[str, Overrides]] = []
    if overrides is not None and overrides.describe() != "baseline":
        runs.append((overrides.describe(), overrides))
    if sweeps:
        runs.extend(expand_sweeps(overrides or Overrides(), sweeps))
    counterfactuals: List[Dict[str, Any]] = []
    for label, ov in runs:
        cf = simulate(wl, ov)
        counterfactuals.append({
            "label": label,
            "config": cf["config"],
            "metrics": cf["metrics"],
            "per_tenant": cf["per_tenant"],
            "refused": cf["refused"],
            "diff": diff_metrics(base["metrics"], cf["metrics"]),
            "holds_removed": _holds_removed(base["per_tenant"],
                                            cf["per_tenant"]),
        })
    report["counterfactuals"] = counterfactuals
    return report


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------
_TABLE_KEYS = ("goodput_fraction", "utilization_fraction",
               "queue_wait_p50_s", "queue_wait_p99_s", "makespan_s",
               "preemptions", "migrations", "restores", "quota_hold_s",
               "fragmentation_hold_s", "capacity_hold_s",
               "preempt_wait_hold_s", "priority_hold_s", "ungranted",
               "refused")


def _cell(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".") or "0"
    return str(v)


def render_report(report: Dict[str, Any]) -> str:
    lines: List[str] = [f"fleet whatif — {report.get('journal', '?')} "
                        f"({report.get('jobs', 0)} jobs, "
                        f"{report.get('records', 0)} records)"]
    par = report.get("parity")
    if par is not None:
        if not par.get("supported"):
            lines.append(f"parity: SKIPPED — {par.get('reason', '?')}")
        elif par.get("ok"):
            lines.append("parity: OK — the recorded decision/grant/"
                         "preempt sequence reproduces bit-for-bit")
        else:
            mc = par.get("mismatch_counts") or {}
            summary = ", ".join(f"{k}={v}" for k, v in sorted(mc.items())
                                if v)
            gate = "gate HOLDS (grant/preempt clean)" \
                if par.get("gate_ok") else "gate BROKEN"
            lines.append(f"parity: {summary or 'mismatches'} — {gate}; "
                         f"counterfactuals are NOT trustworthy beyond "
                         f"the gate")
            for m in (par.get("mismatches") or [])[:5]:
                lines.append(f"  record {m['index']} [{m['kind']}]: "
                             f"expected {m['expected']}; recorded "
                             f"{m['recorded']}")
        for note in par.get("notes") or []:
            lines.append(f"  note: {note}")
    rec = (report.get("recorded") or {}).get("metrics") or {}
    base = (report.get("base") or {}).get("metrics") or {}
    lines.append("")
    lines.append(f"{'metric':<24}{'recorded':>12}{'sim-base':>12}")
    for key in _TABLE_KEYS:
        if key in rec or key in base:
            lines.append(f"{key:<24}{_cell(rec.get(key)):>12}"
                         f"{_cell(base.get(key)):>12}")
    for cf in report.get("counterfactuals") or []:
        lines.append("")
        lines.append(f"counterfactual [{cf['label']}]:")
        lines.append(f"  {'metric':<24}{'base':>12}{'whatif':>12}"
                     f"{'delta':>12}")
        diff = cf.get("diff") or {}
        for key in _TABLE_KEYS:
            entry = diff.get(key)
            if not entry or not entry.get("delta"):
                continue
            mark = ""
            if entry.get("improves") is True:
                mark = "  (improves)"
            elif entry.get("improves") is False:
                mark = "  (regresses)"
            lines.append(f"  {key:<24}{_cell(entry['base']):>12}"
                         f"{_cell(entry['counterfactual']):>12}"
                         f"{_cell(entry['delta']):>12}{mark}")
        for h in cf.get("holds_removed") or []:
            blocking = ", ".join(h["was_blocking"]) or "-"
            lines.append(f"  removed {h['removed_s']}s of "
                         f"{h['hold'].replace('_s', '')} for tenant "
                         f"{h['tenant']!r} (was blocking: {blocking})")
        for r in cf.get("refused") or []:
            lines.append(f"  refused {r['job']} ({r['hosts']} hosts): "
                         f"{r['reason']}")
    return "\n".join(lines)


def whatif_from_dir(fleet_dir: Optional[str] = None, *,
                    path: Optional[str] = None,
                    sets: Optional[Iterable[str]] = None,
                    quotas: Optional[Iterable[str]] = None,
                    pool: Optional[str] = None,
                    priorities: Optional[Iterable[str]] = None,
                    sweeps: Optional[Iterable[str]] = None,
                    parity: bool = True) -> Dict[str, Any]:
    """CLI/portal entry: load the journal through the shared timeline
    fold and run the full report."""
    tl = ftimeline.load(fleet_dir, path=path)
    ov = build_overrides(sets=sets, quotas=quotas, pool=pool,
                         priorities=priorities)
    return whatif(tl, ov, sweeps, parity=parity)


# ---------------------------------------------------------------------------
# no-deps CLI smoke (python -m tony_tpu.fleet.simulator)
# ---------------------------------------------------------------------------
def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    from tony_tpu import constants

    ap = argparse.ArgumentParser(
        prog="python -m tony_tpu.fleet.simulator",
        description="what-if replay of a recorded fleet journal "
                    "(the no-deps smoke behind tony-tpu fleet whatif)")
    ap.add_argument("target", help="fleet dir or journal file")
    ap.add_argument("--set", action="append", default=[], metavar="K=V")
    ap.add_argument("--quota", action="append", default=[],
                    metavar="TENANT=N")
    ap.add_argument("--pool", default="", metavar="SxH")
    ap.add_argument("--priority", action="append", default=[],
                    metavar="JOB=P")
    ap.add_argument("--sweep", action="append", default=[],
                    metavar="K=a,b,c")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--expect-parity", action="store_true",
                    help="exit 1 unless the parity gate reproduces the "
                         "recorded sequence bit-for-bit")
    ap.add_argument("--expect-improves", default="", metavar="T:METRIC",
                    help="exit 1 unless the first counterfactual "
                         "strictly improves tenant T's METRIC "
                         "(e.g. capped:queue_wait_p99_s)")
    args = ap.parse_args(argv)
    path = args.target
    if os.path.isdir(path):
        path = os.path.join(path, constants.FLEET_JOURNAL_FILE)
    report = whatif_from_dir(
        path=path, sets=args.set, quotas=args.quota,
        pool=args.pool or None, priorities=args.priority,
        sweeps=args.sweep)
    print(json.dumps(report, indent=1, sort_keys=True) if args.json
          else render_report(report))
    rc = 0
    par = report.get("parity") or {}
    if args.expect_parity and not par.get("ok"):
        print(f"PARITY FAILED: {par.get('mismatch_counts')} "
              f"{par.get('reason', '')}".strip())
        rc = 1
    if args.expect_improves:
        tenant, sep, metric = args.expect_improves.partition(":")
        if not sep:
            ap.error("--expect-improves needs TENANT:METRIC")
        cfs = report.get("counterfactuals") or []
        if not cfs:
            print("EXPECT-IMPROVES FAILED: no counterfactual ran")
            rc = 1
        else:
            base_v = ((report["base"]["per_tenant"].get(tenant) or {})
                      .get(metric))
            cf_v = ((cfs[0]["per_tenant"].get(tenant) or {})
                    .get(metric))
            if base_v is None or cf_v is None or not cf_v < base_v:
                print(f"EXPECT-IMPROVES FAILED: {tenant}:{metric} "
                      f"base={base_v} counterfactual={cf_v}")
                rc = 1
            else:
                print(f"improves: {tenant}:{metric} {base_v} -> {cf_v}")
    return rc


if __name__ == "__main__":
    raise SystemExit(_main())
