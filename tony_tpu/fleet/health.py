"""Host health: failure attribution, quarantine, probation, probes.

The reference delegated node health entirely to YARN (the NodeManager
health check + node blacklist); this module is that last substrate
layer for the fleet. Without it a flaky host is re-granted to the next
job forever and every retry can land straight back on the machine that
just killed the task.

Model:

- Hosts are the fleet pool's ``slices x hosts_per_slice`` slots, named
  ``s<slice>h<index>`` (synthetic, stable identity — the policy engine
  accounts counts, this book accounts WHICH hosts those counts are).
- Every attributed failure (TASK_FINISHED with an infra failure domain,
  heartbeat expiry, host.loss absorb, straggler/hang kill — USER_ERROR
  never counts: a user bug says nothing about the machine) adds to a
  per-host score that DECAYS with a half-life, so one bad afternoon
  does not brand a host forever but a recurring fault accumulates.
- The score drives a state machine::

      healthy -> suspect -> quarantined -> probation -> healthy
                                 ^                |
                                 +--- (failure) --+  cooldown doubles

  Quarantined/probation hosts are CORDONED: the placement filter takes
  them out of the free pool, so no grant, retry or warm-pool lease can
  land on them. Quarantine expires into probation after a cooldown;
  probation re-admits the host only via a low-priority CANARY grant —
  a clean canary run restores the host, a failed one re-quarantines it
  with a doubled cooldown (exponential backoff on repeat offenders).
- Correlated detection: N suspect-or-worse hosts on ONE slice inside a
  window is a sick slice, not N sick hosts — the whole slice cordons
  and the daemon triggers evacuation migration off it.
- Every transition is journaled write-ahead as a ``REC_FLEET_HEALTH``
  record (fleet/journal.py) carrying its own evidence, so ``fleet
  start --recover`` resumes the identical cordon set and ``tony-tpu
  check`` can audit that no quarantine lacks attributed failures.

Pure and clock-injected (callers pass monotonic ``now``) so the state
machine unit-tests exhaustively without sleeping.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
from typing import Any, Dict, List, Optional, Tuple

from tony_tpu import faults

log = logging.getLogger(__name__)

#: host health states (the REC_FLEET_HEALTH "state" field)
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
#: states whose hosts are cordoned out of the placement pool
CORDONED_STATES = (QUARANTINED, PROBATION)

#: score added per attributed failure, by evidence kind. PREEMPTION is
#: the substrate reclaiming capacity — barely the host's fault, but a
#: host that keeps landing preemptions is worth suspicion; USER_ERROR
#: is never attributed (enforced by the callers, asserted here).
KIND_WEIGHTS: Dict[str, float] = {
    "INFRA_TRANSIENT": 1.0,
    "PREEMPTION": 0.25,
    "hang": 1.0,             # TASK_HUNG: wedged user process on this host
    "straggler": 0.5,        # TASK_STRAGGLER: persistent slow outlier
    "probe": 0.0,            # probe failures cordon directly, not by score
    "manual": 0.0,
}


def host_name(slice_index: int, host_index: int) -> str:
    """The synthetic stable host id for a pool slot."""
    return f"s{int(slice_index)}h{int(host_index)}"


def slice_of(host: str) -> int:
    """Slice index encoded in a host id (-1 for a malformed id)."""
    if not host.startswith("s") or "h" not in host:
        return -1
    try:
        return int(host[1:host.index("h")])
    except ValueError:
        return -1


@dataclasses.dataclass
class HealthConfig:
    """The tony.health.* conf family, resolved once at daemon start."""

    enabled: bool = True
    half_life_s: float = 300.0        # score half-life
    suspect_threshold: float = 1.0    # score >= this -> suspect
    quarantine_threshold: float = 3.0  # score >= this -> quarantined
    quarantine_s: float = 120.0       # initial quarantine cooldown
    probation_priority: int = 0       # canary grants: priority <= this
    blast_n: int = 2                  # suspects on one slice -> sick slice
    blast_window_s: float = 120.0     # ...inside this window
    evidence_cap: int = 16            # evidence entries kept per host


@dataclasses.dataclass
class HostHealth:
    """One host's ledger entry."""

    host: str
    slice_index: int
    state: str = HEALTHY
    score: float = 0.0
    manual: bool = False              # operator cordon (never auto-expires)
    updated_mono: float = 0.0         # decay anchor
    cordoned_mono: float = 0.0        # when the quarantine began
    cooldown_s: float = 0.0           # current quarantine cooldown (backoff)
    evidence: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    @property
    def cordoned(self) -> bool:
        return self.state in CORDONED_STATES


class HostBook:
    """Per-host identity + health over the fleet pool, kept in lockstep
    with the policy engine's :class:`SlicePool` counts: for every slice
    ``len(free_hosts(i)) == pool free`` and ``len(cordoned on i) ==
    pool cordoned``. The daemon owns the lock; this book is plain
    state."""

    def __init__(self, slices: int, hosts_per_slice: int,
                 config: Optional[HealthConfig] = None) -> None:
        self.slices = int(slices)
        self.hosts_per_slice = int(hosts_per_slice)
        self.config = config or HealthConfig()
        self.hosts: Dict[str, HostHealth] = {}
        self._free: List[List[str]] = []
        for i in range(self.slices):
            ids = [host_name(i, j) for j in range(self.hosts_per_slice)]
            self._free.append(list(ids))
            for h in ids:
                self.hosts[h] = HostHealth(host=h, slice_index=i)
        #: job -> assigned host ids (insertion order = task index order)
        self.assigned: Dict[str, List[str]] = {}
        #: slices already declared sick (one evacuation per episode)
        self._sick_slices: set = set()

    # -- queries ---------------------------------------------------------
    def free_hosts(self, slice_index: int) -> List[str]:
        return list(self._free[slice_index])

    def cordoned_hosts(self) -> List[HostHealth]:
        return sorted((h for h in self.hosts.values() if h.cordoned),
                      key=lambda h: h.host)

    def cordoned_names(self) -> List[str]:
        return [h.host for h in self.cordoned_hosts()]

    @property
    def sick_slices(self) -> List[int]:
        """Slices currently in a declared sick episode."""
        return sorted(self._sick_slices)

    def host_of_task(self, job_id: str, task_index: int) -> str:
        """Which host a job's task index runs on (tasks round-robin over
        the assigned hosts in order)."""
        hosts = self.assigned.get(job_id) or []
        if not hosts:
            return ""
        return hosts[int(task_index) % len(hosts)]

    def snapshot(self, now: float) -> List[Dict[str, Any]]:
        """Status rows for `fleet health` / the portal, worst first."""
        rank = {QUARANTINED: 0, PROBATION: 1, SUSPECT: 2, HEALTHY: 3}
        rows = []
        for h in sorted(self.hosts.values(),
                        key=lambda h: (rank.get(h.state, 9), h.host)):
            self._decay(h, now)
            rows.append({
                "host": h.host, "slice": h.slice_index, "state": h.state,
                "score": round(h.score, 3), "manual": h.manual,
                "cooldown_s": round(h.cooldown_s, 1),
                "failures": len(h.evidence),
                "evidence": list(h.evidence[-4:]),
            })
        return rows

    # -- scoring + the state machine -------------------------------------
    def _decay(self, h: HostHealth, now: float) -> None:
        if h.updated_mono and now > h.updated_mono and h.score > 0:
            h.score *= 0.5 ** ((now - h.updated_mono)
                               / max(1e-6, self.config.half_life_s))
        h.updated_mono = now

    def record_failure(self, host: str, kind: str, job_id: str,
                       now: float, ts_ms: int = 0) -> List[Dict[str, Any]]:
        """One attributed failure landed on ``host``. Returns the
        journal-ready transition records it caused (possibly none —
        scores accumulate silently below the thresholds). Callers must
        never attribute USER_ERROR."""
        assert kind != "USER_ERROR", "user errors are never attributed"
        h = self.hosts.get(host)
        if h is None:
            return []
        self._decay(h, now)
        h.score += KIND_WEIGHTS.get(kind, 1.0)
        h.evidence.append({"ts": int(ts_ms), "kind": kind, "job": job_id})
        del h.evidence[:-self.config.evidence_cap]
        out: List[Dict[str, Any]] = []
        if h.state == PROBATION:
            # A probationer that fails again goes straight back behind
            # the fence, and waits twice as long for its next chance.
            out.append(self._quarantine(
                h, now, reason=f"probation failure ({kind} in {job_id})",
                backoff=True))
        elif h.state in (HEALTHY, SUSPECT) \
                and h.score >= self.config.quarantine_threshold:
            out.append(self._quarantine(
                h, now,
                reason=f"score {h.score:.2f} >= quarantine threshold "
                       f"{self.config.quarantine_threshold:g}"))
        elif h.state == HEALTHY \
                and h.score >= self.config.suspect_threshold:
            h.state = SUSPECT
            out.append(self._record(
                h, reason=f"score {h.score:.2f} >= suspect threshold "
                          f"{self.config.suspect_threshold:g}"))
        return out

    def _quarantine(self, h: HostHealth, now: float, reason: str,
                    manual: bool = False,
                    backoff: bool = False) -> Dict[str, Any]:
        h.state = QUARANTINED
        h.manual = manual
        h.cordoned_mono = now
        if backoff and h.cooldown_s > 0:
            h.cooldown_s *= 2
        elif h.cooldown_s <= 0:
            h.cooldown_s = self.config.quarantine_s
        # A free host cordons immediately; an assigned one stays booked
        # until its job releases (the daemon sweeps it then). ``was_free``
        # on the record tells the daemon whether the pool's free count
        # must move to cordoned NOW (vs at job release).
        free = self._free[h.slice_index]
        was_free = h.host in free
        if was_free:
            free.remove(h.host)
        rec = self._record(h, reason=reason)
        rec["was_free"] = was_free
        return rec

    def cordon(self, host: str, reason: str, now: float,
               manual: bool = False, kind: str = "manual",
               ts_ms: int = 0) -> Optional[Dict[str, Any]]:
        """Force-quarantine (operator cordon, probe failure, sick
        slice). Returns the transition record, or None for an unknown
        host. ``was_free`` on the record tells the caller whether the
        pool's free count must drop NOW (vs at job release). The cause
        lands in the evidence trail too (kind "manual"/"probe"/...) so
        every quarantine record is self-evidencing — the
        health-quarantine-evidence check audits exactly that."""
        h = self.hosts.get(host)
        if h is None:
            return None
        self._decay(h, now)
        h.evidence.append({"ts": int(ts_ms), "kind": kind, "job": ""})
        del h.evidence[:-self.config.evidence_cap]
        return self._quarantine(h, now, reason=reason, manual=manual)

    def uncordon(self, host: str, now: float,
                 reason: str = "operator uncordon") -> Optional[Dict[str, Any]]:
        """Restore a cordoned host to service (operator verb, or a
        clean canary). Returns the transition record (with
        ``was_free`` False — the host re-enters the free pool only if
        it is not currently assigned), or None when the host is
        unknown or not cordoned."""
        h = self.hosts.get(host)
        if h is None or not h.cordoned:
            return None
        h.state = HEALTHY
        h.score = 0.0
        h.manual = False
        h.cooldown_s = 0.0
        h.updated_mono = now
        assigned = any(h.host in hs for hs in self.assigned.values())
        if not assigned and h.host not in self._free[h.slice_index]:
            self._free[h.slice_index].append(h.host)
            self._free[h.slice_index].sort()
        rec = self._record(h, reason=reason)
        rec["was_free"] = False
        rec["now_free"] = not assigned
        return rec

    def tick(self, now: float) -> Tuple[List[Dict[str, Any]], List[int]]:
        """Periodic pass: decay scores, expire suspects, roll
        quarantines into probation, and run correlated (sick-slice)
        detection. Returns (transition records, newly sick slices)."""
        out: List[Dict[str, Any]] = []
        for h in self.hosts.values():
            self._decay(h, now)
            if h.state == SUSPECT \
                    and h.score < self.config.suspect_threshold:
                h.state = HEALTHY
                out.append(self._record(
                    h, reason=f"score decayed to {h.score:.2f} < "
                              f"suspect threshold"))
            elif h.state == QUARANTINED and not h.manual \
                    and now - h.cordoned_mono >= h.cooldown_s:
                h.state = PROBATION
                out.append(self._record(
                    h, reason=f"quarantine cooldown "
                              f"({h.cooldown_s:.0f}s) expired — "
                              f"awaiting canary"))
        sick = self._detect_sick_slices(now)
        for i in sick:
            for h in self.hosts.values():
                if h.slice_index == i and h.state != QUARANTINED:
                    rec = self.cordon(
                        h.host, now=now, kind="slice",
                        reason=f"sick slice {i}: correlated failures "
                               f"across >= {self.config.blast_n} hosts")
                    if rec is not None:
                        out.append(rec)
        return out, sick

    def _detect_sick_slices(self, now: float) -> List[int]:
        window_ms = self.config.blast_window_s * 1000.0
        newest = 0
        for h in self.hosts.values():
            for ev in h.evidence:
                newest = max(newest, int(ev.get("ts", 0) or 0))
        sick: List[int] = []
        for i in range(self.slices):
            bad = 0
            for h in self.hosts.values():
                if h.slice_index != i or h.state == HEALTHY:
                    continue
                recent = any(newest - int(ev.get("ts", 0) or 0)
                             <= window_ms for ev in h.evidence)
                if recent or h.state == QUARANTINED:
                    bad += 1
            if bad >= self.config.blast_n and i not in self._sick_slices:
                self._sick_slices.add(i)
                sick.append(i)
            elif bad < self.config.blast_n:
                self._sick_slices.discard(i)
        return sick

    # -- assignment (lockstep with SlicePool allocate/release) -----------
    def assign(self, job_id: str, placement: Dict[int, int],
               priority: int,
               now: float) -> Tuple[List[str], List[Dict[str, Any]]]:
        """Pick concrete hosts for a grant placement. Low-priority
        grants (priority <= probation canary threshold) substitute at
        most ONE probation host per slice for a free one — the canary
        lease. Returns (assigned host ids, transition records for the
        canary re-admissions; each carries ``canary: True`` so the
        daemon can uncordon the pool slot)."""
        chosen: List[str] = []
        canaries: List[Dict[str, Any]] = []
        for i in sorted(placement):
            n = int(placement[i])
            free = self._free[i]
            take = sorted(free)[:n]
            if len(take) < n:
                raise ValueError(
                    f"slice {i}: placement wants {n} hosts but only "
                    f"{len(take)} identities are free (book out of "
                    f"sync with the pool)")
            if priority <= self.config.probation_priority:
                canary = next(
                    (h for h in self.cordoned_hosts()
                     if h.slice_index == i and h.state == PROBATION),
                    None)
                if canary is not None:
                    # swap: the canary takes a slot, one free host stays
                    take = take[:-1] + [canary.host]
                    rec = self._record(
                        canary,
                        reason=f"canary re-admission into {job_id!r} "
                               f"(priority {priority} <= "
                               f"{self.config.probation_priority})")
                    rec["canary"] = True
                    canaries.append(rec)
            for h in take:
                if h in free:
                    free.remove(h)
            chosen.extend(sorted(take))
        self.assigned[job_id] = chosen
        return chosen, canaries

    def unassign(self, job_id: str) -> None:
        """Back out an assignment that never became a grant (the probe
        self-repair loop): healthy hosts re-enter the free pool;
        cordoned picks (the canary, probe-cordoned hosts) stay out and
        keep their state."""
        for name in self.assigned.pop(job_id, []):
            h = self.hosts.get(name)
            if h is None or h.cordoned:
                continue
            if name not in self._free[h.slice_index]:
                self._free[h.slice_index].append(name)
                self._free[h.slice_index].sort()

    def adopt(self, job_id: str, placement: Dict[int, int],
              host_ids: Optional[List[str]] = None) -> List[str]:
        """Recovery path: re-book a running job's hosts (journaled ids
        when the grant record carried them, else lowest-free)."""
        chosen: List[str] = []
        for i in sorted(placement):
            need = int(placement[i])
            journaled = [h for h in (host_ids or [])
                         if slice_of(h) == i and h in self._free[i]]
            take = journaled[:need]
            for h in sorted(self._free[i]):
                if len(take) >= need:
                    break
                if h not in take:
                    take.append(h)
            for h in take:
                self._free[i].remove(h)
            chosen.extend(sorted(take))
        self.assigned[job_id] = chosen
        return chosen

    def release(self, job_id: str, now: float,
                failed: bool = False) -> Tuple[Dict[int, int],
                                               List[Dict[str, Any]]]:
        """A job released its hosts. Cordon-pending hosts (quarantined
        while assigned) stay out of the free pool — the returned
        ``{slice: count}`` of newly cordoned slots tells the daemon to
        move the pool's accounting from free to cordoned. Probation
        canaries resolve here: a clean run restores the host, a failed
        one re-quarantines with doubled cooldown."""
        hosts = self.assigned.pop(job_id, [])
        newly_cordoned: Dict[int, int] = {}
        out: List[Dict[str, Any]] = []
        for name in hosts:
            h = self.hosts.get(name)
            if h is None:
                continue
            if h.state == PROBATION:
                if failed:
                    rec = self._quarantine(
                        h, now, reason=f"canary job {job_id!r} failed",
                        backoff=True)
                    out.append(rec)
                    newly_cordoned[h.slice_index] = \
                        newly_cordoned.get(h.slice_index, 0) + 1
                    continue
                h.state = HEALTHY
                h.score = 0.0
                h.cooldown_s = 0.0
                out.append(self._record(
                    h, reason=f"canary job {job_id!r} completed clean"))
            if h.cordoned:
                # deferred cordon: the slot leaves service only now
                newly_cordoned[h.slice_index] = \
                    newly_cordoned.get(h.slice_index, 0) + 1
                continue
            if name not in self._free[h.slice_index]:
                self._free[h.slice_index].append(name)
                self._free[h.slice_index].sort()
        return newly_cordoned, out

    def reconcile(self, job_id: str,
                  placement: Dict[int, int]) -> Dict[int, int]:
        """A shrink/migration changed a job's per-slice counts: trim or
        extend the job's host set to match. Freed cordon-pending slots
        are returned as ``{slice: count}`` (same contract as
        ``release``); freed healthy hosts re-enter the pool."""
        hosts = self.assigned.get(job_id)
        if hosts is None:
            return {}
        want = {int(i): int(n) for i, n in placement.items()}
        by_slice: Dict[int, List[str]] = {}
        for name in hosts:
            by_slice.setdefault(slice_of(name), []).append(name)
        newly_cordoned: Dict[int, int] = {}
        kept: List[str] = []
        for i in sorted(set(by_slice) | set(want)):
            have = by_slice.get(i, [])
            need = want.get(i, 0)
            # Free cordon-pending hosts FIRST — a shrink is the fastest
            # way to get a sick slot out of a live gang.
            have.sort(key=lambda n: (not self.hosts[n].cordoned, n))
            while len(have) > need:
                name = have.pop(0)
                h = self.hosts[name]
                if h.cordoned:
                    newly_cordoned[i] = newly_cordoned.get(i, 0) + 1
                else:
                    self._free[i].append(name)
                    self._free[i].sort()
            while len(have) < need and self._free[i]:
                have.append(self._free[i].pop(0))
            kept.extend(sorted(have))
        self.assigned[job_id] = kept
        return newly_cordoned

    # -- journal round-trip ----------------------------------------------
    def _record(self, h: HostHealth, reason: str) -> Dict[str, Any]:
        """A journal-ready REC_FLEET_HEALTH payload for the host's
        CURRENT state (self-contained: carries its own evidence so
        `tony-tpu check` audits quarantines without cross-referencing)."""
        return {"host": h.host, "slice": h.slice_index, "state": h.state,
                "score": round(h.score, 4), "reason": reason,
                "manual": bool(h.manual),
                "cooldown_s": round(h.cooldown_s, 1),
                "evidence": list(h.evidence)}

    def apply_record(self, rec: Dict[str, Any], now: float) -> None:
        """Recovery: fold one replayed REC_FLEET_HEALTH record
        (last-wins per host). Free-pool membership is recomputed by the
        caller AFTER adoption re-books running jobs' hosts."""
        h = self.hosts.get(str(rec.get("host", "") or ""))
        if h is None:
            return
        h.state = str(rec.get("state", HEALTHY) or HEALTHY)
        h.score = float(rec.get("score", 0.0) or 0.0)
        h.manual = bool(rec.get("manual", False))
        h.cooldown_s = float(rec.get("cooldown_s", 0.0) or 0.0)
        h.evidence = [dict(e) for e in (rec.get("evidence") or [])
                      if isinstance(e, dict)]
        h.updated_mono = now
        if h.cordoned:
            h.cordoned_mono = now

    def resync_free(self) -> Dict[int, int]:
        """After recovery folds records + adoptions, drop cordoned
        hosts out of the free lists. Returns the per-slice count of
        free slots removed (the pool's cordon accounting delta)."""
        removed: Dict[int, int] = {}
        for h in self.hosts.values():
            if h.cordoned and h.host in self._free[h.slice_index]:
                self._free[h.slice_index].remove(h.host)
                removed[h.slice_index] = \
                    removed.get(h.slice_index, 0) + 1
        return removed


# ---------------------------------------------------------------------------
# preflight probe
# ---------------------------------------------------------------------------
def preflight_probe(host: str, scratch_dir: str,
                    attach_device: bool = False) -> Optional[str]:
    """Cheap per-host go/no-go before a grant lands: an ephemeral port
    bind (the rendezvous contract), a durable scratch write (the
    journal/checkpoint contract), and — only when asked AND a device
    node exists — a device attach stat. Returns None when the host
    passes, else a one-line failure reason. The ``health.probe`` fault
    site (pinned per host via ``task:<host>``) rehearses the failure."""
    if faults.fire("health.probe", task_id=host):
        return "injected probe failure (health.probe)"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("127.0.0.1", 0))
        finally:
            s.close()
    except OSError as e:
        return f"port bind failed: {e}"
    try:
        from tony_tpu.utils.durable import atomic_write

        os.makedirs(scratch_dir, exist_ok=True)
        path = os.path.join(scratch_dir, f"probe-{host}.tmp")
        atomic_write(path, b'{"probe": "ok"}\n')
        os.unlink(path)
    except OSError as e:
        return f"durable scratch write failed: {e}"
    if attach_device:
        # Gated: only meaningful where an accelerator node is visible;
        # absence is NOT a failure (CPU coordinators probe too).
        for dev in ("/dev/accel0", "/dev/vfio"):
            if os.path.exists(dev) and not os.access(dev, os.R_OK):
                return f"device node {dev} exists but is unreadable"
    return None


# ---------------------------------------------------------------------------
# cordon file (fleet -> warm pool handshake)
# ---------------------------------------------------------------------------
def write_cordon_file(path: str, cordons: Dict[str, str]) -> None:
    """Atomically publish the cordon set (host -> state) where the
    warm-pool daemon can see it: a pool worker whose host is listed
    here must never be leased again. Takes a plain dict (snapshotted
    under the daemon lock) so the write itself runs lock-free."""
    from tony_tpu.utils.durable import atomic_write

    atomic_write(path, (json.dumps(
        {"schema": 1, "hosts": dict(cordons)},
        sort_keys=True) + "\n").encode())


def read_cordoned(path: str) -> Dict[str, str]:
    """Tolerant read of a cordon file: absent/torn -> empty (an absent
    fleet means nothing is cordoned)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    hosts = doc.get("hosts") if isinstance(doc, dict) else None
    if not isinstance(hosts, dict):
        return {}
    return {str(k): str(v) for k, v in hosts.items()}
