"""Fleet goodput ledger: where every tenant's chip-seconds actually go.

TonY's history portal explained one job at a time; the multi-job
questions — "which tenant is wasting chips?", "how much of the pool's
life is queue wait vs. training?" — had no in-repo answer (SURVEY §1
L3-L4). This module decomposes every fleet job's wall-clock life into
CONSECUTIVE phases with the PR 9 sum-to-wall discipline (the phases
partition the wall exactly, clamped boundaries, missing anchors fold
forward — never lost, never double-booked), sourced from three
artifacts the system already writes:

- the **fleet journal** (``fleet/journal.py``): submit / grant /
  preempt / restore / terminal timestamps and the piecewise host count;
- the job's **span tree** (``tracing.py`` ``trace.spans.jsonl``):
  client.submit start, executor.first_step end, warm-pool adoption
  markers;
- the job's **perf.json** (PR 9) and **event stream**: ckpt_stall
  seconds and GANG_RESIZED drain windows.

Wall phases (seconds, sum == wall within rounding)::

    queued           submit → grant (nothing held yet)
    provision        grant → client.submit span start (client boot)
    cold_start /     client.submit start → first executor.first_step
      warm_start     end (exactly one of the two, picked by the
                     warm-pool adoption markers in the span tree)
    retry_recompute  startup end → the LAST retry-epoch reset: work the
                     failure threw away plus the relaunch
    ckpt_stall       synchronous checkpoint stalls (perf.json)
    preempted        elastic drain windows a fleet preemption caused
                     (GANG_RESIZED completed with to < from)
    resize_drain     the other drain windows (grow-backs, host loss)
    migration        live-migration windows (GANG_MIGRATED completed):
                     drain→move→reshard wall — its own phase, never
                     booked as train
    train            the remainder — steps actually advancing

Chip-seconds: each post-grant phase is weighted by the average host
count over the granted life (the host timeline from grant / preempt /
restore records), ``held_chip_s`` is the exact integral, and
``goodput_fraction = train chip-seconds / held chip-seconds`` — the
fleet-wide and per-tenant headline exported as
``tony_fleet_goodput_fraction`` / ``tony_fleet_phase_seconds``.

Stdlib-only and side-effect-free: the daemon folds it under the
``fleet.ledger`` fault site (a fold failure degrades the fleet to
counters-only, never blocks a tick), `tony-tpu check` re-folds it
offline to enforce sum-to-wall on every drill artifact, and
``bench.py --suite fleet`` records the rollup as the BENCH_FLEET
headline.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tony_tpu import constants
from tony_tpu.fleet.journal import TERMINAL_STATES, JobFold

log = logging.getLogger(__name__)

#: every phase the ledger can book, in timeline order — the golden
#: anchor for tests and the exposition's label set.
PHASES = ("queued", "provision", "cold_start", "warm_start",
          "retry_recompute", "ckpt_stall", "preempted", "resize_drain",
          "migration", "train")

#: sum-to-wall tolerance the fleet-ledger invariant enforces (matches
#: the perf.json phase-sum discipline: 1% relative + rounding epsilon).
SUM_REL_TOL = 0.01
SUM_ABS_TOL = 0.05


def _load_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _span_anchors(job_dir: str) -> Dict[str, Any]:
    """The ledger's span-tree anchors: client.submit start (us),
    first executor.first_step end (us), gang.rendezvous end (us), and
    whether any task was adopted from the warm pool."""
    from tony_tpu import tracing

    out: Dict[str, Any] = {"submit_us": 0, "first_step_us": 0,
                           "rendezvous_us": 0, "warm": False,
                           "trace_id": ""}
    path = os.path.join(job_dir, constants.TRACE_FILE)
    if not os.path.exists(path):
        return out
    records = tracing.load_records(path)
    opens: Dict[str, str] = {}        # span id → name (E carries none)
    for rec in records:
        out["trace_id"] = out["trace_id"] or str(rec.get("trace", "")
                                                 or "")
        ev = rec.get("ev")
        name = str(rec.get("name", "") or "")
        if ev == "B":
            opens[str(rec.get("span", "") or "")] = name
        elif ev == "E" and not name:
            name = opens.get(str(rec.get("span", "") or ""), "")
        ts = int(rec.get("ts_us", 0) or 0)
        end = ts + int(rec.get("dur_us", 0) or 0)
        if name == "client.submit" and ev in ("B", "X") \
                and not out["submit_us"]:
            out["submit_us"] = ts
        elif name == "executor.first_step" and ev == "X":
            if not out["first_step_us"] or end < out["first_step_us"]:
                out["first_step_us"] = end
        elif name == "gang.rendezvous" and ev in ("E", "X"):
            out["rendezvous_us"] = max(
                out["rendezvous_us"],
                end if ev == "X" else ts)
        if name == "pool.lease" or (
                isinstance(rec.get("args"), dict)
                and rec["args"].get("adopted")):
            out["warm"] = True
    return out


def _event_windows(job_dir: str) -> Tuple[float, float, float]:
    """(preempted_s, resize_drain_s, migration_s) from the job's
    completed gang events: GANG_RESIZED shrink drains (to < from) book
    as preempted — the fleet reclaims via elastic shrink, never a
    kill — the other GANG_RESIZED windows (grow-backs, host-loss
    absorbs that grew nothing) book as resize_drain, and GANG_MIGRATED
    windows (drain→move→reshard wall) book as migration."""
    from tony_tpu.events import events as events_mod

    path = None
    try:
        for name in sorted(os.listdir(job_dir)):
            if name.endswith(constants.EVENTS_SUFFIX) \
                    or name.endswith(constants.INPROGRESS_SUFFIX):
                path = os.path.join(job_dir, name)
                break
    except OSError:
        return 0.0, 0.0, 0.0
    if path is None:
        return 0.0, 0.0, 0.0
    preempted = drain = migration = 0.0
    try:
        evs = events_mod.read_events(path)
    except OSError:
        return 0.0, 0.0, 0.0
    for ev in evs:
        if ev.payload.get("phase") != "completed":
            continue
        dur = float(ev.payload.get("duration_s", 0.0) or 0.0)
        if ev.type.value == "GANG_MIGRATED":
            migration += dur
        elif ev.type.value == "GANG_RESIZED":
            if int(ev.payload.get("to", 0) or 0) \
                    < int(ev.payload.get("from", 0) or 0):
                preempted += dur
            else:
                drain += dur
    return preempted, drain, migration


def _last_retry_reset_ms(job_dir: str) -> int:
    """ts of the LAST retry-epoch reset (session > 0) in the job's
    session journal, 0 when the job never retried."""
    path = os.path.join(job_dir, constants.JOURNAL_FILE)
    last = 0
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0
    for line in data.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("t") == "epoch" \
                and int(rec.get("session", 0) or 0) > 0:
            last = max(last, int(rec.get("ts", 0) or 0))
    return last


def _ckpt_stall_s(job_dir: str) -> float:
    doc = _load_json(os.path.join(job_dir, constants.PERF_FILE))
    if not doc:
        return 0.0
    phases = doc.get("phases_s")
    if not isinstance(phases, dict):
        return 0.0
    try:
        return max(0.0, float(phases.get("ckpt_stall", 0.0) or 0.0))
    except (TypeError, ValueError):
        return 0.0


def _host_integral(events: List[Tuple[int, int]],
                   end_ms: int) -> Tuple[float, float]:
    """(held_chip_s, avg_hosts) — the exact integral of the piecewise
    host count from the grant to ``end_ms``."""
    if not events or end_ms <= events[0][0]:
        return 0.0, 0.0
    total = 0.0
    span = (end_ms - events[0][0]) / 1000.0
    for i, (ts, hosts) in enumerate(events):
        nxt = events[i + 1][0] if i + 1 < len(events) else end_ms
        nxt = min(max(nxt, ts), end_ms)
        total += max(0, nxt - ts) / 1000.0 * max(0, hosts)
    return total, (total / span if span > 0 else 0.0)


def compute_job_ledger(fold: JobFold, job_dir: Optional[str] = None,
                       now_ms: Optional[int] = None) -> Dict[str, Any]:
    """One job's goodput ledger. ``job_dir`` is the job's HISTORY dir
    (span log / perf.json / events / session journal live there);
    None degrades to journal-only accounting (queued + train). Live
    jobs need ``now_ms`` as the provisional end anchor and are marked
    ``provisional``."""
    terminal = fold.state in TERMINAL_STATES
    end_ms = fold.finished_ms if terminal and fold.finished_ms \
        else int(now_ms or 0)
    start_ms = fold.submitted_ms
    phases: Dict[str, float] = {p: 0.0 for p in PHASES}
    doc: Dict[str, Any] = {
        "job": fold.job_id, "tenant": fold.tenant, "state": fold.state,
        "provisional": not terminal, "start_kind": "",
        "phases_s": phases, "wall_s": 0.0, "chip_seconds": {},
        "held_chip_s": 0.0, "lost_preempted_chip_s": 0.0,
        "goodput_fraction": None,
    }
    if not start_ms or end_ms <= start_ms:
        return doc
    wall_s = (end_ms - start_ms) / 1000.0
    doc["wall_s"] = round(wall_s, 4)

    anchors = {"submit_us": 0, "first_step_us": 0, "rendezvous_us": 0,
               "warm": False, "trace_id": ""}
    preempted_s = drain_s = migration_s = ckpt_s = 0.0
    last_reset_ms = 0
    if job_dir and os.path.isdir(job_dir):
        anchors = _span_anchors(job_dir)
        preempted_s, drain_s, migration_s = _event_windows(job_dir)
        ckpt_s = _ckpt_stall_s(job_dir)
        last_reset_ms = _last_retry_reset_ms(job_dir)
    doc["trace_id"] = anchors["trace_id"]

    def clamp(ms: float) -> float:
        return min(max(ms, float(start_ms)), float(end_ms))

    # Consecutive boundaries: each missing anchor folds its time
    # forward, so the partition stays exact (PR 6 cold-start shape).
    prev = float(start_ms)
    b_grant = clamp(fold.granted_ms) if fold.granted_ms else prev
    phases["queued"] = (b_grant - prev) / 1000.0
    prev = b_grant
    if not fold.granted_ms:
        # Never granted: the whole life is queue wait.
        phases["queued"] = wall_s
        _finish(doc, fold, end_ms)
        return doc
    b_client = clamp(anchors["submit_us"] / 1000.0) \
        if anchors["submit_us"] else prev
    b_client = max(b_client, prev)
    phases["provision"] = (b_client - prev) / 1000.0
    prev = b_client
    startup_us = anchors["first_step_us"] or anchors["rendezvous_us"]
    b_start = max(clamp(startup_us / 1000.0), prev) if startup_us \
        else prev
    start_kind = "warm" if anchors["warm"] else "cold"
    doc["start_kind"] = start_kind
    phases[f"{start_kind}_start"] = (b_start - prev) / 1000.0
    prev = b_start

    run_s = (end_ms - prev) / 1000.0
    retry_s = 0.0
    if last_reset_ms:
        retry_s = min(max(0.0, (last_reset_ms - prev) / 1000.0), run_s)
    phases["retry_recompute"] = retry_s
    post_s = run_s - retry_s
    stalls = {"ckpt_stall": ckpt_s, "preempted": preempted_s,
              "resize_drain": drain_s, "migration": migration_s}
    stall_total = sum(stalls.values())
    if stall_total > post_s > 0:
        # Over-attribution (overlapping windows, artifact rounding):
        # scale the stalls into the window rather than going negative.
        scale = post_s / stall_total
        stalls = {k: v * scale for k, v in stalls.items()}
        stall_total = post_s
    elif post_s <= 0:
        stalls = {k: 0.0 for k in stalls}
        stall_total = 0.0
    phases.update(stalls)
    phases["train"] = max(0.0, post_s - stall_total)
    for k in phases:
        phases[k] = round(phases[k], 4)
    _finish(doc, fold, end_ms)
    return doc


def _finish(doc: Dict[str, Any], fold: JobFold, end_ms: int) -> None:
    """Chip-second weighting + goodput over the final phase map."""
    phases = doc["phases_s"]
    held, avg_hosts = _host_integral(fold.host_events, end_ms)
    doc["held_chip_s"] = round(held, 4)
    if fold.host_events:
        hosts0 = fold.host_events[0][1]
        full, _ = _host_integral([(fold.host_events[0][0], hosts0)],
                                 end_ms)
        doc["lost_preempted_chip_s"] = round(max(0.0, full - held), 4)
    chip = {p: round(s * (avg_hosts if p != "queued" else 0.0), 4)
            for p, s in phases.items()}
    doc["chip_seconds"] = chip
    doc["goodput_fraction"] = round(chip["train"] / held, 4) \
        if held > 0 else None


def sum_to_wall_error(doc: Dict[str, Any]) -> float:
    """Absolute |sum(phases) - wall| beyond tolerance; 0.0 when the
    ledger holds its own invariant (what `tony-tpu check` enforces)."""
    wall = float(doc.get("wall_s", 0.0) or 0.0)
    total = sum(float(v) for v in (doc.get("phases_s") or {}).values())
    tol = max(SUM_ABS_TOL, SUM_REL_TOL * wall)
    err = abs(total - wall)
    return err if err > tol else 0.0


def rollup(ledgers: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-tenant and fleet-wide aggregation: chip-seconds per phase,
    goodput fraction, warm-start fraction, job counts."""
    tenants: Dict[str, Dict[str, Any]] = {}
    fleet = _empty_bucket()
    for led in ledgers:
        bucket = tenants.setdefault(str(led.get("tenant", "") or "?"),
                                    _empty_bucket())
        for b in (bucket, fleet):
            b["jobs"] += 1
            b["held_chip_s"] += float(led.get("held_chip_s", 0.0) or 0.0)
            b["lost_preempted_chip_s"] += float(
                led.get("lost_preempted_chip_s", 0.0) or 0.0)
            for p, v in (led.get("chip_seconds") or {}).items():
                b["phase_chip_s"][p] = b["phase_chip_s"].get(p, 0.0) \
                    + float(v or 0.0)
            for p, v in (led.get("phases_s") or {}).items():
                b["phase_s"][p] = b["phase_s"].get(p, 0.0) \
                    + float(v or 0.0)
            kind = led.get("start_kind")
            if kind == "warm":
                b["warm_starts"] += 1
            elif kind == "cold":
                b["cold_starts"] += 1
    for b in list(tenants.values()) + [fleet]:
        held = b["held_chip_s"]
        b["goodput_fraction"] = round(
            b["phase_chip_s"].get("train", 0.0) / held, 4) \
            if held > 0 else None
        starts = b["warm_starts"] + b["cold_starts"]
        b["warm_start_fraction"] = round(b["warm_starts"] / starts, 4) \
            if starts else None
        b["held_chip_s"] = round(held, 2)
        b["lost_preempted_chip_s"] = round(b["lost_preempted_chip_s"], 2)
        b["phase_chip_s"] = {p: round(v, 2)
                             for p, v in sorted(b["phase_chip_s"].items())}
        b["phase_s"] = {p: round(v, 2)
                        for p, v in sorted(b["phase_s"].items())}
    return {"tenants": {t: tenants[t] for t in sorted(tenants)},
            "fleet": fleet}


def _empty_bucket() -> Dict[str, Any]:
    return {"jobs": 0, "held_chip_s": 0.0, "lost_preempted_chip_s": 0.0,
            "phase_chip_s": {}, "phase_s": {}, "warm_starts": 0,
            "cold_starts": 0}


def job_history_dirs(fleet_dir: str) -> Dict[str, str]:
    """app_id → job history dir for every job the fleet ran (the fleet
    injects its own history root into every grant)."""
    from tony_tpu.events import history

    root = os.path.join(fleet_dir, "history")
    if not os.path.isdir(root):
        return {}
    return history.list_job_dirs(root)


def fold_fleet_dir(fleet_dir: str,
                   now_ms: Optional[int] = None,
                   timeline=None) -> Dict[str, Any]:
    """Offline entry: fold the fleet journal (via the shared
    fleet/timeline.py replay — pass ``timeline`` to reuse a fold the
    caller already paid for), resolve each job's history dir, compute
    every ledger and the rollup — what `tony-tpu check`,
    `fleet diagnose` (offline) and the bench suite consume."""
    from tony_tpu.fleet import timeline as ftimeline

    st = (timeline or ftimeline.load(fleet_dir)).state
    dirs = job_history_dirs(fleet_dir)
    jobs: Dict[str, Dict[str, Any]] = {}
    for job_id, fold in sorted(st.jobs.items()):
        jobs[job_id] = compute_job_ledger(
            fold, job_dir=dirs.get(fold.app_id), now_ms=now_ms)
    out = rollup(jobs.values())
    out["jobs"] = jobs
    return out
