"""One shared fold of the fleet journal — the single replay every
offline consumer rides.

Before this module, four call sites parsed the fleet journal
independently: ``fleet explain``'s offline fallback
(diagnose.offline_explain), ``fleet diagnose --from-dir``
(diagnose.bundle_from_dir), the goodput ledger re-fold
(ledger.fold_fleet_dir) and the what-if simulator
(fleet/simulator.py). Each re-derived the same things — the
FleetReplayState job fold, the raw record prefix, preemption counts,
grant waits, the last-wins alert fold — with four chances to drift.
``load()`` folds once and hands every consumer the same
:class:`FleetTimeline`.

The module also owns the hold-interval algebra (``hold_intervals`` /
``holds_summary``): a REC_FLEET_DECISION record opens a hold that
closes at the next reason transition, the grant, or the terminal
anchor. ``fleet explain`` surfaces the summary (which jobs were
blocking, for how long, with how many free hosts) and the what-if
differ uses the same math to attribute quota-hold and
fragmentation-hold seconds per tenant — one algebra, two consumers,
no skew between what the explainer says and what the simulator
accounts.

Stdlib-only, like everything else in tony_tpu/fleet/.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple

from tony_tpu import constants
from tony_tpu.fleet import journal as fjournal


def journal_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, constants.FLEET_JOURNAL_FILE)


@dataclasses.dataclass
class FleetTimeline:
    """The shared offline fold: the replayed state plus the raw record
    prefix and the derived counters every consumer used to re-compute."""

    path: str
    #: the canonical per-job fold (journal.replay) — states, anchors,
    #: host events, decision history, quotas, pool shape
    state: fjournal.FleetReplayState
    #: the decodable record prefix, in journal order (torn tail cut)
    records: List[Dict[str, Any]]
    torn_tail: bool
    # -- derived (previously re-computed per consumer) -------------------
    grants_total: int
    preemptions_total: int
    migrations_total: int
    restores_total: int
    preempts_per_job: Dict[str, int]
    #: grant waits in seconds for every granted job, sorted ascending
    grant_waits: List[float]
    #: rule -> last raw REC_FLEET_ALERT record (severity/value/summary)
    alert_last: Dict[str, Dict[str, Any]]

    @property
    def terminal(self) -> bool:
        """True when every journaled job reached a terminal state — the
        precondition for a trustworthy parity replay (a live queue's
        next decision is not in the journal yet)."""
        return all(f.state in fjournal.TERMINAL_STATES
                   for f in self.state.jobs.values())


def load(fleet_dir: Optional[str] = None, *,
         path: Optional[str] = None) -> FleetTimeline:
    """Fold a fleet journal once. Raises
    :class:`journal.FleetJournalError` like ``journal.replay`` when the
    file is absent/unreadable."""
    if path is None:
        if fleet_dir is None:
            raise ValueError("load() needs fleet_dir or path")
        path = journal_path(fleet_dir)
    state = fjournal.replay(path)
    records, torn = _raw_records(path)
    grants = preempts = migrates = restores = 0
    preempts_per_job: Dict[str, int] = {}
    alert_last: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        t = rec.get("t")
        if t == fjournal.REC_FLEET_GRANT:
            grants += 1
        elif t == fjournal.REC_FLEET_PREEMPT:
            job = str(rec.get("job", "") or "")
            preempts += 1
            preempts_per_job[job] = preempts_per_job.get(job, 0) + 1
        elif t == fjournal.REC_FLEET_MIGRATE:
            migrates += 1
        elif t == fjournal.REC_FLEET_STATE \
                and rec.get("state") == fjournal.STATE_RESTORED:
            restores += 1
        elif t == fjournal.REC_FLEET_ALERT:
            alert_last[str(rec.get("rule", "") or "")] = rec
    waits = sorted(
        max(0.0, (f.granted_ms - f.submitted_ms) / 1000.0)
        for f in state.jobs.values() if f.granted_ms)
    return FleetTimeline(
        path=path, state=state, records=records, torn_tail=torn,
        grants_total=grants, preemptions_total=preempts,
        migrations_total=migrates, restores_total=restores,
        preempts_per_job=preempts_per_job, grant_waits=waits,
        alert_last=alert_last)


def _raw_records(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    from tony_tpu.devtools.invariants import _iter_journal_records

    recs, torn = _iter_journal_records(path)
    return [r for _, r in recs], torn


# ---------------------------------------------------------------------------
# hold algebra: decision records -> attributed hold intervals
# ---------------------------------------------------------------------------
#: a capacity hold whose free count covers the request is a
#: fragmentation hold — the hosts EXIST but do not pack (the same
#: free >= hosts test fleet-diagnose's FRAGMENTATION rule keys off)
FRAGMENTATION = "fragmentation"


def classify_hold(action: str, free: int, hosts: int) -> str:
    """Hold attribution bucket for one decision: quota / capacity /
    fragmentation / held / preempt-wait."""
    from tony_tpu.fleet import policy as fpolicy

    if action == fpolicy.CAPACITY_DENIED and hosts and free >= hosts:
        return FRAGMENTATION
    return action


def hold_intervals(decisions: List[Dict[str, Any]], *,
                   granted_ms: int = 0, finished_ms: int = 0,
                   now_ms: int = 0,
                   hosts: int = 0) -> List[Dict[str, Any]]:
    """Each hold-reason transition opens an interval that closes at the
    NEXT transition, the grant, the terminal state, or ``now_ms`` (for
    a still-queued job). Entries whose action is not a hold (the live
    ring's closing ``granted`` entry) close the previous interval and
    open nothing."""
    from tony_tpu.fleet import policy as fpolicy

    end_anchor = granted_ms or finished_ms or now_ms
    out: List[Dict[str, Any]] = []
    for i, d in enumerate(decisions):
        action = str(d.get("action", "") or "")
        if action not in fpolicy.HOLD_ACTIONS:
            continue
        start = int(d.get("ts_ms", 0) or 0)
        if i + 1 < len(decisions):
            end = int(decisions[i + 1].get("ts_ms", 0) or 0)
        else:
            end = end_anchor
        end = max(end, start)
        out.append({
            "action": action,
            "kind": classify_hold(action, int(d.get("free", 0) or 0),
                                  hosts),
            "reason": str(d.get("reason", "") or ""),
            "blocking": [str(b) for b in (d.get("blocking") or [])],
            "free": int(d.get("free", 0) or 0),
            "start_ms": start, "end_ms": end,
            "seconds": round((end - start) / 1000.0, 3)})
    return out


def holds_summary(intervals: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-kind rollup of hold intervals: total seconds, the union of
    blocking jobs/tenants, and the last observed free count — the
    `fleet explain --json` "holds" section and the differ's
    which-hold-did-the-counterfactual-remove citation."""
    out: Dict[str, Any] = {}
    for iv in intervals:
        bucket = out.setdefault(iv["kind"], {
            "seconds": 0.0, "episodes": 0, "blocking": [], "free": 0})
        bucket["seconds"] = round(bucket["seconds"] + iv["seconds"], 3)
        bucket["episodes"] += 1
        for b in iv["blocking"]:
            if b not in bucket["blocking"]:
                bucket["blocking"].append(b)
        bucket["free"] = iv["free"]
    for bucket in out.values():
        bucket["blocking"] = sorted(bucket["blocking"])
    return out
