"""Write-ahead fleet journal: the scheduler's crash-survivable memory.

Same discipline as the per-job session journal (``coordinator/
journal.py`` — whose module docstring is the contract's full statement):
every scheduler state transition — submission, grant, preemption, job
state change, daemon generation bump — is appended as one JSON line and
fsync'd BEFORE the transition is acted on, so a SIGKILLed daemon
restarted with ``tony-tpu fleet start --recover`` replays into the SAME
queue state with zero duplicated or lost grants. Torn/undecodable tails
replay as the prefix (write-ahead means the lost record was never acted
on). Record types are ``REC_FLEET_*`` constants (never string literals)
so the tonylint ``journal-parity`` rule checks both halves — every type
appended somewhere, every type replayed — exactly as it does for the
session journal.

The ``fgen`` record additionally carries the pool shape (slices ×
hosts-per-slice): ``tony-tpu check`` uses it to assert that granted
hosts never exceed the pool at any point in the journal's history
(devtools/invariants.py ``fleet-capacity``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from tony_tpu.utils.durable import AppendLog, DurableWriteError

log = logging.getLogger(__name__)

#: record types (the "t" field) — globally unique against the session
#: journal's REC_* values so the parity rule can match writers by name.
REC_FLEET_GEN = "fgen"          # daemon (re)start: generation + pool shape
REC_FLEET_SUBMIT = "fsubmit"    # a submission entered the queue
REC_FLEET_GRANT = "fgrant"      # capacity granted (write-ahead of spawn)
REC_FLEET_PREEMPT = "fpreempt"  # victim shrunk to reclaim hosts
REC_FLEET_STATE = "fstate"      # job state transition (spawned/running/...)
# Scheduler decision explainer (tony-tpu fleet explain): a queued job's
# not-placed reason TRANSITIONED — quota / capacity / fragmentation /
# priority-held / preempt-wait, with the blocking jobs/tenants named.
# Written per transition (never per tick — the dedup is part of the
# contract, checked by the fleet-decision invariant) so the journal
# holds the job's full causal hold timeline without per-tick bloat.
REC_FLEET_DECISION = "fdecision"
# A running job live-migrated between slices (spot-reclaim survival or
# FRAGMENTATION repacking): write-ahead of the victim coordinator's
# migrate RPC; the post-move placement is journaled so replay
# re-accounts the pool exactly (host COUNT is unchanged — migration
# moves capacity, it never shrinks it).
REC_FLEET_MIGRATE = "fmigrate"
# A host's health state TRANSITIONED (fleet/health.py: healthy /
# suspect / quarantined / probation), write-ahead of the cordon or
# restore taking effect. Each record is self-contained — it carries
# the host's attributed-failure evidence — so `fleet start --recover`
# resumes the identical cordon set (the fold persists across fgen
# records: cordons outlive daemon lives) and `tony-tpu check` audits
# that no quarantine lacks evidence.
REC_FLEET_HEALTH = "fhealth"
# A fleet-scope alert rule TRANSITIONED (tony_tpu/alerts/: pending /
# firing / resolved), write-ahead of the FLEET event and gauge update.
# The fold is last-wins per rule and persists across fgen records —
# like cordons, a firing alert outlives daemon lives until a journaled
# resolve closes it; `fleet start --recover` re-arms the identical
# firing set via AlertEngine.seed().
REC_FLEET_ALERT = "falert"

#: in-fold cap on per-job decision history (the journal keeps all of it
#: on disk; the replayed fold only needs enough to seed the explain
#: ring and the dedup fence).
DECISION_FOLD_CAP = 64

#: job states the fstate record carries (QUEUED/GRANTED are implied by
#: fsubmit/fgrant; these are the post-grant lifecycle).
STATE_SPAWNED = "SPAWNED"       # client subprocess forked (pid recorded)
STATE_RUNNING = "RUNNING"       # app dir discovered (app_id recorded)
STATE_RESTORED = "RESTORED"     # grow-back resize landed (hosts recorded)
STATE_FINISHED = "FINISHED"
STATE_FAILED = "FAILED"
STATE_CANCELLED = "CANCELLED"
TERMINAL_STATES = (STATE_FINISHED, STATE_FAILED, STATE_CANCELLED)


class FleetJournalError(RuntimeError):
    pass


@dataclasses.dataclass
class JobFold:
    """Folded per-job state."""

    job_id: str = ""
    tenant: str = ""
    priority: int = 0
    hosts_requested: int = 0
    min_hosts: int = 0
    model: str = ""
    seq: int = 0
    conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    state: str = "QUEUED"
    hosts: int = 0                 # currently granted
    placement: Dict[int, int] = dataclasses.field(default_factory=dict)
    #: concrete host identities the grant landed on (fleet/health.py
    #: names), when the grant record carried them
    host_ids: List[str] = dataclasses.field(default_factory=list)
    app_id: str = ""
    pid: int = 0
    exit_code: Optional[int] = None
    # --- goodput-ledger anchors (tony_tpu/fleet/ledger.py) -------------
    submitted_ms: int = 0          # fsubmit ts
    granted_ms: int = 0            # latest fgrant ts (re-grants supersede)
    finished_ms: int = 0           # terminal fstate ts
    #: piecewise host count over the granted life: (ts_ms, hosts) at the
    #: grant, each preempt shrink, and each grow-back restore — the
    #: chip-second integrand.
    host_events: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)
    #: replayed decision history (capped at DECISION_FOLD_CAP): dicts of
    #: {ts_ms, action, reason, blocking, free} — seeds the recovered
    #: daemon's explain ring and the offline explain fallback.
    decisions: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class FleetReplayState:
    """What a recovering daemon reconstructs from the journal."""

    generation: int = 0
    slices: int = 0
    hosts_per_slice: int = 0
    quotas: Dict[str, int] = dataclasses.field(default_factory=dict)
    seq: int = 0                   # highest submission sequence seen
    jobs: Dict[str, JobFold] = dataclasses.field(default_factory=dict)
    records: int = 0
    torn_tail: bool = False
    #: last-wins per-host health fold (host -> the latest fhealth
    #: record). NOT reset on fgen: a cordon survives daemon restarts
    #: until a journaled transition closes it.
    health: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    #: last-wins per-rule alert fold (rule -> latest journaled state:
    #: pending/firing/resolved). NOT reset on fgen, like health.
    alerts: Dict[str, str] = dataclasses.field(default_factory=dict)


class FleetJournal:
    """Append side. Appends are serialized by an I/O lock (the lock
    exists solely to keep the fsync'd record order equal to the decision
    order — submit handlers and the scheduler tick both append)."""

    def __init__(self, path: str, enabled: bool = True) -> None:
        from tony_tpu.devtools.sanitizer import io_lock

        self.path = path
        self.enabled = enabled
        #: first durable-write failure, sticky (ENOSPC/EIO). The first
        #: failing append raises; later appends no-op — the daemon must
        #: STOP scheduling against a journal that can no longer write
        #: ahead (daemon.run checks this), and the committed prefix on
        #: disk stays replayable for `fleet start --recover`.
        self.dead: Optional[DurableWriteError] = None
        self._log: Optional[AppendLog] = AppendLog(path) if enabled else None
        self._lock = io_lock()

    def append(self, record: Dict[str, Any]) -> None:
        if self._log is None:
            return
        if self.dead is not None:
            return
        record.setdefault("ts", int(time.time() * 1000))
        data = (json.dumps(record, sort_keys=True) + "\n").encode("utf-8")
        with self._lock:
            try:
                self._log.append(data)
            except DurableWriteError as e:
                self.dead = e
                log.critical(
                    "fleet journal %s is DEAD (%s): the daemon must stop "
                    "— scheduling decisions it cannot write ahead would "
                    "be lost to recovery; the committed prefix remains "
                    "replayable", self.path, e)
                raise

    # -- typed appenders --------------------------------------------------
    def generation(self, generation: int, slices: int,
                   hosts_per_slice: int,
                   quotas: Optional[Dict[str, int]] = None) -> None:
        self.append({"t": REC_FLEET_GEN, "generation": int(generation),
                     "slices": int(slices),
                     "hosts_per_slice": int(hosts_per_slice),
                     "quotas": {str(t): int(q)
                                for t, q in (quotas or {}).items()}})

    def submit(self, job_id: str, tenant: str, priority: int, hosts: int,
               min_hosts: int, model: str, seq: int,
               conf: Dict[str, str]) -> None:
        self.append({"t": REC_FLEET_SUBMIT, "job": job_id,
                     "tenant": tenant, "priority": int(priority),
                     "hosts": int(hosts), "min_hosts": int(min_hosts),
                     "model": model, "seq": int(seq),
                     "conf": dict(conf)})

    def grant(self, job_id: str, hosts: int, placement: Dict[int, int],
              host_ids: Optional[List[str]] = None) -> None:
        rec: Dict[str, Any] = {
            "t": REC_FLEET_GRANT, "job": job_id, "hosts": int(hosts),
            "placement": {str(i): int(n)
                          for i, n in placement.items()}}
        if host_ids:
            # Concrete host identities (fleet/health.py names) so a
            # recovering daemon re-books the SAME slots — a cordoned
            # host must stay cordoned even while an adopted job runs
            # beside it. Optional: pre-health journals replay fine.
            rec["host_ids"] = [str(h) for h in host_ids]
        self.append(rec)

    def preempt(self, job_id: str, from_hosts: int, to_hosts: int,
                for_job: str, placement: Dict[int, int]) -> None:
        """Write-ahead of the victim's shrink: the post-shrink placement
        is journaled so replay re-accounts the pool exactly."""
        self.append({"t": REC_FLEET_PREEMPT, "job": job_id,
                     "from": int(from_hosts), "to": int(to_hosts),
                     "for": for_job,
                     "placement": {str(i): int(n)
                                   for i, n in placement.items()}})

    def migrate(self, job_id: str, source: int, target: int,
                placement: Dict[int, int], reason: str = "") -> None:
        """Write-ahead of a live migration: the job's gang moves from
        slice ``source`` to slice ``target`` with its host count intact;
        ``placement`` is the post-move slice map."""
        self.append({"t": REC_FLEET_MIGRATE, "job": job_id,
                     "source": int(source), "target": int(target),
                     "placement": {str(i): int(n)
                                   for i, n in placement.items()},
                     "reason": str(reason)})

    def health(self, record: Dict[str, Any]) -> None:
        """One host-health transition (fleet/health.py builds the
        payload: host, slice, state, score, reason, manual, cooldown_s,
        evidence). Write-ahead: appended BEFORE the cordon/restore is
        applied to the pool."""
        rec = {"t": REC_FLEET_HEALTH}
        for k in ("host", "slice", "state", "score", "reason", "manual",
                  "cooldown_s", "evidence"):
            if k in record:
                rec[k] = record[k]
        self.append(rec)

    def alert(self, rule: str, state: str, severity: str,
              value: Optional[float], labels: Dict[str, str],
              summary: str) -> None:
        """One fleet-alert state transition (tony_tpu/alerts/), appended
        BEFORE the event/gauge surfaces it. The engine's dedup fence
        guarantees consecutive records for a rule never repeat a state
        (the alert-journal invariant audits this)."""
        rec: Dict[str, Any] = {"t": REC_FLEET_ALERT, "rule": rule,
                               "state": state, "severity": severity,
                               "summary": summary}
        if value is not None:
            rec["value"] = float(value)
        if labels:
            rec["labels"] = dict(labels)
        self.append(rec)

    def decision(self, job_id: str, action: str, reason: str,
                 blocking: Optional[List[str]] = None,
                 free: int = 0) -> None:
        """One hold-reason transition for a queued job (the explainer's
        write-ahead stream). Callers dedup on reason — two consecutive
        identical records for one job violate the fleet-decision
        invariant."""
        self.append({"t": REC_FLEET_DECISION, "job": job_id,
                     "action": str(action), "reason": str(reason),
                     "blocking": [str(b) for b in (blocking or [])],
                     "free": int(free)})

    def state(self, job_id: str, state: str, app_id: str = "",
              pid: int = 0, exit_code: Optional[int] = None,
              hosts: int = 0,
              placement: Optional[Dict[int, int]] = None) -> None:
        rec: Dict[str, Any] = {"t": REC_FLEET_STATE, "job": job_id,
                               "state": state}
        if app_id:
            rec["app_id"] = app_id
        if pid:
            rec["pid"] = int(pid)
        if exit_code is not None:
            rec["exit"] = int(exit_code)
        if hosts:
            rec["hosts"] = int(hosts)
        if placement is not None:
            rec["placement"] = {str(i): int(n)
                                for i, n in placement.items()}
        self.append(rec)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None


def _placement(rec: Dict[str, Any]) -> Dict[int, int]:
    out: Dict[int, int] = {}
    for k, v in (rec.get("placement") or {}).items():
        try:
            out[int(k)] = int(v)
        except (TypeError, ValueError):
            continue
    return out


def replay(path: str) -> FleetReplayState:
    """Fold the fleet journal into a FleetReplayState (same torn-tail
    posture as the session journal's replay: decode in order, stop at
    the first bad line, the prefix is the truth)."""
    if not os.path.exists(path):
        raise FleetJournalError(
            f"no fleet journal at {path} — this directory never ran a "
            f"fleet daemon, or the wrong --dir was given")
    from tony_tpu.coordinator.journal import _iter_complete_lines

    state = FleetReplayState()
    lines, torn = _iter_complete_lines(path)
    state.torn_tail = bool(torn)
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except (ValueError, UnicodeDecodeError) as e:
            log.warning("fleet journal %s: undecodable record after %d "
                        "good ones (%s) — replaying the prefix", path,
                        state.records, e)
            state.torn_tail = True
            break
        state.records += 1
        t = rec.get("t")
        ts_ms = int(rec.get("ts", 0) or 0)
        if t == REC_FLEET_GEN:
            state.generation = max(state.generation,
                                   int(rec.get("generation", 0) or 0))
            state.slices = int(rec.get("slices", 0) or 0)
            state.hosts_per_slice = int(
                rec.get("hosts_per_slice", 0) or 0)
            for t, q in (rec.get("quotas") or {}).items():
                try:
                    state.quotas[str(t)] = int(q)
                except (TypeError, ValueError):
                    continue
        elif t == REC_FLEET_SUBMIT:
            job = str(rec.get("job", "") or "")
            seq = int(rec.get("seq", 0) or 0)
            state.seq = max(state.seq, seq)
            state.jobs[job] = JobFold(
                job_id=job, tenant=str(rec.get("tenant", "") or ""),
                priority=int(rec.get("priority", 0) or 0),
                hosts_requested=int(rec.get("hosts", 0) or 0),
                min_hosts=int(rec.get("min_hosts", 0) or 0),
                model=str(rec.get("model", "") or ""), seq=seq,
                conf={str(k): str(v)
                      for k, v in (rec.get("conf") or {}).items()},
                submitted_ms=ts_ms)
        elif t == REC_FLEET_GRANT:
            fold = state.jobs.get(str(rec.get("job", "") or ""))
            if fold is None:
                continue           # unknown job: invariants flag it
            fold.state = "GRANTED"
            fold.hosts = int(rec.get("hosts", 0) or 0)
            fold.placement = _placement(rec)
            fold.host_ids = [str(h) for h in (rec.get("host_ids") or [])]
            fold.granted_ms = ts_ms
            fold.host_events = [(ts_ms, fold.hosts)]
        elif t == REC_FLEET_PREEMPT:
            fold = state.jobs.get(str(rec.get("job", "") or ""))
            if fold is None:
                continue
            fold.hosts = int(rec.get("to", fold.hosts) or 0)
            fold.placement = _placement(rec)
            fold.host_events.append((ts_ms, fold.hosts))
        elif t == REC_FLEET_MIGRATE:
            fold = state.jobs.get(str(rec.get("job", "") or ""))
            if fold is None:
                continue
            # Host count is unchanged by a move — only the slice map.
            fold.placement = _placement(rec)
        elif t == REC_FLEET_HEALTH:
            host = str(rec.get("host", "") or "")
            if host:
                state.health[host] = rec
        elif t == REC_FLEET_ALERT:
            rule = str(rec.get("rule", "") or "")
            if rule:
                state.alerts[rule] = str(rec.get("state", "") or "")
        elif t == REC_FLEET_DECISION:
            fold = state.jobs.get(str(rec.get("job", "") or ""))
            if fold is None:
                continue           # unknown job: invariants flag it
            fold.decisions.append({
                "ts_ms": ts_ms,
                "action": str(rec.get("action", "") or ""),
                "reason": str(rec.get("reason", "") or ""),
                "blocking": [str(b)
                             for b in (rec.get("blocking") or [])],
                "free": int(rec.get("free", 0) or 0)})
            del fold.decisions[:-DECISION_FOLD_CAP]
        elif t == REC_FLEET_STATE:
            fold = state.jobs.get(str(rec.get("job", "") or ""))
            if fold is None:
                continue
            st = str(rec.get("state", "") or "")
            fold.state = st
            if rec.get("app_id"):
                fold.app_id = str(rec["app_id"])
            if rec.get("pid"):
                fold.pid = int(rec["pid"])
            if "exit" in rec:
                fold.exit_code = int(rec["exit"])
            if st in TERMINAL_STATES:
                fold.finished_ms = ts_ms
            if st == STATE_RESTORED:
                fold.hosts = int(rec.get("hosts", fold.hosts) or 0)
                if rec.get("placement") is not None:
                    fold.placement = _placement(rec)
                fold.state = STATE_RUNNING
                fold.host_events.append((ts_ms, fold.hosts))
        else:
            log.warning("fleet journal %s: unknown record type %r "
                        "skipped", path, t)
    return state


def queued_folds(state: FleetReplayState) -> List[JobFold]:
    """Still-queued jobs in original submission order (the queue a
    recovered daemon re-enqueues)."""
    return sorted((f for f in state.jobs.values() if f.state == "QUEUED"),
                  key=lambda f: f.seq)
