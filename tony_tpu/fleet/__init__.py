"""Fleet: a persistent multi-job gang scheduler over a shared slice pool.

TonY delegated everything above one job — queueing, quotas, priorities,
preemption — to YARN's ResourceManager (SURVEY §1 L4/L3); this package is
that layer rebuilt TPU-native. A persistent daemon (``tony-tpu fleet
start`` / ``python -m tony_tpu.fleet serve``) owns a pool of TPU slices
and gang-schedules many jobs against it:

- **policy engine** (``policy.py``, stdlib-only): priority queues with
  FIFO tiebreak, per-tenant host quotas that queue rather than starve
  other tenants, bin-packing of sub-slice jobs onto shared slices, and
  preempt-to-reclaim victim selection that shrinks elastic jobs toward
  their floor instead of killing them.
- **write-ahead journal** (``journal.py``): every submission, grant,
  preemption and state transition fsync'd before it is acted on — the
  same ``REC_*``/replay/torn-tail discipline as ``coordinator/
  journal.py`` — so ``fleet start --recover`` resumes the same queue
  state with zero duplicated or lost grants.
- **daemon** (``daemon.py``): the RPC plane (``fleet.submit`` /
  ``fleet.status`` / ``fleet.cancel`` / ``fleet.stop`` over rpc/wire.py,
  token-authed, generation-fenced), per-job coordinator launches against
  leased hosts (the ordinary ``tony-tpu submit`` stack, one client
  subprocess per grant), elastic-shrink preemption driving
  ``coordinator/elastic.py``'s absorb path, warm-pool and per-model
  compile-cache injection into every grant, and the ``tony_fleet_*``
  metric families + fleet event stream.

Maple (PAPERS.md) is the template for portable multi-cluster scheduling,
Arax for decoupling jobs from the accelerators they land on; the warm
executor pool (``tony_tpu/pool.py``) is the executor substrate and
LocalSim + virtual executors the drill substrate at width.

Deliberately no re-exports: ``python -m tony_tpu.fleet.policy`` is the
no-deps CI smoke, and an ``__init__`` that pre-imports the module would
shadow the runpy execution (and drag the policy import into every
``tony_tpu.fleet`` consumer that only wants the client).
"""
