"""Fleet daemon entrypoint: ``python -m tony_tpu.fleet serve``.

The operator-facing wrapper is ``tony-tpu fleet start`` (spawns this
detached and waits for the endpoint); running ``serve`` directly keeps
the daemon in the foreground — the systemd/supervisor deployment shape.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
from typing import List, Optional

from tony_tpu.fleet.daemon import FleetDaemon, FleetError
from tony_tpu.fleet.health import HealthConfig


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tony-tpu-fleet")
    sub = p.add_subparsers(dest="role", required=True)
    s = sub.add_parser("serve", help="run the fleet daemon (foreground)")
    s.add_argument("--dir", required=True, help="fleet state directory")
    s.add_argument("--slices", type=int, default=1)
    s.add_argument("--hosts-per-slice", type=int, default=8)
    s.add_argument("--quotas", default="",
                   help="per-tenant host quotas: tenant=hosts,...")
    s.add_argument("--pool-dir", default="",
                   help="warm executor pool granted jobs adopt from")
    s.add_argument("--cache-root", default="",
                   help="root of the per-model shared compile caches")
    s.add_argument("--tick-s", type=float, default=0.5)
    s.add_argument("--decision-ring", type=int, default=64,
                   help="per-job scheduler-decision ring bound "
                        "(tony.fleet.decision-ring)")
    s.add_argument("--ledger-interval-s", type=float, default=5.0,
                   help="goodput-ledger refresh cadence for running "
                        "jobs (tony.fleet.ledger-interval-s)")
    s.add_argument("--recover", action="store_true",
                   help="replay the fleet journal and resume the queue "
                        "(required when the dir holds non-terminal jobs)")
    s.add_argument("--health-enabled", type=int, default=1,
                   help="host-health subsystem switch "
                        "(tony.health.enabled)")
    s.add_argument("--health-half-life-s", type=float, default=300.0)
    s.add_argument("--health-suspect-threshold", type=float, default=1.0)
    s.add_argument("--health-quarantine-threshold", type=float,
                   default=3.0)
    s.add_argument("--health-quarantine-s", type=float, default=120.0)
    s.add_argument("--health-probation-priority", type=int, default=0)
    s.add_argument("--health-blast-n", type=int, default=2)
    s.add_argument("--health-blast-window-s", type=float, default=120.0)
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    health_conf = HealthConfig(
        enabled=bool(args.health_enabled),
        half_life_s=args.health_half_life_s,
        suspect_threshold=args.health_suspect_threshold,
        quarantine_threshold=args.health_quarantine_threshold,
        quarantine_s=args.health_quarantine_s,
        probation_priority=args.health_probation_priority,
        blast_n=args.health_blast_n,
        blast_window_s=args.health_blast_window_s)
    try:
        daemon = FleetDaemon(args.dir, slices=args.slices,
                             hosts_per_slice=args.hosts_per_slice,
                             quotas=args.quotas, pool_dir=args.pool_dir,
                             cache_root=args.cache_root,
                             tick_s=args.tick_s, recover=args.recover,
                             decision_ring=args.decision_ring,
                             ledger_interval_s=args.ledger_interval_s,
                             health_conf=health_conf)
    except (FleetError, ValueError) as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 1
    signal.signal(signal.SIGTERM, lambda *_: daemon.request_stop())
    signal.signal(signal.SIGINT, lambda *_: daemon.request_stop())
    return daemon.run()


if __name__ == "__main__":
    sys.exit(main())
